"""3-D heat diffusion pinned to TPU devices — no visualization.

Port of `/root/reference/examples/diffusion3D_multigpu_CuArrays_novis.jl`: the
reference's GPU variant differs from the CPU one only in allocating `CuArray`s
and binding each rank to a GPU (`select_device`); here the same is
``device_type="tpu"`` — fields live in TPU HBM and each host process binds its
local chips automatically.

Run:
    python examples/diffusion3d_tpu_novis.py [--nx 256] [--nt 1000]
"""

import argparse
import importlib.util
import os

_here = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "diffusion3d_multidevice_novis", os.path.join(_here, "diffusion3d_multidevice_novis.py")
)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=256)
    p.add_argument("--nt", type=int, default=1000)
    a = p.parse_args()
    import jax

    _mod.diffusion3d(nx=a.nx, nt=a.nt, device_type="tpu", dtype=jax.numpy.float32)
