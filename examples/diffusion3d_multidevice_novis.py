"""3-D heat diffusion on the implicit global grid — no visualization.

Port of `/root/reference/examples/diffusion3D_multicpu_novis.jl` (and its GPU
twin `diffusion3D_multigpu_CuArrays_novis.jl` — on TPU the device distinction
is just `device_type`).  This is the three-function promise in action: a
single-device stencil solver plus `init_global_grid` / `update_halo` /
`finalize_global_grid` runs on every device of the slice.

Run:
    python examples/diffusion3d_multidevice_novis.py [--nx 128] [--nt 1000]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

import implicitglobalgrid_tpu as igg


def diffusion3d(nx=128, ny=None, nz=None, nt=1000, device_type="auto", dtype=None):
    # Physics (reference lines :14-16)
    lam = 1.0          # thermal conductivity
    cp_min = 1.0       # minimal heat capacity
    lx, ly, lz = 10.0, 10.0, 10.0

    # Numerics
    ny = nx if ny is None else ny
    nz = nx if nz is None else nz
    me, dims, nprocs, coords, mesh = igg.init_global_grid(
        nx, ny, nz, device_type=device_type
    )
    dx = lx / (igg.nx_g() - 1)  # global grid spacing (reference :21-23)
    dy = ly / (igg.ny_g() - 1)
    dz = lz / (igg.nz_g() - 1)
    if dtype is None:
        dtype = jax.dtypes.canonicalize_dtype(float)

    # Initial conditions: heat capacity and temperature with two Gaussian
    # anomalies each, from *global* coordinates (reference :33-37).
    T = igg.zeros((nx, ny, nz), dtype)
    X, Y, Z = igg.coord_fields(T, (dx, dy, dz), dtype=dtype)

    @igg.stencil
    def init_ic(X, Y, Z):
        Cp = cp_min + (
            5 * jnp.exp(-((X - lx / 1.5) ** 2) - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
            + 5 * jnp.exp(-((X - lx / 3.0) ** 2) - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
        )
        T = 100 * jnp.exp(
            -(((X - lx / 2) / 2) ** 2) - ((Y - ly / 2) / 2) ** 2 - ((Z - lz / 3.0) / 2) ** 2
        ) + 50 * jnp.exp(
            -(((X - lx / 2) / 2) ** 2) - ((Y - ly / 2) / 2) ** 2 - ((Z - lz / 1.5) / 2) ** 2
        )
        return Cp.astype(dtype), T.astype(dtype)

    Cp, T = init_ic(X, Y, Z)

    # Time step for 3-D heat diffusion (reference :39)
    dt = min(dx * dx, dy * dy, dz * dz) * cp_min / lam / 8.1

    def inn(A):
        return A[1:-1, 1:-1, 1:-1]

    @igg.stencil(donate_argnums=(0,))
    def step(T, Cp):
        # Fourier's law + conservation of energy, fused (reference :41-45);
        # with scalar lam the flux divergence is the Laplacian.
        lap = (
            (T[2:, 1:-1, 1:-1] - 2 * inn(T) + T[:-2, 1:-1, 1:-1]) / (dx * dx)
            + (T[1:-1, 2:, 1:-1] - 2 * inn(T) + T[1:-1, :-2, 1:-1]) / (dy * dy)
            + (T[1:-1, 1:-1, 2:] - 2 * inn(T) + T[1:-1, 1:-1, :-2]) / (dz * dz)
        )
        T = T + jnp.pad(dt * lam / inn(Cp) * lap, 1)
        T = igg.update_halo(T)  # reference :46
        return T, Cp

    sync = mesh.devices.flat[0].platform == "cpu"  # virtual-mesh dispatch guard
    igg.tic()
    for it in range(nt):
        T, Cp = step(T, Cp)
        if sync:
            jax.block_until_ready(T)
    wtime = igg.toc()
    if me == 0:
        print(f"nt={nt} steps, global {igg.nx_g()}x{igg.ny_g()}x{igg.nz_g()}, "
              f"{nprocs} device(s), time {wtime:.3f} s ({wtime / nt * 1e3:.3f} ms/step)")

    igg.finalize_global_grid()
    return T


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=128)
    p.add_argument("--nt", type=int, default=1000)
    p.add_argument("--device-type", default="auto")
    a = p.parse_args()
    diffusion3d(nx=a.nx, nt=a.nt, device_type=a.device_type)
