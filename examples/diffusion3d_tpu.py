"""3-D heat diffusion on TPU with in-situ visualization on process 0.

Port of `/root/reference/examples/diffusion3D_multigpu_CuArrays.jl`: the full
solver with the `gather` → heatmap → GIF pipeline, with fields in TPU HBM
(``device_type="tpu"``).

Run:
    python examples/diffusion3d_tpu.py [--nx 128] [--nt 2000] [--nvis 500]
"""

import argparse
import importlib.util
import os

_here = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "diffusion3d_multidevice", os.path.join(_here, "diffusion3d_multidevice.py")
)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=128)
    p.add_argument("--nt", type=int, default=2000)
    p.add_argument("--nvis", type=int, default=500)
    p.add_argument("--outdir", default=".")
    a = p.parse_args()
    _mod.diffusion3d_vis(a.nx, a.nt, a.nvis, "tpu", a.outdir)
