"""Visualization pattern only — the solver lines are elided.

Port of `/root/reference/examples/diffusion3D_multigpu_CuArrays_onlyvis.jl`,
which documents just the in-situ visualization recipe: every ``nvis`` steps,
strip the halo locally, gather the blocks to process 0, and render the
mid-plane.  See `diffusion3d_multidevice.py` for the complete solver.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import implicitglobalgrid_tpu as igg


def diffusion3d():
    # Physics
    # (...)

    # Numerics
    # (...)
    me, dims, nprocs, coords, mesh = igg.init_global_grid(nx, ny, nz)  # noqa: F821
    # (...)

    # Array initializations + initial conditions
    # (...)

    # Preparation of visualization: the gathered array is the halo-stripped
    # blocks side by side — (n-2)*dims cells per dimension.
    frames = []
    ny_v = (ny - 2) * dims[1]  # noqa: F821

    # Time loop
    for it in range(nt):  # noqa: F821
        if it % 1000 == 0:  # visualize every 1000th step
            T_nohalo = igg.block_slice(T, (slice(1, -1),) * 3)  # noqa: F821  strip halo locally
            T_v = igg.gather(T_nohalo)  # gather on process 0
            if me == 0:
                frames.append(np.array(T_v[:, ny_v // 2, :]).T)  # mid-plane heatmap frame
        # (... stencil update + update_halo ...)

    # Postprocessing: write frames to GIF/MP4 on process 0.
    # (...)
    igg.finalize_global_grid()
