"""Visualization pattern only — the solver lines are elided.

Port of `/root/reference/examples/diffusion3D_multigpu_CuArrays_onlyvis.jl`,
which documents just the in-situ visualization recipe: every ``nvis`` steps,
strip the halo locally, gather the blocks to process 0, and render the
mid-plane.  The solver (physics, numerics, stencil update) is deliberately
elided — see `diffusion3d_multidevice.py` for the complete program — but the
recipe itself is runnable: one field stands in for the solver state so the
strip/gather/frame path executes end to end.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import implicitglobalgrid_tpu as igg


def diffusion3d(nx=8, ny=8, nz=8, nt=3, nvis=1, **grid_kwargs):
    # Physics
    # (...)

    # Numerics
    # (...)
    me, dims, nprocs, coords, mesh = igg.init_global_grid(nx, ny, nz, **grid_kwargs)

    # Array initializations + initial conditions (solver arrays elided — one
    # field suffices to demonstrate the visualization recipe)
    T = igg.zeros((nx, ny, nz))

    # Preparation of visualization: the gathered array is the halo-stripped
    # blocks side by side — (n-2)*dims cells per dimension.
    frames = []
    ny_v = (ny - 2) * dims[1]

    # Time loop
    for it in range(nt):
        if it % nvis == 0:  # visualize every nvis-th step
            T_nohalo = igg.block_slice(T, (slice(1, -1),) * 3)  # strip halo locally
            T_v = igg.gather(T_nohalo)  # gather on process 0
            if me == 0:
                frames.append(np.array(T_v[:, ny_v // 2, :]).T)  # mid-plane heatmap frame
        # (... stencil update + update_halo ...)

    # Postprocessing: write frames to GIF/MP4 on process 0.
    # (...)
    igg.finalize_global_grid()
    return frames


if __name__ == "__main__":
    n = len(diffusion3d())
    print(f"onlyvis recipe produced {n} frame(s)")
