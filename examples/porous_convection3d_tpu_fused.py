"""3-D porous convection with the fused PT-iteration kernel — the fast path.

The flagship (HydroMech weak-scaling analogue, BASELINE config 4) on its
production configuration: ``overlap = 2w`` deep halos license ``w``
pseudo-transient relaxation iterations per HBM pass *and* per all-field slab
exchange — `porous_convection3d.make_multi_step(fused_k=w)` wires both over
the padded face layout (`ops/pallas_pt.py`).  On one v5e chip at 256^3 f32
the PT loop sustains ~1050 GB/s/chip effective (8-pass convention, w=6) vs
~225 GB/s for the XLA path at the same size; the full time step (including
the temperature update) lands at ~700-770 GB/s/PT-iter.

``w`` must divide ``npt`` (the PT iterations per time step — a caller error
otherwise); shapes outside the kernel envelope (e.g. a minor dimension that
is not a multiple of 128) warn once and fall back to the XLA cadence.

Run (any number of devices; overlap=12 enables the tuned w=6):
    python examples/porous_convection3d_tpu_fused.py [--nx 256] [--nt 24] [--w 6] [--npt 12]
"""

import argparse


def porous_convection3d_fused(nx=256, nt=24, w=6, npt=12, ny=None, nz=None,
                              fused_tile=None, **setup_kwargs):
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import porous_convection3d as pc

    state, params = pc.setup(
        nx,
        ny if ny is not None else nx,
        nz if nz is not None else nx,
        npt=npt,
        overlapx=2 * w,
        overlapy=2 * w,
        overlapz=2 * w,
        dtype=jax.numpy.float32,
        **setup_kwargs,
    )
    # Whole time steps chunk into one program (each carries npt PT
    # iterations); donate=False for remote/tunneled runtimes — flip it back
    # on for a locally attached pod (docs/performance.md).
    chunk = max(min(nt, 8), 1)
    step = pc.make_multi_step(
        params, chunk, fused_k=w, fused_tile=fused_tile, donate=False
    )
    state = step(*state)  # compile + warmup chunk
    float(state[0].addressable_shards[0].data[0, 0, 0])  # honest completion sync
    igg.tic()
    for _ in range(max(nt // chunk, 1)):
        state = step(*state)
    T = pc.temperature(state)
    float(T.addressable_shards[0].data[0, 0, 0])
    t = igg.toc()
    me = igg.get_global_grid().me
    igg.finalize_global_grid()
    if me == 0:
        steps = max(nt // chunk, 1) * chunk
        print(
            f"{steps} steps x {npt} PT iterations in {t:.3f} s = "
            f"{t / (steps * npt) * 1e3:.3f} ms/PT-iteration"
        )
    return T


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=256)
    p.add_argument("--nt", type=int, default=24)
    p.add_argument("--w", type=int, default=6)
    p.add_argument("--npt", type=int, default=12)
    a = p.parse_args()
    porous_convection3d_fused(nx=a.nx, nt=a.nt, w=a.w, npt=a.npt)
