"""3-D heat diffusion, fused deep-halo cadence on a z-split decomposition.

The production path for topologies that split the MINOR (z) dimension —
where a naive slab exchange is the most expensive (minor-dim plane surgery
at lane-unaligned offsets forces whole-array relayouts at the Pallas kernel
boundary; docs/performance.md's exchanged-dimension anisotropy section).
`make_multi_step(fused_k=k)` detects z halo activity and routes the z
exchange through small patch arrays: the kernel applies the incoming patch
tile-by-tile in VMEM AND exports the next group's send slabs
(`ops/pallas_stencil.py` ``z_export``), so the z communication runs
entirely on thin arrays — on a mesh the z `collective_permute` moves
(nx, ny, k)-sized slabs instead of full fields.  Since round 5 the
diffusion cadence auto-selects full-y tiles where VMEM allows and then uses
the TRANSPOSED thin-patch layout (`ops/halo.py::z_patch_from_export_t`,
~16x less patch window traffic than the packed 128-lane form it falls back
to on y-windowed tiles).

Measured on one v5e chip (periodic-z self-neighbor degenerate config, the
same exchange work a z-split mesh pays per hop): 256^3 f32 k=4 at ~520
GB/s/chip effective (round 4 packed: 409; round-2 non-kernel cadence:
~210); the acoustic analogue reaches ~855 GB/s (vs 557 receive-side-only).

The reference has no counterpart: its z exchange always copies full halo
planes through staged buffers (`/root/reference/src/update_halo.jl:544-563`).

Run (1 device exercises the self-neighbor wrap; N devices split z):
    python examples/diffusion3d_tpu_zsplit_fused.py [--nx 256] [--nt 200] [--k 4]
"""

import argparse
import time


def diffusion3d_zsplit(nx=256, nt=200, k=4, ny=None, nz=None, **setup_kwargs):
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    ny = nx if ny is None else ny
    nz = nx if nz is None else nz
    n_dev = len(setup_kwargs.get("devices") or jax.devices())
    if n_dev > 1:
        # Force the decomposition onto z — the config this cadence exists
        # for (default dims_create splits x first).
        setup_kwargs.update(dimx=1, dimy=1, dimz=n_dev)
    else:
        # One device: periodic z makes the block its own z-neighbor, so the
        # full z-patch pipeline (pack -> communicate -> in-kernel apply +
        # export) runs and is verifiable — the reference's self-neighbor
        # trick (/root/reference/test/test_update_halo.jl:1-3).
        setup_kwargs.setdefault("periodz", 1)
    state, params = diffusion3d.setup(
        nx, ny, nz,
        overlapx=2 * k, overlapy=2 * k, overlapz=2 * k,
        dtype=jax.numpy.float32,
        **setup_kwargs,
    )
    chunk = max(k * max(min(nt, 100) // k, 1), k)
    step = diffusion3d.make_multi_step(params, chunk, fused_k=k, donate=False)
    state = step(*state)  # compile + warmup chunk
    float(state[0].addressable_shards[0].data[0, 0, 0])
    igg.tic()
    for _ in range(max(nt // chunk, 1)):
        state = step(*state)
    T = diffusion3d.temperature(state)
    float(T.addressable_shards[0].data[0, 0, 0])
    t = igg.toc()
    gg = igg.get_global_grid()
    me, dims = gg.me, gg.dims
    igg.finalize_global_grid()
    if me == 0:
        steps = max(nt // chunk, 1) * chunk + chunk
        teff = 2 * nx * ny * nz * 4 / (t / (max(nt // chunk, 1) * chunk)) / 1e9
        print(
            f"z-split fused diffusion: dims={dims}, ({nx},{ny},{nz})/block, k={k}, "
            f"{steps} steps, T_eff ~ {teff:.0f} GB/s/chip (single-sync wall "
            "clock — on tunneled backends the host round trip dominates "
            "short runs; benchmarks/run.py --period z cancels it)"
        )
    return T


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=256)
    p.add_argument("--nt", type=int, default=200)
    p.add_argument("--k", type=int, default=4)
    a = p.parse_args()
    diffusion3d_zsplit(a.nx, a.nt, a.k)
