"""3-D staggered acoustic FDTD with the fused leapfrog kernel — the fast path.

The staggered sibling of `diffusion3d_tpu_fused.py`: ``overlap = 2k`` deep
halos license ``k`` temporally-blocked leapfrog steps per HBM pass *and* per
all-field slab exchange — `acoustic3d.make_multi_step(fused_k=k)` wires both
over the even-extent padded face layout (`ops/pallas_leapfrog.py`).  On one
v5e chip at 256^3 f32 this sustains ~1050-1130 GB/s/chip effective (8-pass
convention) vs ~400 GB/s for the best per-step XLA config — the kernel that
the round-2 analysis said could not exist for ``n+1`` staggered fields (see
`docs/performance.md`).

The reference has no counterpart: its staggered test fields
(`/root/reference/test/test_update_halo.jl:828-937`) always exchange one
plane per step.

Run (any number of devices; overlap=12 enables the tuned k=6; the minor
dimension must be a multiple of 128 or the model falls back to XLA):
    python examples/acoustic3d_tpu_fused.py [--nx 256] [--nt 600] [--k 6]
"""

import argparse


def acoustic3d_fused(nx=256, nt=600, k=6, ny=None, nz=None, fused_tile=None,
                     **setup_kwargs):
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import acoustic3d

    state, params = acoustic3d.setup(
        nx,
        ny if ny is not None else nx,
        nz if nz is not None else nx,
        overlapx=2 * k,
        overlapy=2 * k,
        overlapz=2 * k,
        dtype=jax.numpy.float32,
        **setup_kwargs,
    )
    # Large chunks amortize per-call dispatch latency; `fused_k` must divide
    # the chunk.  donate=False for remote/tunneled runtimes — flip it back on
    # for a locally attached pod (docs/performance.md).
    chunk = max(k * max(min(nt, 96) // k, 1), k)
    step = acoustic3d.make_multi_step(
        params, chunk, fused_k=k, fused_tile=fused_tile, donate=False
    )
    state = step(*state)  # compile + warmup chunk
    float(state[0].addressable_shards[0].data[0, 0, 0])  # honest completion sync
    igg.tic()
    for _ in range(max(nt // chunk, 1)):
        state = step(*state)
    P = acoustic3d.pressure(state)
    float(P.addressable_shards[0].data[0, 0, 0])
    t = igg.toc()
    me = igg.get_global_grid().me
    igg.finalize_global_grid()
    if me == 0:
        steps = max(nt // chunk, 1) * chunk
        print(f"{steps} steps in {t:.3f} s = {t / steps * 1e3:.3f} ms/step")
    return P


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=256)
    p.add_argument("--nt", type=int, default=600)
    p.add_argument("--k", type=int, default=6)
    a = p.parse_args()
    acoustic3d_fused(nx=a.nx, nt=a.nt, k=a.k)
