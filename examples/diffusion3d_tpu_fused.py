"""3-D heat diffusion with deep-halo temporal blocking — the fast path.

The production configuration for bandwidth-bound runs: ``overlap = 2k`` deep
halos license ``k`` temporally-blocked Pallas kernel steps per HBM pass *and*
per halo collective (`update_halo(width=k)` slab exchange) — `make_multi_step
(fused_k=k)` wires both.  On one v5e chip at 256^3 f32 this sustains ~550
GB/s/chip effective vs ~380-400 GB/s for the per-step XLA path (3.6x the
reference's optimized-P100 baseline, `/root/reference/README.md:159-163`);
on a mesh, each `collective_permute` hop additionally amortizes over k steps.

The reference has no counterpart: it always exchanges one plane per step
(`/root/reference/src/update_halo.jl:544-563`).  This is the TPU-first
redesign its custom-kernel precedent points at
(`/root/reference/src/update_halo.jl:430`).

Run (any number of devices; overlap=4 enables k=2):
    python examples/diffusion3d_tpu_fused.py [--nx 256] [--nt 1000] [--k 2]
"""

import argparse
import time


def diffusion3d_fused(nx=256, nt=1000, k=2, **setup_kwargs):
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    state, params = diffusion3d.setup(
        nx,
        nx,
        nx,
        overlapx=2 * k,
        overlapy=2 * k,
        overlapz=2 * k,
        dtype=jax.numpy.float32,
        **setup_kwargs,
    )
    # Large chunks amortize per-call dispatch latency (one compiled program
    # advances `chunk` steps); `fused_k` must divide the chunk.
    # donate=False: on remote/tunneled runtimes donated buffers round-trip
    # through the host (docs/performance.md); on a locally attached pod flip
    # it back on — donation is the memory-correct production setting there.
    chunk = max(k * max(min(nt, 100) // k, 1), k)
    step = diffusion3d.make_multi_step(params, chunk, fused_k=k, donate=False)
    state = step(*state)  # compile + warmup chunk
    float(state[0].addressable_shards[0].data[0, 0, 0])  # honest completion sync
    igg.tic()
    for _ in range(max(nt // chunk, 1)):
        state = step(*state)
    # Async dispatch: force completion before reading the clock.  A one-element
    # fetch is the only sync some remote backends honor (block_until_ready can
    # return early there); it costs one host round trip.
    T = diffusion3d.temperature(state)
    float(T.addressable_shards[0].data[0, 0, 0])
    t = igg.toc()
    me = igg.get_global_grid().me
    igg.finalize_global_grid()
    if me == 0:
        steps = max(nt // chunk, 1) * chunk
        print(f"{steps} steps in {t:.3f} s = {t / steps * 1e3:.3f} ms/step")
    return T


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=256)
    p.add_argument("--nt", type=int, default=1000)
    p.add_argument("--k", type=int, default=2)
    a = p.parse_args()
    diffusion3d_fused(nx=a.nx, nt=a.nt, k=a.k)
