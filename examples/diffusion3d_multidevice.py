"""3-D heat diffusion with in-situ visualization on process 0.

Port of `/root/reference/examples/diffusion3D_multicpu.jl` (vis variant; the
GPU twin is `diffusion3D_multigpu_CuArrays.jl`).  Every ``nvis`` steps the
halo-stripped temperature blocks are gathered to the root process and a
mid-plane heatmap frame is written; at the end the frames become a GIF —
the reference's `gather!` → `heatmap` → `gif` pipeline
(`/root/reference/examples/diffusion3D_multicpu.jl:44-56,66-68`).

Run:
    python examples/diffusion3d_multidevice.py [--nx 64] [--nt 2000] [--nvis 500]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import implicitglobalgrid_tpu as igg


def diffusion3d_vis(nx=64, nt=2000, nvis=500, device_type="auto", outdir="."):
    import jax.numpy as jnp

    lam, cp_min = 1.0, 1.0
    lx, ly, lz = 10.0, 10.0, 10.0
    ny = nz = nx
    me, dims, nprocs, coords, mesh = igg.init_global_grid(
        nx, ny, nz, device_type=device_type
    )
    dx = lx / (igg.nx_g() - 1)
    dy = ly / (igg.ny_g() - 1)
    dz = lz / (igg.nz_g() - 1)
    dtype = jax.dtypes.canonicalize_dtype(float)

    T = igg.zeros((nx, ny, nz), dtype)
    X, Y, Z = igg.coord_fields(T, (dx, dy, dz), dtype=dtype)

    @igg.stencil
    def init_ic(X, Y, Z):
        Cp = cp_min + (
            5 * jnp.exp(-((X - lx / 1.5) ** 2) - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
            + 5 * jnp.exp(-((X - lx / 3.0) ** 2) - (Y - ly / 2) ** 2 - (Z - lz / 1.5) ** 2)
        )
        T = 100 * jnp.exp(
            -(((X - lx / 2) / 2) ** 2) - ((Y - ly / 2) / 2) ** 2 - ((Z - lz / 3.0) / 2) ** 2
        ) + 50 * jnp.exp(
            -(((X - lx / 2) / 2) ** 2) - ((Y - ly / 2) / 2) ** 2 - ((Z - lz / 1.5) / 2) ** 2
        )
        return Cp.astype(dtype), T.astype(dtype)

    Cp, T = init_ic(X, Y, Z)
    dt = min(dx * dx, dy * dy, dz * dz) * cp_min / lam / 8.1

    def inn(A):
        return A[1:-1, 1:-1, 1:-1]

    @igg.stencil(donate_argnums=(0,))
    def step(T, Cp):
        lap = (
            (T[2:, 1:-1, 1:-1] - 2 * inn(T) + T[:-2, 1:-1, 1:-1]) / (dx * dx)
            + (T[1:-1, 2:, 1:-1] - 2 * inn(T) + T[1:-1, :-2, 1:-1]) / (dy * dy)
            + (T[1:-1, 1:-1, 2:] - 2 * inn(T) + T[1:-1, 1:-1, :-2]) / (dz * dz)
        )
        T = T + jnp.pad(dt * lam / inn(Cp) * lap, 1)
        return igg.update_halo(T), Cp

    # Preparation of visualization (reference :42-48): the gathered array is
    # the halo-stripped blocks side by side — (nx-2)*dims per dimension.
    frames = []
    ny_v = (ny - 2) * dims[1]
    sync = mesh.devices.flat[0].platform == "cpu"

    for it in range(nt):
        if it % nvis == 0:  # reference :52 (visualize every nvis-th step)
            T_nohalo = igg.block_slice(T, (slice(1, -1),) * 3)  # strip halo (:53)
            T_v = igg.gather(T_nohalo)  # gather on process 0 (:54)
            if me == 0:
                frames.append(np.array(T_v[:, ny_v // 2, :]).T)  # mid-plane (:55)
        T, Cp = step(T, Cp)
        if sync:
            jax.block_until_ready(T)

    if me == 0 and frames:
        _write_frames(frames, outdir)
    igg.finalize_global_grid()
    return frames


def _write_frames(frames, outdir):
    """Write heatmap frames; make a GIF when matplotlib is available
    (the reference's `gif(anim, ...)`, else dump raw .npy frames)."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib import animation

        fig, ax = plt.subplots()
        im = ax.imshow(frames[0], origin="lower", aspect="equal", cmap="inferno")
        fig.colorbar(im, ax=ax)

        def update(i):
            im.set_data(frames[i])
            im.autoscale()
            ax.set_title(f"frame {i}")
            return (im,)

        ani = animation.FuncAnimation(fig, update, frames=len(frames))
        path = os.path.join(outdir, "diffusion3d.gif")
        ani.save(path, writer=animation.PillowWriter(fps=15))
        print(f"wrote {path} ({len(frames)} frames)")
    except Exception as e:  # matplotlib optional in this environment
        path = os.path.join(outdir, "diffusion3d_frames.npy")
        np.save(path, np.stack(frames))
        print(f"matplotlib unavailable ({e!r}); wrote {path}")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--nx", type=int, default=64)
    p.add_argument("--nt", type=int, default=2000)
    p.add_argument("--nvis", type=int, default=500)
    p.add_argument("--device-type", default="auto")
    p.add_argument("--outdir", default=".")
    a = p.parse_args()
    diffusion3d_vis(a.nx, a.nt, a.nvis, a.device_type, a.outdir)
