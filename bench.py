"""Benchmark: 3-D heat diffusion effective memory throughput (T_eff) per chip.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}``.

Thin wrapper over `benchmarks/run.py` (the full harness — weak scaling,
acoustic, porous configs live there); this entry point runs the headline
config and adds the baseline ratio.

T_eff follows the reference community's convention (ParallelStencil/IGG
papers): the diffusion step *must* stream temperature once in and once out per
iteration, so ``A_eff = 2 * nx*ny*nz * sizeof(dtype)`` and
``T_eff = A_eff / t_it``.  This is a lower bound on achieved HBM traffic
(reads of Cp and the halo exchange are free on top), making the number
directly comparable across machines and implementations.

Baseline: the reference publishes 510^3 on 8x P100 = local 256^3/GPU at 17.4
ms/step for the broadcast version (100k steps / 29 min, `README.md:159-163`
of the reference) => T_eff = 2*256^3*8 B / 17.4 ms = 15.4 GB/s, and states
the optimized kernel version is ">10x faster" (`README.md:163`) => 154 GB/s
per P100.  ``vs_baseline`` is measured T_eff / 154 GB/s.

Run on the default backend (one real TPU chip under the driver; any JAX
backend works).  Local grid 256^3 Float32 — the same per-chip problem as the
reference's headline run, in TPU-native single precision.
"""

import importlib.util
import json
import os

BASELINE_TEFF_GBS = 154.0  # reference optimized version, per P100 (see docstring)

_here = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "igg_benchmarks_run", os.path.join(_here, "benchmarks", "run.py")
)
_bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_bench)


def main():
    rec = _bench.bench_diffusion(n=256, chunk=25, reps=4, dtype="float32", emit=False)
    print(
        json.dumps(
            {
                "metric": rec["metric"] + "_teff",
                "value": rec["value"],
                "unit": "GB/s/chip",
                "vs_baseline": round(rec["value"] / BASELINE_TEFF_GBS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
