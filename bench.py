"""Benchmark: 3-D heat diffusion effective memory throughput (T_eff) per chip.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}``.

T_eff follows the reference community's convention (ParallelStencil/IGG
papers): the diffusion step *must* stream temperature once in and once out per
iteration, so ``A_eff = 2 * nx*ny*nz * sizeof(dtype)`` and
``T_eff = A_eff / t_it``.  This is a lower bound on achieved HBM traffic
(reads of Cp and the halo exchange are free on top), making the number
directly comparable across machines and implementations.

Baseline: the reference publishes 510^3 on 8x P100 = local 256^3/GPU at 17.4
ms/step for the broadcast version (100k steps / 29 min, `README.md:159-163`
of the reference) => T_eff = 2*256^3*8 B / 17.4 ms = 15.4 GB/s, and states
the optimized kernel version is ">10x faster" (`README.md:163`) => 154 GB/s
per P100.  ``vs_baseline`` is measured T_eff / 154 GB/s.

Run on the default backend (one real TPU chip under the driver; any JAX
backend works).  Local grid 256^3 Float32 — the same per-chip problem as the
reference's headline run, in TPU-native single precision.
"""

import json
import time


BASELINE_TEFF_GBS = 154.0  # reference optimized version, per P100 (see docstring)


def _sync(state):
    """Full synchronization: fetch one scalar (block_until_ready alone can
    return early on remote-tunneled backends)."""
    import jax

    jax.block_until_ready(state)
    float(state[0].ravel()[0])


def bench_diffusion_teff(n: int = 256, chunk: int = 25, reps: int = 4):
    import jax

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    state, params = diffusion3d.setup(
        n, n, n, dtype=jax.numpy.float32, quiet=True
    )
    step = diffusion3d.make_multi_step(params, chunk)
    state = step(*state)  # compile + warm up
    _sync(state)
    t0 = time.perf_counter()
    for _ in range(reps):
        state = step(*state)
    _sync(state)
    t_it = (time.perf_counter() - t0) / (reps * chunk)
    igg.finalize_global_grid()

    nprocs = len(jax.devices())
    bytes_per_chip = 2 * n**3 * jax.numpy.dtype(params.dtype).itemsize
    teff = bytes_per_chip / t_it / 1e9
    return teff, t_it, nprocs


def main():
    teff, t_it, nprocs = bench_diffusion_teff()
    print(
        json.dumps(
            {
                "metric": "diffusion3d_256_f32_teff",
                "value": round(teff, 2),
                "unit": "GB/s/chip",
                "vs_baseline": round(teff / BASELINE_TEFF_GBS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
