"""Benchmark: 3-D heat diffusion effective memory throughput (T_eff) per chip.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline", "extras"}``.

Thin wrapper over `benchmarks/run.py` (the full harness — weak scaling,
acoustic, porous configs live there); this entry point measures the headline
config on both production paths — the plain XLA stencil and the
temporally-blocked Pallas kernel (`implicitglobalgrid_tpu/ops/pallas_stencil.py`,
k=4 steps per HBM pass, full-y (32, n1) tiles since round 5 — ~1.7x the XLA
path on v5e)
— and reports the faster one, with both recorded in ``extras`` alongside the
remaining BASELINE.json configs (comm/compute-overlap variant, acoustic,
porous) so every promised config has a round artifact.

T_eff follows the reference community's convention (ParallelStencil/IGG
papers): the diffusion step *must* stream temperature once in and once out per
iteration, so ``A_eff = 2 * nx*ny*nz * sizeof(dtype)`` and
``T_eff = A_eff / t_it``.  This is a lower bound on achieved HBM traffic
(reads of Cp and the halo exchange are free on top) — and it is exactly why
temporal blocking can push T_eff *above* raw copy bandwidth: k fused steps
read/write HBM roughly once, so the per-step effective traffic exceeds the
streaming bound.

Baseline: the reference publishes 510^3 on 8x P100 = local 256^3/GPU at 17.4
ms/step for the broadcast version (100k steps / 29 min, `README.md:159-163`
of the reference) => T_eff = 2*256^3*8 B / 17.4 ms = 15.4 GB/s, and states
the optimized kernel version is ">10x faster" (`README.md:163`) => 154 GB/s
per P100.  ``vs_baseline`` is measured T_eff / 154 GB/s.  Two caveats bias
this comparison and are accepted as-is: (a) the reference's 29-minute figure
*includes in-situ visualization*, so 17.4 ms/step overstates the baseline's
pure-solver cost (ratio biased in our favor); (b) the baseline ran Float64
while this bench runs TPU-native Float32 — under the byte-counting T_eff
convention an f32 step moves half the bytes of an f64 step, so equal GB/s
does not mean equal steps/s.

Run on the default backend (one real TPU chip under the driver; any JAX
backend works).  Local grid 256^3 Float32 — the same per-chip problem as the
reference's headline run, in TPU-native single precision.

Record persistence: besides the stdout JSON line, a script run ALSO writes
the record as the next ``BENCH_r<N>.json`` via a temp file + ``os.replace``
(`_write_round_record`; ``--out PATH`` overrides the name, ``--no-record``
suppresses it).  Round 5's record was lost exactly the way this prevents —
the capture crashed mid-write and the only copy was half-flushed stdout, so
the trajectory carries a hole the perf gate must baseline around.  An
atomic rename publishes a record whole or not at all.
"""

import importlib.util
import json
import os

BASELINE_TEFF_GBS = 154.0  # reference optimized version, per P100 (see docstring)

_here = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "igg_benchmarks_run", os.path.join(_here, "benchmarks", "run.py")
)
_bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_bench)


def _cpu_mesh_json(args, timeout=1800):
    # Shared subprocess driver for records defined on the virtual
    # 8-device CPU mesh (the TPU backend is already initialized in this
    # process; one core timeshares all 8 "devices" there, so wall times
    # from these runs are code-path records, not performance numbers).
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_here, env.get("PYTHONPATH")) if p
    )
    out = subprocess.run(
        [sys.executable, os.path.join(_here, "benchmarks", "run.py"),
         *args],
        capture_output=True, text=True, env=env, timeout=timeout,
    )
    rec = None
    for line in out.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # brace-prefixed non-JSON noise
    if rec is None:
        raise RuntimeError(
            f"{args[0]} run produced no JSON (rc={out.returncode}): "
            f"{out.stderr[-400:]}"
        )
    return rec


def _write_round_record(record: dict, out: str = "auto") -> str | None:
    """Atomically persist ``record`` as a ``BENCH_r*.json`` round artifact.

    ``out="auto"`` picks the next round number after the committed ones;
    an explicit path is used as-is; ``None``/empty skips.  The bytes are
    flushed + fsynced into a ``.tmp`` sibling and published with ONE
    ``os.replace`` — a crash mid-capture leaves no partial file, so a
    round can never again exist only as truncated stdout (see module
    docstring: that is how r05 was lost).
    """
    import glob
    import re
    import sys

    if not out:
        return None
    if out == "auto":
        rounds = [
            int(m.group(1))
            for p in glob.glob(os.path.join(_here, "BENCH_r*.json"))
            for m in [re.search(r"BENCH_r(\d+)\.json$", p)]
            if m
        ]
        out = os.path.join(
            _here, f"BENCH_r{(max(rounds) + 1) if rounds else 1:02d}.json"
        )
    from implicitglobalgrid_tpu.utils.telemetry import atomic_write_json

    atomic_write_json(out, record, indent=1)
    print(f"[bench] record written atomically to {out}", file=sys.stderr)
    return out


def _frontdoor_serving_record(n=32, requests=6, max_steps=8, capacity=2):
    """ISSUE 12: the network-facing serving record — submit→result latency
    and round throughput through the REAL HTTP front door on this backend
    (loopback, ephemeral port; `implicitglobalgrid_tpu/serving/frontdoor.py`).
    ``rounds_per_s`` and the inverse latencies ``result_p50_per_s`` /
    ``result_p99_per_s`` are gated perf metrics (`analysis.perf.GATED_KEYS`
    — a latency rise is a rate drop, so the one-sided gate catches it);
    the raw seconds ride along as reported keys.
    """
    import json as _json
    import time
    import urllib.request

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import FrontDoor, ServingLoop

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    igg.init_global_grid(n, n, n, quiet=True)
    try:
        _, params = diffusion3d.setup(n, n, n, init_grid=False)
        loop = ServingLoop(
            diffusion3d, params, capacity=capacity, steps_per_round=1
        )
        fd = FrontDoor(loop, port=0)
        try:
            t0 = time.perf_counter()
            rids = []
            for i in range(requests):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{fd.port}/v1/submit",
                    data=_json.dumps({
                        "tenant": f"t{i % 3}",
                        "params": {"max_steps": max_steps,
                                   "ic_scale": 1.0 + i / 16.0},
                    }).encode(),
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    rids.append(_json.load(r)["request_id"])
            # one iteration at a time, stopping the clock at the LAST
            # retirement: a fixed iteration budget would pad `elapsed`
            # with idle-sleep iterations after the work is done and
            # dilute the gated rounds_per_s metric
            budget = requests * max_steps + 8
            while budget > 0 and not all(
                (fd.result_view(rid) or {}).get("status") == "done"
                for rid in rids
            ):
                fd.serve_rounds(max_rounds=1)
                budget -= 1
            elapsed = time.perf_counter() - t0
            lats = []
            for rid in rids:
                view = fd.result_view(rid)
                if not view or view.get("status") != "done":
                    raise RuntimeError(f"request {rid} never completed: {view}")
                lats.append(view["latency_s"])
            lats.sort()
            p50 = lats[len(lats) // 2]
            p99 = lats[min(len(lats) - 1, round(0.99 * (len(lats) - 1)))]
            return {
                "n": n,
                "requests": requests,
                "capacity": capacity,
                "max_steps": max_steps,
                "rounds": loop.rounds,
                "rounds_per_s": round(loop.rounds / elapsed, 3),
                "result_p50_per_s": round(1.0 / p50, 4),
                "result_p99_per_s": round(1.0 / p99, 4),
                "submit_to_result_p50_s": round(p50, 4),
                "submit_to_result_p99_s": round(p99, 4),
                "note": (
                    "loopback HTTP through serving.FrontDoor; latency "
                    "includes queue wait (requests > capacity by design)"
                ),
            }
        finally:
            fd.close()
    finally:
        igg.finalize_global_grid()


def _request_trace_record(n=32, max_steps=6, capacity=2):
    """ISSUE 19: the request critical-path record — ONE traced request
    through the real loopback front door (inbound W3C ``traceparent``
    accepted and echoed), its causal tree reconstructed in-process from
    the span ring (the same per-rank doc schema ``igg_trace.py`` reads)
    and its latency attributed per segment (`utils.tracing.critical_path`).
    The flat ``*_share`` keys are REPORTED perf-gate keys
    (`analysis.perf.REPORTED_KEYS`): a latency regression names its
    segment from the artifact alone.
    """
    import json as _json
    import urllib.request

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import diffusion3d
    from implicitglobalgrid_tpu.serving import FrontDoor, ServingLoop
    from implicitglobalgrid_tpu.utils import tracing as _tracing

    if not _tracing.enabled():
        return {"skipped": "tracing disabled (IGG_TELEMETRY/IGG_TRACE_RING)"}
    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    igg.init_global_grid(n, n, n, quiet=True)
    try:
        _, params = diffusion3d.setup(n, n, n, init_grid=False)
        loop = ServingLoop(
            diffusion3d, params, capacity=capacity, steps_per_round=1
        )
        fd = FrontDoor(loop, port=0)
        try:
            inbound = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
            req = urllib.request.Request(
                f"http://127.0.0.1:{fd.port}/v1/submit",
                data=_json.dumps({
                    "tenant": "trace", "params": {"max_steps": max_steps},
                }).encode(),
                headers={"traceparent": inbound},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                rid = _json.load(r)["request_id"]
                echoed = r.headers.get("traceparent")
            budget = max_steps + 8
            while budget > 0 and (
                (fd.result_view(rid) or {}).get("status") != "done"
            ):
                fd.serve_rounds(max_rounds=1)
                budget -= 1
            view = fd.result_view(rid)
            if not view or view.get("status") != "done":
                raise RuntimeError(f"traced request never completed: {view}")
            ctx = _tracing.parse_traceparent(echoed)
            if ctx is None or ctx["trace_id"] != "ab" * 16:
                raise RuntimeError(
                    f"traceparent did not round-trip: {echoed!r}"
                )
            # the in-process twin of dump_trace's per-rank doc — the tree
            # builds from the live ring without touching disk
            doc = {
                "schema": _tracing.TRACE_SCHEMA, "rank": 0, "gen": None,
                "dropped": _tracing.spans_dropped(),
                "clock_sync": _tracing.clock_sync(),
                "spans": _tracing.span_records(),
            }
            tree = _tracing.request_tree([doc], ctx["trace_id"])
            cp = _tracing.critical_path(tree)
            rec = {
                "trace_id": ctx["trace_id"],
                "spans": tree["spans"],
                "incomplete": tree["incomplete"],
                "latency_s": round(view["latency_s"], 4),
                "total_s": round(cp["total_s"], 4),
            }
            for seg, v in cp["segments"].items():
                rec[f"{seg}_share"] = round(v["share"], 4)
                rec[f"{seg}_s"] = round(v["s"], 6)
            return rec
        finally:
            fd.close()
    finally:
        igg.finalize_global_grid()


def _batch_extra(rec=None):
    # ISSUE 8: the ensemble-batching record — members/s/chip over a
    # B∈{1,2,4,8} sweep of the vmapped serving cadence.  Every sweep row's
    # ``members_per_s`` is a gated metric (analysis.perf.GATED_KEYS), so a
    # batching regression fails the bench-regression pass like a bandwidth
    # drop.  ``rec``: a pre-measured `bench_batch` record (main_batch) —
    # one projection of the record, however it was obtained.
    r = rec if rec is not None else _bench.bench_batch(
        n=128, chunk=16, reps=3, emit=False
    )
    return {
        "members_per_s": r["members_per_s"],
        "best_B": r["best_B"],
        "job_steps": r["job_steps"],
        "throughput_multiplier": r["throughput_multiplier"],
        "sweep": r["sweep"],
    }


def _batch_hlo_extra():
    # The structural half of the batching claim: the B=8 coalesced
    # exchange's compiled HLO must emit EXACTLY the B=1 collective count
    # (payload ×8).  Virtual-mesh record (see _cpu_mesh_json).
    rec = _cpu_mesh_json(["batch_hlo"])
    rec["note"] = (
        "virtual 8-device CPU mesh: structural collective-count A/B; "
        "equality is the B-for-the-price-of-1 invariant"
    )
    return rec


def main_batch():
    """``python bench.py batch`` — the focused ensemble-serving record:
    one JSON line with the members/s/chip sweep, the HLO collective A/B
    and its own perf-gate verdict."""
    extras = {}
    rec = _bench.bench_batch(n=128, chunk=16, reps=3, emit=False)
    extras["batch_ensemble"] = _batch_extra(rec)
    try:
        extras["batch_hlo_ab"] = _batch_hlo_extra()
    except Exception as e:  # structural A/B must not sink the record
        extras["batch_hlo_ab"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        from implicitglobalgrid_tpu.analysis.perf import gate_summary

        # No "value" key on purpose: this record's headline is members/s,
        # the committed rounds' is GB/s — only the namespaced
        # ``members_per_s`` extras are comparable across rounds.
        extras["perf_gate"] = gate_summary({"extras": extras}, _here)
    except Exception as e:
        extras["perf_gate"] = {"error": f"{type(e).__name__}: {e}"}
    print(
        json.dumps(
            {
                "metric": "diffusion3d_batch_members_per_s",
                "value": rec["members_per_s"],
                "unit": "members/s/chip",
                "extras": extras,
            }
        )
    )


def main(out: str | None = None):
    # Headline: the faster of the two production paths at the headline config
    # (metric name unchanged from round 1 for comparability).  The XLA path
    # is the always-available fallback if the Pallas kernel fails on some
    # backend.
    # reps=5 (odd) on the two headline configs: the time-shared chip drifts
    # ~10% between reps, and the recorded value is the per-rep median (odd
    # rep counts make that the true middle sample, not the upper-median).
    rec = _bench.bench_diffusion(n=256, chunk=24, reps=5, dtype="float32", emit=False)
    extras = {"diffusion_xla": {"teff": rec["value"], "t_it_ms": rec["t_it_ms"]}}

    def _extra(name, fn):
        # Per-config isolation: one crashing extra (e.g. a backend compile
        # fault) must not discard the remaining configs.  Shape-level kernel
        # rejection no longer lands here: make_multi_step(fused_k=...) falls
        # back to the XLA cadence on its own (warn-once), and the recorded
        # "path" says which one actually ran.
        try:
            extras[name] = fn()
        except Exception as e:
            extras[name] = {"error": f"{type(e).__name__}: {e}"}

    def _fused_record(r):
        # Path provenance comes from the harness itself now
        # (benchmarks/run.py::_fused_provenance — the same envelope check
        # the model's fallback uses, evaluated on the actual local block);
        # per-rep spread rides along (VERDICT r3 #7: cross-round drift on a
        # time-shared chip is uninterpretable without it).
        rec = {
            "teff": r["value"], "t_it_ms": r["t_it_ms"], "path": r.get("path"),
            "spread": r.get("spread"),
        }
        if "pipelined" in r:
            rec["pipelined"] = r["pipelined"]
        return rec

    def _fused():
        r = _bench.bench_diffusion(
            n=256, chunk=24, reps=5, dtype="float32", emit=False, fused_k=4
        )
        return _fused_record(r)

    def _fused512():
        # BASELINE config 5's per-chip problem size (512^3/chip).  The XLA
        # path collapses past a 256 minor dim (see docs/performance.md); the
        # fused kernel holds its throughput, so it is the production choice
        # at this size.  (32,128) measures ~7% over the (32,64) default at
        # this volume (lower halo-recompute redundancy, 1.41x vs 1.56x).
        r = _bench.bench_diffusion(
            n=512, chunk=24, reps=3, dtype="float32", emit=False, fused_k=4,
            fused_tile=(32, 128),
        )
        return _fused_record(r)

    def _overlap():
        r = _bench.bench_diffusion(
            n=256, chunk=24, reps=3, dtype="float32", emit=False, hide_comm=True
        )
        return {
            "teff": r["value"],
            "t_it_ms": r["t_it_ms"],
            "note": "1 chip: no neighbors, delta vs plain is scheduling noise",
        }

    def _acoustic():
        r = _bench.bench_acoustic(n=192, chunk=25, reps=3, dtype="float32", emit=False)
        return {"teff": r["value"], "t_it_ms": r["t_it_ms"]}

    def _acoustic_overlap():
        # BASELINE config 3 promises overlap on/off; on 1 chip the delta is
        # scheduling noise (no neighbors), recorded for artifact completeness.
        r = _bench.bench_acoustic(
            n=192, chunk=25, reps=3, dtype="float32", emit=False, hide_comm=True
        )
        return {"teff": r["value"], "t_it_ms": r["t_it_ms"]}

    def _acoustic_fused():
        # The staggered fused kernel (ops/pallas_leapfrog.py, k=6 tuned on
        # v5e) needs a 128-multiple minor dim, so it benches at 256^3 (the
        # 192^3 XLA number above is the faster XLA config; 256^3 sits past
        # the minor-dim cliff, see docs/performance.md).
        r = _bench.bench_acoustic(
            n=256, chunk=24, reps=3, dtype="float32", emit=False, fused_k=6
        )
        return _fused_record(r)

    def _porous():
        # 160^3: the smallest size whose state spills VMEM on v5e, giving a
        # stable HBM-bound number (at 128^3 the ~67 MB state is largely
        # VMEM-resident and the measurement swings 350-1100 GB/s with chip
        # tenancy).
        r = _bench.bench_porous(n=160, chunk=4, reps=3, npt=10, dtype="float32", emit=False)
        return {"teff": r["value"], "t_pt_ms": r.get("t_pt_ms")}

    def _porous_fused():
        # The fused PT kernel (ops/pallas_pt.py) needs a 128-multiple minor
        # dim -> 256^3.  Since round 4 the ragged schedule lifts the old
        # ``w | npt`` restriction, so npt=10 (a physically ordinary choice)
        # runs the tuned w=6 as chunks [6, 4] — recorded alongside npt=12
        # (VERDICT r3 #5's done criterion: npt=10 within 15% of npt=12).
        r6 = _bench.bench_porous(
            n=256, chunk=2, reps=3, npt=12, dtype="float32", emit=False, fused_k=6
        )
        r10 = _bench.bench_porous(
            n=256, chunk=2, reps=3, npt=10, dtype="float32", emit=False, fused_k=6
        )
        rec = _fused_record(r6)
        rec["t_pt_ms"] = r6.get("t_pt_ms")
        rec["npt12_w6"] = {"teff": r6["value"], "t_pt_ms": r6.get("t_pt_ms")}
        rec["npt10_w6_ragged"] = {"teff": r10["value"], "t_pt_ms": r10.get("t_pt_ms")}
        return rec

    def _diffusion_periodz_fused():
        # The z-active fused diffusion record (VERDICT r3 #1's done
        # criterion): periodic-z self-neighbor 256^3, deep halo overlapz=8,
        # k=4 — the in-kernel z-slab apply + export cadence
        # (docs/performance.md's exchanged-dimension anisotropy section).
        r = _bench.bench_diffusion(
            n=256, chunk=24, reps=3, dtype="float32", emit=False, fused_k=4,
            overlap=8, period="z",
        )
        return _fused_record(r)

    def _acoustic_periodz_fused():
        # Same degenerate config for the staggered kernel family (VERDICT
        # r3 #4: round-3 stopped at receive-side application, 557 GB/s; the
        # round-4 in-kernel export cadence measured 625).
        r = _bench.bench_acoustic(
            n=256, chunk=24, reps=3, dtype="float32", emit=False, fused_k=6,
            overlap=12, period="z",
        )
        return _fused_record(r)

    def _update_halo_donate():
        # VERDICT r4 weak #2 record: the public update_halo's donate knob,
        # measured on/off (global-array entry, 256^3 f32, periodic-z
        # self-copy so a real exchange runs on one chip).  On this tunneled
        # runtime donation round-trips through the host (docs/performance.md)
        # — the record shows which default a user should pick here.
        import implicitglobalgrid_tpu as igg

        rec = {}
        for flag in (False, True):
            if igg.grid_is_initialized():
                igg.finalize_global_grid()
            igg.init_global_grid(256, 256, 256, periodz=1, quiet=True)
            T = igg.ones((256, 256, 256), "float32")
            step = lambda T: (igg.update_halo(T, donate=flag),)
            t_it, _, spread = _bench._time_steps(step, (T,), 1, 3)
            igg.finalize_global_grid()
            rec["donate_on" if flag else "donate_off"] = {
                "t_call_ms": round(t_it * 1e3, 4), "spread": spread,
            }
        rec["note"] = "kwarg update_halo(..., donate=); env default IGG_DONATE"
        return rec

    _extra("update_halo_donate", _update_halo_donate)
    _extra("diffusion_pallas_fused4", _fused)
    _extra("diffusion_512_pallas_fused4", _fused512)
    _extra("diffusion_xla_overlap", _overlap)
    _extra("acoustic", _acoustic)
    _extra("acoustic_overlap", _acoustic_overlap)
    _extra("acoustic_256_pallas_fused6", _acoustic_fused)
    _extra("porous_pt", _porous)
    _extra("porous_256_pallas_fused", _porous_fused)
    def _porous_periodz_fused():
        # The PT family's z-active record (round 5: the merged cell+z-face
        # patch/export bands measured +16% here — 474 -> 550 GB/s/PT-iter).
        r = _bench.bench_porous(
            n=256, chunk=2, reps=3, npt=12, dtype="float32", emit=False,
            fused_k=6, overlap=14, period="z",
        )
        rec = _fused_record(r)
        rec["t_pt_ms"] = r.get("t_pt_ms")
        return rec

    _extra("diffusion_periodz_pallas_fused4", _diffusion_periodz_fused)
    _extra("acoustic_periodz_pallas_fused6", _acoustic_periodz_fused)
    _extra("porous_periodz_pallas_fused6", _porous_periodz_fused)

    # --- ISSUE 2: pipelined-vs-serialized group-schedule A/B ---------------
    # One paired record per model on its periodic-z fused config.  On this
    # 1-chip grid only z communicates, so the ring/mid split is
    # inadmissible there and the "pipelined" run honestly records its
    # fallback-serialized provenance; the periodic-xz sibling (x
    # self-neighbor => the split ENGAGES on one chip) measures the actual
    # split-launch cadence, and the 256-chip AOT proxy below shows the
    # interior passes scheduled across the collectives structurally.
    def _ab(fn):
        return {"serialized": fn(False), "pipelined": fn(True)}

    def _diffusion_ab(period):
        def run(p):
            r = _bench.bench_diffusion(
                n=256, chunk=24, reps=3, dtype="float32", emit=False,
                fused_k=4, overlap=8, period=period, pipelined=p,
            )
            return _fused_record(r)

        return _ab(run)

    def _acoustic_ab(period):
        def run(p):
            r = _bench.bench_acoustic(
                n=256, chunk=24, reps=3, dtype="float32", emit=False,
                fused_k=6, overlap=12, period=period, pipelined=p,
            )
            return _fused_record(r)

        return _ab(run)

    def _porous_ab(period):
        def run(p):
            r = _bench.bench_porous(
                n=256, chunk=2, reps=3, npt=12, dtype="float32", emit=False,
                fused_k=6, overlap=14, period=period, pipelined=p,
            )
            rec = _fused_record(r)
            rec["t_pt_ms"] = r.get("t_pt_ms")
            return rec

        return _ab(run)

    _extra("diffusion_periodz_pipelined_ab", lambda: _diffusion_ab("z"))
    _extra("acoustic_periodz_pipelined_ab", lambda: _acoustic_ab("z"))
    _extra("porous_periodz_pipelined_ab", lambda: _porous_ab("z"))
    _extra("diffusion_periodxz_pipelined_ab", lambda: _diffusion_ab("xz"))
    _extra("acoustic_periodxz_pipelined_ab", lambda: _acoustic_ab("xz"))
    _extra("porous_periodxz_pipelined_ab", lambda: _porous_ab("xz"))

    def _halo_coalesce_ab():
        # ISSUE 5 acceptance: the coalesced-vs-per-field A/B with collective
        # counts + payload bytes read from each variant's optimized HLO.  On
        # the 1-chip bench backend every partner is a self-copy (no
        # collectives either way), so the record comes from the virtual
        # 8-device CPU mesh — the structural counts are the point; the
        # timing columns are CPU code-path numbers.
        rec = _cpu_mesh_json(["coalesce", "--n", "32", "--reps", "2"])
        rec["note"] = (
            "virtual 8-device CPU mesh: collective counts/payloads are "
            "structural; t_call_ms is a code-path record, not performance"
        )
        return rec

    def _diffusion_grad():
        # VERDICT weak #6: the gradient-path throughput record
        # (`fused_with_xla_grad` — fused forward, rematerialized XLA twin
        # backward), on the real bench backend; docs/performance.md carries
        # the written row.
        r = _bench.bench_diffusion_grad(
            n=256, chunk=8, reps=3, dtype="float32", fused_k=4, emit=False
        )
        return {
            "teff_grad": r["value"], "t_grad_ms": r["t_it_ms"],
            "t_fwd_ms": r["t_fwd_ms"], "grad_over_fwd": r["grad_over_fwd"],
        }

    _extra("halo_coalesce_ab", _halo_coalesce_ab)
    _extra("diffusion_grad_fused4", _diffusion_grad)

    def _tuned_vs_default():
        # ISSUE 13: the autotuner's closed loop — tuned-vs-default A/B for
        # all three models at their fused-capable bench sizes.  Each row's
        # ``tuned_speedup`` (t_default / t_tuned) is a gated perf key
        # (analysis.perf.GATED_KEYS): a tuner regression fails check_perf
        # the way a collective-count regression already does.  The tuned
        # build resolves through the winner cache (committed seed layer +
        # IGG_TUNE_CACHE); "cache" records hit vs fresh-search provenance.
        out = {}
        for label, kwargs in (
            ("diffusion", dict(model="diffusion", n=256, chunk=24)),
            ("acoustic", dict(model="acoustic", n=256, chunk=24)),
            ("porous", dict(model="porous", n=256, chunk=2, npt=12)),
        ):
            try:
                out[label] = _bench.bench_tuned_vs_default(
                    reps=3, emit=False, **kwargs
                )
            except Exception as e:  # one model's A/B must not sink the rest
                out[label] = {"error": f"{type(e).__name__}: {e}"}
        return out

    _extra("tuned_vs_default", _tuned_vs_default)

    def _weak_codepath():
        # VERDICT r4 missing #2(a): the virtual-mesh weak-scaling CODE-PATH
        # record, in the driver artifact itself (see `_cpu_mesh_json` for
        # why a subprocess, and why the ratio is NOT a performance number).
        rec = _cpu_mesh_json(["weak", "--n", "16", "--chunk", "4",
                              "--reps", "2"])
        rec["note"] = (
            "virtual 8-device CPU mesh CODE-PATH record: one core timeshares "
            "all devices, the efficiency ratio is NOT a performance number"
        )
        return rec

    def _weak_aot_proxy():
        # VERDICT r4 missing #2(b): the north-star-topology structural
        # record — 256-chip (4,4,16) mesh, 512^3/chip, packed-z exchange;
        # per-hop collective-permute payload bytes from the compiled HLO.
        # pipelined=False: the serialized differential control for the
        # pipelined proxy below (same program as before the knob existed,
        # plus its overlap-evidence fields).  The written efficiency budget
        # lives in docs/performance.md.
        return _bench.aot_weak_proxy(emit=False, pipelined=False)

    def _weak_aot_proxy_pipelined():
        # ISSUE 2 acceptance (CPU-only environments): the pipelined cadence
        # at the north-star topology — the HLO must show interior kernel
        # launches schedulable across the group-boundary collective-permutes
        # (overlap_evidence.independent_pairs > 0) with per-hop payloads
        # unchanged vs the serialized control.
        return _bench.aot_weak_proxy(emit=False, pipelined=True)

    _extra("weak_scaling_codepath", _weak_codepath)
    _extra("weak_scaling_aot_proxy_256chip", _weak_aot_proxy)
    _extra("weak_scaling_aot_proxy_256chip_pipelined", _weak_aot_proxy_pipelined)
    # ISSUE 8: ensemble batching — members/s/chip B-sweep (gated metrics)
    # + the B=8-vs-B=1 compiled collective-count A/B.
    _extra("batch_ensemble", _batch_extra)
    _extra("batch_hlo_ab", _batch_hlo_extra)
    # ISSUE 12: the front-door serving record (gated rounds/s + inverse
    # submit→result latencies; see _frontdoor_serving_record).
    _extra("frontdoor_serving", _frontdoor_serving_record)
    # ISSUE 19: one traced request's critical-path decomposition — the
    # reported *_share perf-gate keys (see _request_trace_record).
    _extra("request_trace", _request_trace_record)

    def _profile_attribution():
        # ISSUE 15: the measured device-timeline record — a windowed
        # profiler capture on the virtual CPU mesh's communicating grid
        # (`benchmarks/run.py profile` -> utils/profiling), parsed into
        # per-scope device seconds and the measured comm/compute overlap
        # fraction.  ``overlap_fraction`` is a REPORTED perf-gate key
        # (analysis.perf.REPORTED_KEYS) — the trajectory a future gate
        # regresses against, same on-ramp achieved_fraction took.
        rec = _cpu_mesh_json(["profile"])
        rec["note"] = (
            "virtual 8-device CPU mesh: scope seconds are code-path "
            "records; the overlap fraction is the measured "
            "union-intersection of the capture's collective vs kernel "
            "intervals (see scripts/igg_prof.py)"
        )
        return rec

    _extra("profile_attribution", _profile_attribution)

    def _efficiency():
        # ISSUE 10: the cost-model reconciliation — achieved-vs-modeled
        # traffic per model (analysis/reconcile.py, compiled fresh on the
        # virtual CPU mesh), joined with THIS record's measured teffs:
        # measured_teff / achieved_fraction = the modeled GB/s the chip
        # actually sustained.  efficiency.*.achieved_fraction is a
        # reported (not yet gated) perf-gate key (analysis.perf).  Since
        # ISSUE 15 the measured overlap fraction (extras.
        # profile_attribution) rides the same report as a per-model
        # measured-overlap column.
        from implicitglobalgrid_tpu.analysis.reconcile import join_measured

        report = _cpu_mesh_json(["reconcile"])
        measured = {
            "diffusion": extras.get("diffusion_xla", {}).get("teff"),
            "acoustic": extras.get("acoustic", {}).get("teff"),
            "porous": extras.get("porous_pt", {}).get("teff"),
        }
        frac = extras.get("profile_attribution", {}).get("overlap_fraction")
        overlap = {"diffusion": frac} if frac is not None else None
        return join_measured(report, measured, measured_overlap=overlap)

    _extra("efficiency", _efficiency)
    # The observability surface is the record of record now: every bench
    # above folded its measurement into the process registry (`_emit`), so
    # the snapshot ships in the artifact instead of a private tally
    # (docs/observability.md) — since ISSUE 10 with the host-span summary
    # alongside.
    try:
        import implicitglobalgrid_tpu as igg
        from implicitglobalgrid_tpu.utils.liveplane import get_engine, slo_view
        from implicitglobalgrid_tpu.utils.tracing import span_summary

        snap = igg.telemetry_snapshot()
        extras["telemetry"] = {
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": snap["histograms"],
            "spans": span_summary(),
            # ISSUE 11: the live-plane view of the same registry — the
            # rolling-window quantiles (what /healthz would have served at
            # the end of the round) and any anomaly alerts the run fired.
            "slo_windows": slo_view(snap),
            "alerts": get_engine().recent_alerts(),
        }
    except Exception as e:  # never let instrumentation sink the artifact
        extras["telemetry"] = {"error": f"{type(e).__name__}: {e}"}

    best = rec["value"]
    extras["headline_path"] = "xla"
    fused = extras.get("diffusion_pallas_fused4", {})
    # The headline is the faster production path whatever it was (the fused
    # config may itself have auto-fallen-back to the XLA cadence); the
    # recorded path makes the provenance unambiguous (advisor round 2).
    if fused.get("teff", 0.0) > best:
        best = fused["teff"]
        extras["headline_path"] = (
            "pallas_fused4" if fused.get("path") == "pallas-fused"
            else "xla_fallback_cadence"
        )
    # Perf-regression verdict vs the newest committed BENCH round
    # (docs/performance.md, perf-regression gate): the fresh record carries
    # its own gate result so the driver (and scripts/check_perf.py) can
    # refuse to commit a regressed artifact.
    try:
        from implicitglobalgrid_tpu.analysis.perf import gate_summary

        extras["perf_gate"] = gate_summary(
            {"value": best, "extras": extras},
            os.path.dirname(os.path.abspath(__file__)),
        )
    except Exception as e:  # never let the gate sink the artifact
        extras["perf_gate"] = {"error": f"{type(e).__name__}: {e}"}
    record = {
        "metric": "diffusion3d_256_float32_teff",
        "value": best,
        "unit": "GB/s/chip",
        "vs_baseline": round(best / BASELINE_TEFF_GBS, 3),
        "extras": extras,
    }
    print(json.dumps(record))
    if out:
        _write_round_record(record, out)


if __name__ == "__main__":
    import sys

    argv = sys.argv[1:]
    out = "auto"
    if "--no-record" in argv:
        argv.remove("--no-record")
        out = None
    if "--out" in argv:
        i = argv.index("--out")
        try:
            out = argv[i + 1]
        except IndexError:
            raise SystemExit("--out needs a path argument")
        del argv[i:i + 2]
    if argv and argv[0] == "batch":
        main_batch()
    elif argv:
        raise SystemExit(
            f"unknown mode {argv[0]!r}: bench.py [batch] [--out PATH] "
            f"[--no-record] (no mode = the full headline record)"
        )
    else:
        main(out=out)
