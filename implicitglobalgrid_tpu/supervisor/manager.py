"""`RunSupervisor`: launch and own a multi-process run end to end.

The orchestration loop of the subsystem (docs/robustness.md, "self-healing
supervisor"): spawn the ranks of one *incarnation*, watch them — process
liveness plus each rank's liveplane ``/healthz`` endpoint (discovered via
the ``liveplane.p<rank>.json`` endpoint files) — collect the evidence
(flight bundles, latched ``alert.*`` events, checkpoint-integrity events),
classify what failed (`supervisor.classify`), ask the policy engine what
to do (`supervisor.policy.decide`), and execute: fence the superseded
generation (`supervisor.generation.publish_generation` BEFORE the kill —
a zombie that outlives its SIGKILL is refused at every publish path), then
relaunch in place, shrink a rung, scale back up, or give up.  Each
transition lands as ``supervisor.detect`` → ``supervisor.classify`` →
``supervisor.recover`` events in the shared telemetry dir, so the recovery
timeline is machine-verifiable next to the workers' own events (the soak
``chaos`` drill asserts exactly that order).

Fault-spec hygiene across incarnations: the supervisor owns the
``IGG_FAULT_INJECT`` spec (including ``chaos:`` expansion,
`utils.resilience.chaos_schedule`) and prunes faults that already FIRED —
matched against the workers' ``fault.*`` events — from the next
incarnation's environment, extending the injector's fire-once semantics
across restarts (a crash at step N must not re-crash the incarnation that
resumes from the step-N checkpoint).

This module runs strictly host-side: subprocesses, files, HTTP scrapes —
never jax, never a collective (the supervisor must keep deciding while
the fabric it supervises is wedged).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import os
import subprocess
import time
import urllib.request
from typing import Callable, Sequence

from ..utils import config as _config
from ..utils import telemetry as _telemetry
from ..utils import tracing as _tracing
# NOTE: the package __init__ re-exports the `classify` FUNCTION under the
# same name as its module, so names must be imported from the module by
# its dotted path, never via a package attribute.
from .classify import RESIZE_STATUS as _RESIZE_STATUS
from .classify import classify as _classify_incident
from .classify import collect_evidence as _collect_evidence
from . import generation as _generation
from . import policy as _policy

__all__ = [
    "Incarnation",
    "RunSupervisor",
    "SupervisorReport",
]

DEFAULT_POLL_S = 0.5
#: grace given to surviving ranks after a peer died before they are reaped
DEFAULT_GRACE_S = 20.0


@dataclasses.dataclass
class Incarnation:
    """One generation's live processes (+ their logs and endpoints)."""

    generation: int
    rung: int
    nranks: int
    procs: list
    log_paths: list
    t0: float
    endpoints: dict = dataclasses.field(default_factory=dict)
    observations: list = dataclasses.field(default_factory=list)

    def poll(self) -> list:
        return [p.poll() for p in self.procs]

    def alive(self) -> bool:
        return any(rc is None for rc in self.poll())

    def kill(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass


@dataclasses.dataclass
class SupervisorReport:
    """What one supervised run did, incident by incident."""

    ok: bool
    reason: str
    incidents: list
    generations: int
    final_rung: int
    quarantined: tuple

    def summary(self) -> str:
        legs = ",".join(
            i["decision"]["action"] for i in self.incidents
        ) or "clean"
        return (
            f"{'OK' if self.ok else 'FAILED'} after "
            f"{self.generations + 1} incarnation(s) [{legs}] "
            f"({self.reason})"
        )


def _scrape_health(host: str, port: int, timeout: float = 2.0) -> dict | None:
    try:
        with urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=timeout
        ) as r:
            return json.loads(r.read().decode())
    except (OSError, ValueError):
        return None


class RunSupervisor:
    """Failure-domain manager for one multi-process run (module docstring).

    ``command_for(rank, nranks, rung, generation)`` — argv of one rank of
    one incarnation (the supervisor adds the generation/fence/telemetry/
    fault environment).  ``ladder`` — process count per rung, rung 0 the
    preferred (largest) topology; shrink walks down the list.  ``workdir``
    — logs + the fence file; ``telemetry_dir`` — the shared evidence dir
    the workers write (armed in their env).  ``fault_spec`` — the
    ``IGG_FAULT_INJECT`` value the FIRST incarnation runs under (chaos
    specs expand; fired faults are pruned per relaunch).  ``env`` — extra
    child environment.  ``drive`` — optional per-incarnation callable
    ``(incarnation) -> None`` run after spawn (a load generator); when
    given, the supervisor's own health polling is skipped while it runs.
    ``on_resize(plan) -> rung`` — maps a workload-published ``resize.json``
    onto the ladder (required to supervise a front door).
    """

    def __init__(
        self,
        command_for: Callable[[int, int, int, int], Sequence[str]],
        *,
        ladder: Sequence[int],
        workdir: str,
        telemetry_dir: str,
        policy: "_policy.RecoveryPolicy | None" = None,
        fault_spec: str | None = None,
        env: dict | None = None,
        drive: Callable | None = None,
        on_resize: Callable[[dict], int] | None = None,
        resize_plan_path: str | None = None,
        initial_rung: int = 0,
        preferred_rung: int = 0,
        poll_s: float | None = None,
        grace_s: float = DEFAULT_GRACE_S,
        name: str = "run",
    ):
        if not ladder or any(int(n) < 1 for n in ladder):
            raise ValueError(f"ladder must be >= 1 process per rung: {ladder}")
        if not 0 <= initial_rung < len(ladder):
            raise ValueError(
                f"initial_rung {initial_rung} outside the ladder ({ladder})"
            )
        self.command_for = command_for
        self.ladder = [int(n) for n in ladder]
        self.workdir = os.fspath(workdir)
        self.telemetry_dir = os.fspath(telemetry_dir)
        self.policy = (
            policy if policy is not None else _policy.RecoveryPolicy.from_env()
        )
        self.env = dict(env or {})
        self.drive = drive
        self.on_resize = on_resize
        self.resize_plan_path = resize_plan_path
        self.preferred_rung = preferred_rung
        env_poll = _config.supervise_poll_env()
        self.poll_s = (
            poll_s if poll_s is not None
            else (env_poll if env_poll is not None else DEFAULT_POLL_S)
        )
        self.grace_s = grace_s
        self.name = name
        self.state = _policy.SupervisorState(rung=initial_rung)
        # the armed fault schedule, pruned of fired faults per relaunch
        from ..utils import resilience as _resilience

        self._fault_specs = list(_resilience.expand_fault_spec(fault_spec))
        # per-file byte offsets for incremental evidence reads: each
        # incident parses only the lines appended since the last one
        self._evidence_offsets: dict = {}

    # - events (the supervisor's own timeline) -

    def _event(self, etype: str, **payload) -> None:
        _telemetry.event(etype, supervisor=self.name, **payload)

    # - launch -

    def _child_env(self) -> dict:
        env = dict(os.environ)
        env.pop("IGG_FAULT_INJECT", None)
        env.update(self.env)
        env["IGG_TELEMETRY"] = env.get("IGG_TELEMETRY", "1")
        env["IGG_TELEMETRY_DIR"] = self.telemetry_dir
        env["IGG_GENERATION"] = str(self.state.generation)
        env["IGG_FENCE_DIR"] = self.workdir
        if self._fault_specs:
            env["IGG_FAULT_INJECT"] = ",".join(self._fault_specs)
        return env

    def launch(self) -> Incarnation:
        """Spawn one incarnation at the current rung/generation (fence
        published first: the authoritative token always leads the procs
        that carry it).  Spawning runs under an ``igg.supervisor.launch``
        span: a request context active across a restart (the serving
        resize path) ties the relaunch into the affected requests'
        causal trees."""
        gen, rung = self.state.generation, self.state.rung
        nranks = self.ladder[rung]
        with _tracing.trace_span("igg.supervisor.launch", generation=gen,
                                 rung=rung, nranks=nranks):
            _generation.publish_generation(
                gen, self.workdir, rung=rung, nranks=nranks
            )
            os.makedirs(self.workdir, exist_ok=True)
            env = self._child_env()
            procs, logs = [], []
            t0 = time.time()
            for rank in range(nranks):
                log_path = os.path.join(
                    self.workdir, f"{self.name}_g{gen}_r{rank}.log"
                )
                logs.append(log_path)
                f = open(log_path, "w")
                try:
                    procs.append(subprocess.Popen(
                        list(self.command_for(rank, nranks, rung, gen)),
                        env=env, stdout=f, stderr=subprocess.STDOUT, text=True,
                    ))
                finally:
                    f.close()  # the child holds its own descriptor
            inc = Incarnation(
                generation=gen, rung=rung, nranks=nranks, procs=procs,
                log_paths=logs, t0=t0,
            )
        self._event(
            "supervisor.launch", generation=gen, rung=rung, nranks=nranks,
            faults=list(self._fault_specs),
        )
        return inc

    # - monitoring -

    def _discover_endpoints(self, inc: Incarnation) -> None:
        for path in _glob.glob(
            os.path.join(self.telemetry_dir, "liveplane.p*.json")
        ):
            try:
                with open(path) as f:
                    doc = json.load(f)
                if float(doc.get("ts") or 0) < inc.t0:
                    continue  # a previous incarnation's endpoint file
                inc.endpoints[int(doc["rank"])] = (doc["host"], doc["port"])
            except (OSError, ValueError, KeyError, TypeError):
                continue

    def _health_pass(self, inc: Incarnation) -> None:
        """One scrape sweep: live CRITICAL alerts become ``supervisor.detect``
        observations (once per (rule, rank) per incarnation)."""
        self._discover_endpoints(inc)
        for rank, (host, port) in sorted(inc.endpoints.items()):
            doc = _scrape_health(host, port)
            if not doc:
                continue
            for alert in doc.get("alerts", {}).get("active", []):
                key = (alert.get("rule"), rank)
                if key in {(o["rule"], o["rank"]) for o in inc.observations}:
                    continue
                obs = {
                    "rule": alert.get("rule"),
                    "severity": alert.get("severity"),
                    "rank": rank,
                    "source": "healthz",
                    "evidence": alert.get("evidence"),
                }
                inc.observations.append(obs)
                self._event(
                    "supervisor.detect", generation=inc.generation, **obs
                )

    def monitor(self, inc: Incarnation, timeout: float) -> list:
        """Watch one incarnation until every rank exited: liveness polling
        + liveplane health scrapes.  A rank dying puts the survivors on a
        grace clock (they are stranded mid-collective) before the reap.
        Returns the per-rank exit statuses (None = killed while running).
        """
        deadline = time.monotonic() + timeout
        first_death: float | None = None
        while True:
            rcs = inc.poll()
            if all(rc is not None for rc in rcs):
                return rcs
            now = time.monotonic()
            # RESIZE_STATUS is a clean, REQUESTED exit (the workload asked
            # for a new topology): it must not start the grace clock —
            # SIGKILLing the peers mid-resize-teardown would turn the
            # resize into a phantom crash and orphan the published plan.
            bad = [
                rc for rc in rcs if rc not in (None, 0, _RESIZE_STATUS)
            ]
            if bad and first_death is None:
                first_death = now
                self._event(
                    "supervisor.detect", generation=inc.generation,
                    source="liveness", rcs=rcs,
                )
            if first_death is not None and now - first_death > self.grace_s:
                inc.kill()
                return inc.poll()
            if now > deadline:
                self._event(
                    "supervisor.detect", generation=inc.generation,
                    source="timeout", rcs=rcs,
                )
                inc.kill()
                return inc.poll()
            if self.drive is None:
                self._health_pass(inc)
            time.sleep(self.poll_s)

    # - fault hygiene -

    def _prune_fired_faults(self, evidence: dict, since_ts: float) -> None:
        """Drop faults whose ``fault.*`` event is on this incarnation's
        timeline.  Reads the ALREADY-collected evidence (one JSONL parse
        per incident, shared with classification) — the event history
        grows with every incarnation, so re-scanning it here would double
        an unbounded cost."""
        from ..utils import resilience as _resilience

        if not self._fault_specs:
            return
        fired = [
            e for e in evidence.get("events", [])
            if str(e.get("type", "")).startswith("fault.")
            and float(e.get("ts") or 0) >= since_ts
        ]
        remaining = [
            spec for spec in self._fault_specs
            if not _resilience.fault_event_matches_spec(fired, spec)
        ]
        if remaining != self._fault_specs:
            self._event(
                "supervisor.faults_pruned",
                fired=[s for s in self._fault_specs if s not in remaining],
                remaining=remaining,
            )
            self._fault_specs = remaining

    # - the loop -

    def run(self, *, timeout: float = 600.0,
            max_incarnations: int = 16) -> SupervisorReport:
        """Drive the run to completion (module docstring).  ``timeout`` is
        per incarnation; ``max_incarnations`` bounds the whole loop (a
        backstop far above any sane recovery sequence)."""
        incidents: list = []
        prev_dir = os.environ.get("IGG_TELEMETRY_DIR")
        os.environ["IGG_TELEMETRY_DIR"] = self.telemetry_dir
        try:
            return self._run(timeout, max_incarnations, incidents)
        finally:
            if prev_dir is None:
                os.environ.pop("IGG_TELEMETRY_DIR", None)
            else:
                os.environ["IGG_TELEMETRY_DIR"] = prev_dir

    def _run(self, timeout, max_incarnations, incidents) -> SupervisorReport:
        for _ in range(max_incarnations):
            inc = self.launch()
            if self.drive is not None:
                try:
                    self.drive(inc)
                except Exception as e:
                    inc.kill()
                    return self._report(
                        False, f"drive hook failed: {e!r}", incidents
                    )
            rcs = self.monitor(inc, timeout)
            # the reap-time detection marker: whatever the liveness/health
            # polling saw mid-flight, the timeline ALWAYS carries detect →
            # classify → recover in order for every incident
            self._event(
                "supervisor.detect", generation=inc.generation,
                source="exit", rcs=list(rcs),
            )
            evidence = _collect_evidence(
                self.telemetry_dir, offsets=self._evidence_offsets
            )
            incident = _classify_incident(rcs, evidence, since_ts=inc.t0)
            # fold the incident into the strike bookkeeping BEFORE the
            # decision (integrity failures accumulate toward quarantine)
            self.state.record_incident(incident)
            # observations the health scrapes made while the loop was
            # still wedged ride into the record (the classifier already
            # sees their event-log twins)
            self._event(
                "supervisor.classify", generation=inc.generation,
                kind=incident.kind, ranks=list(incident.ranks),
                rcs=list(rcs), detail=incident.detail,
            )
            decision = _policy.decide(
                incident, self.state, self.policy,
                ladder_len=len(self.ladder),
                preferred_rung=self.preferred_rung,
            )
            if incident.kind == "resize":
                decision = self._resize_decision(decision)
                if decision is None:
                    return self._report(
                        False, "resize exit without a readable plan",
                        incidents,
                    )
            incidents.append({
                "generation": inc.generation,
                "rung": inc.rung,
                "kind": incident.kind,
                "rcs": list(rcs),
                "detail": incident.detail,
                "observations": list(inc.observations),
                "decision": {
                    "action": decision.action,
                    "rung": decision.rung,
                    "reason": decision.reason,
                },
            })
            self._event(
                "supervisor.recover", generation=inc.generation,
                action=decision.action, rung=decision.rung,
                reason=decision.reason,
                quarantined=list(decision.quarantined),
            )
            if decision.action == "none":
                return self._report(True, "run completed", incidents)
            if decision.action == "give_up":
                # the terminal verdict's quarantine still lands in the
                # state so the report / supervisor.done name the bad ranks
                self.state.quarantined.update(decision.quarantined)
                return self._report(False, decision.reason, incidents)
            if decision.action == "scale_up" and incident.kind == "healthy":
                # a bounded job that finished healthy has nothing left to
                # scale for; a service workload signals growth via resize
                return self._report(True, "run completed", incidents)
            if decision.delay_s:
                time.sleep(decision.delay_s)
            self.state.apply(decision)
            # Fence FIRST, then reap: a zombie that survives the kill is
            # refused at every publish path by the already-moved token.
            _generation.publish_generation(
                self.state.generation, self.workdir,
                rung=self.state.rung, reason=decision.action,
            )
            inc.kill()
            self._prune_fired_faults(evidence, inc.t0)
        return self._report(
            False, f"gave up after {max_incarnations} incarnations",
            incidents,
        )

    def _resize_decision(self, decision) -> "_policy.Decision | None":
        """Resolve a workload-requested resize into a concrete next rung
        via the ``resize.json`` plan + the ``on_resize`` mapping."""
        plan_path = self.resize_plan_path
        if plan_path is None or self.on_resize is None:
            return None
        try:
            with open(plan_path) as f:
                plan = json.load(f)
            os.remove(plan_path)
            rung = int(self.on_resize(plan))
        except (OSError, ValueError, TypeError, KeyError):
            # KeyError included: on_resize callbacks index the plan's
            # fields directly — a plan missing one must become the
            # designed failure report, not a traceback out of run()
            return None
        if not 0 <= rung < len(self.ladder):
            return None
        return dataclasses.replace(
            decision, rung=rung,
            reason=f"workload resize plan -> rung {rung} "
                   f"({plan.get('reason')})",
        )

    def _report(self, ok: bool, reason: str, incidents) -> SupervisorReport:
        report = SupervisorReport(
            ok=ok,
            reason=reason,
            incidents=incidents,
            generations=self.state.generation,
            final_rung=self.state.rung,
            quarantined=tuple(sorted(self.state.quarantined)),
        )
        self._event(
            "supervisor.done", ok=ok, reason=reason,
            generations=report.generations,
            quarantined=list(report.quarantined),
        )
        return report
