"""Recovery policy: classified incident -> supervisor action.

The decide step of the supervisor state machine (detect → classify →
**policy** → fence; docs/robustness.md).  Split the `serving.autoscale`
way: `decide` is a PURE function of ``(incident, state, policy)`` —
deterministic, clock-free, pinned by synthetic-incident tests — and
`SupervisorState` is the bookkeeping shell (per-rank strike counts,
quarantine set, generation counter) `RunSupervisor` owns.

Actions (`ACTIONS`):

``restart``    relaunch the failed incarnation in place at the same
               topology, after the next `utils.resilience.backoff_schedule`
               delay — transient faults (a crash, a wedged loop) get
               ``IGG_SUPERVISE_MAX_RESTARTS`` strikes before escalation.
``shrink``     strikes exhausted (or a rank quarantined): drop to the next
               rung down the topology ladder and relaunch — the restart
               rides `restore_checkpoint`'s elastic resharding path, so
               the shrunk incarnation resumes the same physical run.
``scale_up``   the run is healthy below its preferred rung and spare
               capacity returned: move one rung up (again through the
               elastic checkpoint path).
``resize``     the workload itself asked (`serving.RESIZE_STATUS` + plan).
``quarantine`` the implicated rank keeps producing integrity failures
               (corrupt checkpoints) or tripwire faults: pin it out of
               every future incarnation and shrink around it.  A
               ``silent_corruption`` incident skips the strike bar and
               quarantines on the FIRST offense — restarting a rank whose
               silicon produced a finite wrong value hands it fresh state
               to corrupt.
``none``       healthy — nothing to do.
``give_up``    no rung fits (everything quarantined / ladder exhausted).

`recovery_plan` additionally states, per supervised RANK, the ordered
host-transport collective schedule that applying one in-band recovery
directive implies — the contract the ``collective-consistency`` analyzer
censuses per simulated rank (`analysis.collectives.supervisor_plan_censuses`):
a recovery decision keyed on rank identity or rank-local fence state is
the `_gather_chunked` deadlock class wearing a supervisor hat, and the
census catches it statically.
"""

from __future__ import annotations

import dataclasses

from ..utils import config as _config

__all__ = [
    "ACTIONS",
    "Decision",
    "RecoveryPolicy",
    "SupervisorState",
    "decide",
    "recovery_plan",
]

ACTIONS = (
    "none",
    "restart",
    "shrink",
    "scale_up",
    "resize",
    "quarantine",
    "give_up",
)

#: incident kinds that consume a restart strike (transient-looking faults)
_TRANSIENT = ("crash", "step_stall", "guard_trip", "straggler")
#: incident kinds that mark the implicated rank suspect (integrity class).
#: ``silent_corruption`` is in the suspect family for strike bookkeeping,
#: but `decide` short-circuits it to IMMEDIATE quarantine: the other
#: suspect kinds tolerate strikes because their damage is at rest and the
#: checkpoint fallback routes around it, while a rank whose silicon
#: produced a finite wrong value re-lies on restart — restart-in-place is
#: exactly the wrong verdict for a liar.
_SUSPECT = ("corrupt_checkpoint", "gather_tripwire", "silent_corruption")

DEFAULT_MAX_RESTARTS = 2


@dataclasses.dataclass(frozen=True)
class Decision:
    """One policy verdict: what to do, where to land, and why."""

    action: str
    #: topology-ladder rung index the next incarnation launches at
    rung: int
    #: backoff delay before the relaunch (seconds; 0 for none/resize)
    delay_s: float
    reason: str
    quarantined: tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """The knobs of `decide` (kwarg > supervise env tier > default).

    ``max_restarts`` — in-place restarts per CONTINUOUS failure streak
    before the ladder drops a rung; ``backoff_s`` — base of the
    exponential relaunch backoff (`utils.resilience.backoff_schedule`
    semantics: delay i = min(base * 2**i, 30), deterministic under
    ``seed``); ``quarantine_after`` — suspect incidents implicating one
    rank before it is pinned out; ``scale_up_after`` — consecutive
    healthy-at-reduced-rung incarnations before a spare-return reattempt.
    """

    max_restarts: int = DEFAULT_MAX_RESTARTS
    backoff_s: float = 0.5
    quarantine_after: int = 2
    scale_up_after: int = 1
    seed: int = 0

    @classmethod
    def from_env(cls, **kw) -> "RecoveryPolicy":
        kw.setdefault("max_restarts", _config.supervise_max_restarts_env())
        kw.setdefault("backoff_s", _config.supervise_backoff_env())
        return cls(**{k: v for k, v in kw.items() if v is not None})

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0 (got {self.max_restarts})"
            )
        if self.backoff_s <= 0:
            raise ValueError(f"backoff_s must be > 0 (got {self.backoff_s})")
        if self.quarantine_after < 1 or self.scale_up_after < 1:
            raise ValueError(
                "quarantine_after and scale_up_after must be >= 1"
            )


@dataclasses.dataclass
class SupervisorState:
    """Mutable bookkeeping across incarnations (owned by `RunSupervisor`)."""

    rung: int = 0
    generation: int = 0
    #: in-place restarts consumed during the CURRENT failure streak
    restarts: int = 0
    #: suspect-incident count per implicated rank
    suspect_strikes: dict = dataclasses.field(default_factory=dict)
    quarantined: set = dataclasses.field(default_factory=set)
    #: consecutive healthy incarnations at a rung below the preferred one
    healthy_streak: int = 0

    def record_incident(self, incident) -> None:
        """Fold one classified incident into the bookkeeping BEFORE the
        decision: suspect kinds (integrity failures) charge a strike
        against every implicated rank — the counter `decide`'s quarantine
        bar reads.  Called by `RunSupervisor` right after classification;
        without it quarantine could never trigger (a fresh count per
        decision would always read 1)."""
        if incident.kind in _SUSPECT:
            for rank in incident.ranks:
                self.suspect_strikes[rank] = (
                    self.suspect_strikes.get(rank, 0) + 1
                )

    def apply(self, decision: Decision) -> None:
        """Advance the bookkeeping for an executed decision."""
        if decision.action in ("none",):
            self.restarts = 0
            self.healthy_streak += 1
            return
        self.generation += 1
        self.healthy_streak = 0
        if decision.action == "restart":
            self.restarts += 1
        else:
            self.restarts = 0
        self.rung = decision.rung
        self.quarantined.update(decision.quarantined)


def _backoff(policy: RecoveryPolicy, attempt: int) -> float:
    from ..utils.resilience import backoff_schedule

    sched = backoff_schedule(
        attempt + 1, base_s=policy.backoff_s, seed=policy.seed
    )
    return sched[attempt]


def decide(incident, state: SupervisorState, policy: RecoveryPolicy,
           *, ladder_len: int, preferred_rung: int = 0) -> Decision:
    """PURE verdict for one classified incident (module docstring).

    ``ladder_len`` — rungs available (rung 0 = the preferred/full
    topology, higher = smaller); ``preferred_rung`` — where scale-up
    reattempts aim.  Same inputs, same decision — no clocks, no globals.
    """
    if ladder_len < 1:
        raise ValueError("ladder_len must be >= 1")
    if incident.kind == "healthy":
        if (
            state.rung > preferred_rung
            and state.healthy_streak + 1 >= policy.scale_up_after
        ):
            return Decision(
                action="scale_up", rung=state.rung - 1, delay_s=0.0,
                reason=(
                    f"healthy x{state.healthy_streak + 1} below the "
                    f"preferred rung: reattempting rung {state.rung - 1}"
                ),
            )
        return Decision(action="none", rung=state.rung, delay_s=0.0,
                        reason="healthy")
    if incident.kind == "resize":
        return Decision(action="resize", rung=state.rung, delay_s=0.0,
                        reason="workload-requested resize")

    if incident.kind == "silent_corruption" and incident.ranks:
        # No strike bar: one proven finite wrong value is enough.  The
        # detector (transport checksum / shadow audit / lineage chain)
        # already localized the liar; giving it `quarantine_after` more
        # incarnations just feeds it more state to corrupt.
        doomed = tuple(incident.ranks)
        rung = state.rung + 1
        detector = (incident.detail or {}).get("detector", "integrity")
        if rung >= ladder_len:
            return Decision(
                action="give_up", rung=state.rung, delay_s=0.0,
                reason=(
                    f"rank(s) {doomed} caught corrupting data in flight "
                    f"({detector}) but no smaller rung exists"
                ),
                quarantined=doomed,
            )
        return Decision(
            action="quarantine", rung=rung, delay_s=_backoff(policy, 0),
            reason=(
                f"rank(s) {doomed} caught corrupting data in flight "
                f"({detector}): quarantined immediately, shrinking to "
                f"rung {rung}"
            ),
            quarantined=doomed,
        )

    if incident.kind in _SUSPECT:
        # strike counts maintained by `SupervisorState.record_incident`
        # (called before each decision), so repeated integrity failures
        # accumulate across incarnations
        doomed = tuple(
            r for r in incident.ranks
            if state.suspect_strikes.get(r, 0) >= policy.quarantine_after
        )
        if doomed:
            rung = state.rung + 1
            if rung >= ladder_len:
                return Decision(
                    action="give_up", rung=state.rung, delay_s=0.0,
                    reason=(
                        f"rank(s) {doomed} quarantined "
                        f"({incident.kind}) but no smaller rung exists"
                    ),
                    quarantined=doomed,
                )
            return Decision(
                action="quarantine", rung=rung,
                delay_s=_backoff(policy, 0),
                reason=(
                    f"rank(s) {doomed} failed integrity "
                    f"{policy.quarantine_after}x ({incident.kind}): "
                    f"quarantined, shrinking to rung {rung}"
                ),
                quarantined=doomed,
            )
        # suspect but under the quarantine bar: restart in place (the
        # integrity fallback already routed around the damage), counting
        # a restart strike like any transient
        if state.restarts < policy.max_restarts:
            return Decision(
                action="restart", rung=state.rung,
                delay_s=_backoff(policy, state.restarts),
                reason=(
                    f"{incident.kind} on rank(s) {incident.ranks}: restart "
                    f"{state.restarts + 1}/{policy.max_restarts} "
                    f"(integrity fallback handles the damaged generation)"
                ),
            )

    if incident.kind in _TRANSIENT and state.restarts < policy.max_restarts:
        return Decision(
            action="restart", rung=state.rung,
            delay_s=_backoff(policy, state.restarts),
            reason=(
                f"{incident.kind} on rank(s) {incident.ranks}: restart "
                f"in place {state.restarts + 1}/{policy.max_restarts}"
            ),
        )

    # strikes exhausted (or an un-enumerated kind): walk down the ladder
    rung = state.rung + 1
    if rung >= ladder_len:
        return Decision(
            action="give_up", rung=state.rung, delay_s=0.0,
            reason=(
                f"{incident.kind}: {state.restarts} restart(s) exhausted "
                f"and no smaller rung exists"
            ),
        )
    return Decision(
        action="shrink", rung=rung, delay_s=_backoff(policy, 0),
        reason=(
            f"{incident.kind}: {state.restarts} in-place restart(s) "
            f"exhausted (IGG_SUPERVISE_MAX_RESTARTS="
            f"{policy.max_restarts}); elastic shrink to rung {rung}"
        ),
    )


# -- the in-band control plan (analyzer contract) -----------------------------


def recovery_plan(is_root: bool, action: str, stale: bool) -> tuple:
    """The ordered host-transport collective schedule ONE SUPERVISED RANK
    follows when a recovery directive lands in-band.

    ``is_root`` exists precisely so the ``collective-consistency`` census
    can prove the schedule ignores rank identity (the
    `ops.gather.collective_plan` / `tuning.search.control_plan` contract).
    ``stale`` is the fence verdict — rank-uniform by construction
    (`supervisor.generation.fence_refusal`: per-incarnation env token vs
    the shared fence file), so a superseded incarnation refuses the
    directive on EVERY rank together (empty plan) instead of some ranks
    entering the checkpoint barriers their peers skip.

    Schedules: ``resize``/``shrink``/``scale_up`` = the front-door resize
    execution (`serving.frontdoor.FrontDoor._execute_resize`): one
    control broadcast, then `save_checkpoint`'s two barriers; ``restart``
    = out-of-band (the supervisor kills and relaunches; the fresh
    incarnation's restore is per-process reads) — no collective;
    ``quarantine``/``give_up``/``none`` = no in-band work.
    """
    del is_root  # rank identity must not shape the schedule
    if stale:
        return ()  # fenced: every rank refuses the directive together
    if action in ("resize", "shrink", "scale_up"):
        return (
            ("broadcast_control", "directive"),
            ("save_checkpoint", "shard-barrier"),
            ("save_checkpoint", "publish-barrier"),
        )
    return ()
