"""Failure classification: exit evidence -> a named failure class.

The supervisor's detect step yields raw evidence — per-rank exit statuses,
crash flight bundles (``flight_<rank>.json``), latched ``alert.*`` events
and checkpoint-integrity events from the per-rank JSONL logs, live
``/healthz`` scrapes.  This module turns that evidence into ONE of a small
closed set of failure classes the policy engine can act on.  Everything
here is a pure function of already-collected data: no processes, no
collectives, no clocks — the tier-1 contract (`tests/test_supervisor.py`
pins the matrix with synthetic evidence).

Classes (`FAILURE_KINDS`):

``healthy``            every rank exited 0.
``resize``             every rank exited `serving.RESIZE_STATUS` — not a
                       failure: the pool asked its supervisor for a new
                       topology (the autoscaler contract).
``guard_trip``         a rank's flight bundle says the NaN/Inf guard
                       tripped (``guard.trip``) — numerical fault.
``gather_tripwire``    a rank's flight bundle carries
                       ``reason=gather_tripwire``: the deterministic
                       3-round gloo gather regression fired (ROADMAP watch
                       item) — a *transport* fault, distinct from a
                       generic crash, so it is escalated by name instead
                       of vanishing into one.
``corrupt_checkpoint`` integrity machinery engaged: ``checkpoint.fallback``
                       / ``checkpoint.verify_failed`` events next to a
                       failed rank — the newest generation is damaged.
``silent_corruption``  an integrity-plane detector (transport checksum or
                       shadow-step audit, the `integrity` package) caught
                       a FINITE wrong value in flight and dumped a
                       ``reason=sdc`` bundle.  The incident implicates the
                       rank the DETECTOR names (``info.implicated_rank`` —
                       the sender of a bad slab, not the receiver that
                       noticed), because that is whose silicon is lying;
                       policy must quarantine it, never restart-in-place.
``step_stall``         a latched ``alert.step_stall`` (live-plane rule) or
                       a watchdog flight bundle: the loop wedged.
``straggler``          ``skew.straggler`` / ``alert.skew_sustained``
                       evidence without a crash: slow, not dead.
``crash``              a rank died (nonzero exit) with no more specific
                       marker — includes the injected ``worker_crash``
                       (status 17), which carries its injection event as
                       detail.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
import re
from typing import Sequence

__all__ = [
    "FAILURE_KINDS",
    "Incident",
    "classify",
    "collect_evidence",
]

FAILURE_KINDS = (
    "healthy",
    "resize",
    "guard_trip",
    "gather_tripwire",
    "corrupt_checkpoint",
    "silent_corruption",
    "step_stall",
    "straggler",
    "crash",
)

from ..utils.resilience import FaultInjector as _FaultInjector

#: exit status of `utils.resilience.FaultInjector.maybe_crash` (canonical
#: definition imported — resilience is jax-free at module level)
CRASH_STATUS = _FaultInjector.CRASH_STATUS
#: exit status of `serving.frontdoor` after publishing a resize plan.  A
#: literal copy by necessity — importing the serving package would pull
#: the model zoo into this host-only module; the cross-module equality is
#: pinned by `tests/test_supervisor.py::test_exit_status_constants_agree`.
RESIZE_STATUS = 19

#: flight-bundle reasons mapped straight to a class (most-specific wins —
#: ``sdc`` first: an integrity trip often cascades into guard trips and
#: crashes on peer ranks, and the root cause must not vanish into those)
_BUNDLE_KINDS = (
    ("sdc", "silent_corruption"),
    ("gather_tripwire", "gather_tripwire"),
    ("guard.trip", "guard_trip"),
    ("watchdog.deadline_exceeded", "step_stall"),
)


@dataclasses.dataclass(frozen=True)
class Incident:
    """One classified failure: the policy engine's input."""

    kind: str
    #: ranks implicated (exit != 0, or named by the evidence)
    ranks: tuple[int, ...]
    #: per-rank exit statuses as observed (None = still running when killed)
    rcs: tuple[int | None, ...]
    #: free-form evidence trail (event types, bundle reasons, alert rules)
    detail: dict

    @property
    def failed(self) -> bool:
        return self.kind not in ("healthy", "resize")


def _read_jsonl_tail(path: str, offsets: dict | None) -> list[dict]:
    """Parse a JSONL file, resuming from ``offsets[path]`` when an offset
    map is given (the supervisor's incremental read: a shared telemetry
    directory accumulates every incarnation's history, and re-parsing it
    whole per incident would make evidence collection quadratic over a
    long run).  The offset only ever advances past COMPLETE lines, so a
    torn trailing line is re-read — never silently skipped — once its
    writer finishes it."""
    import json

    start = offsets.get(path, 0) if offsets is not None else 0
    try:
        with open(path, "rb") as f:
            f.seek(start)
            data = f.read()
    except OSError:
        return []
    end = data.rfind(b"\n")
    if end < 0:
        return []
    if offsets is not None:
        offsets[path] = start + end + 1
    out = []
    for line in data[: end + 1].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


def collect_evidence(telemetry_dir: str | None, *,
                     offsets: dict | None = None) -> dict:
    """Gather the on-disk evidence of one incarnation: flight bundles per
    rank and the latched ``alert.*`` / checkpoint-integrity / fault events
    from every per-rank JSONL log.  Tolerant of absence — a run without
    telemetry classifies on exit statuses alone.  ``offsets`` (a mutable
    ``{path: byte offset}`` the caller keeps across calls) switches to
    incremental reads: only lines appended since the previous collection
    are parsed — `RunSupervisor` passes its own map so per-incident cost
    tracks the incident, not the run's whole history."""
    evidence: dict = {"bundles": {}, "alerts": [], "events": []}
    if not telemetry_dir or not os.path.isdir(telemetry_dir):
        return evidence
    for path in sorted(_glob.glob(os.path.join(telemetry_dir, "flight_*.json"))):
        try:
            rank = int(os.path.basename(path)[len("flight_"):-len(".json")])
        except ValueError:
            continue
        bundles = _read_jsonl_tail(path, offsets)
        if bundles:
            evidence["bundles"][rank] = bundles
    for path in sorted(_glob.glob(os.path.join(telemetry_dir, "events*.jsonl"))):
        evidence["events"].extend(_read_jsonl_tail(path, offsets))
    evidence["alerts"] = [
        e for e in evidence["events"]
        if str(e.get("type", "")).startswith("alert.")
    ]
    return evidence


def _shard_ranks(ckpt_events: Sequence[dict]) -> tuple[int, ...]:
    """The WRITER ranks the integrity evidence names: `verify_checkpoint`
    problems spell the damaged shard file (``shards_pN.npz``), and shard N
    is written by rank N — the rank whose storage keeps corrupting, which
    is who quarantine must target (the exit-failed ranks may be innocent
    collateral of the ensuing recovery)."""
    ranks = set()
    for e in ckpt_events:
        for m in re.finditer(r"shards_p(\d+)\.npz", str(e.get("problem", ""))):
            ranks.add(int(m.group(1)))
    return tuple(sorted(ranks))


def _bundle_class(bundles: dict) -> tuple[str, int, str, dict] | None:
    """Most specific (kind, rank, reason, record) across every rank's
    bundles."""
    for reason, kind in _BUNDLE_KINDS:
        for rank, recs in sorted(bundles.items()):
            for rec in recs:
                if rec.get("reason") == reason:
                    return kind, rank, reason, rec
    return None


def classify(
    rcs: Sequence[int | None],
    evidence: dict | None = None,
    *,
    since_ts: float | None = None,
) -> Incident:
    """Classify one incarnation's outcome (module docstring).

    ``rcs`` — per-rank exit statuses in rank order.  ``evidence`` — a
    `collect_evidence` dict (optional).  ``since_ts`` — ignore evidence
    older than this wall-clock timestamp (a shared telemetry dir carries
    every incarnation's history; each classification must only read its
    own).  A failure class is only ever assigned when some rank FAILED
    (nonzero-non-resize exit, or killed while running); within failures the
    precedence is specific bundle reasons > checkpoint integrity >
    stall/straggler (all implicated ranks killed, never self-exited) >
    generic crash.  On a clean exit (every rank 0/RESIZE_STATUS), mid-run
    evidence of transient-and-recovered faults — a latched stall alert, a
    guard trip whose rollback succeeded — rides as detail: classifying it
    as a failure would restart a finished job.
    """
    rcs = tuple(rcs)
    evidence = evidence or {"bundles": {}, "alerts": [], "events": []}

    def fresh(recs):
        if since_ts is None:
            return list(recs)
        return [r for r in recs if float(r.get("ts") or 0) >= since_ts]

    bundles = {
        rank: fresh(recs)
        for rank, recs in evidence.get("bundles", {}).items()
        if fresh(recs)
    }
    events = fresh(evidence.get("events", []))
    alerts = fresh(evidence.get("alerts", []))
    failed_ranks = tuple(
        i for i, rc in enumerate(rcs) if rc not in (0, RESIZE_STATUS)
    )
    detail: dict = {}

    specific = _bundle_class(bundles)
    ckpt_events = [
        e for e in events
        if e.get("type") in ("checkpoint.fallback", "checkpoint.verify_failed")
    ]
    fault_events = sorted(
        {str(e["type"]) for e in events
         if str(e.get("type", "")).startswith("fault.")}
    )
    if fault_events:
        detail["faults"] = fault_events
    stall = [a for a in alerts if a.get("type") == "alert.step_stall"]
    skew = [
        a for a in alerts if a.get("type") == "alert.skew_sustained"
    ] + [e for e in events if e.get("type") == "skew.straggler"]

    if failed_ranks:
        # Suspect kinds implicate the rank the EVIDENCE names (the strike
        # bookkeeping / quarantine target), not whichever ranks happened
        # to exit badly — a corrupting rank can take innocent peers down
        # with it.  The exit picture stays visible through ``rcs``.
        if specific is not None:
            kind, rank, reason, rec = specific
            detail["bundle_reason"] = reason
            detail["bundle_rank"] = rank
            ranks = (rank,)
            if kind == "silent_corruption":
                # The bundle-writing rank is the DETECTING rank (a transport
                # checksum trips on the receiver); the corruption lives on
                # the rank the detector names.  Quarantine must target the
                # liar, not the witness.
                info = rec.get("info") or {}
                if info.get("detector"):
                    detail["detector"] = info["detector"]
                imp = info.get("implicated_rank")
                if imp is not None:
                    ranks = (int(imp),)
                    detail["implicated_rank"] = int(imp)
            return Incident(kind=kind, ranks=ranks, rcs=rcs,
                            detail=detail)
        if ckpt_events:
            detail["checkpoint_problems"] = [
                e.get("problem") for e in ckpt_events
            ][:4]
            ranks = _shard_ranks(ckpt_events) or failed_ranks
            return Incident(kind="corrupt_checkpoint", ranks=ranks,
                            rcs=rcs, detail=detail)
        if stall:
            detail["alert"] = "step_stall"
            detail["stall_ranks"] = sorted({a.get("rank") for a in stall})
        if any(rc == CRASH_STATUS for rc in rcs):
            detail["injected"] = True
        # Every failed rank was KILLED rather than dying on its own —
        # rc None (unreaped) or -9 (the supervisor's SIGKILL after grace/
        # timeout) — so the run wedged (stall evidence) or crawled into
        # the deadline (skew evidence); any other status is a real crash.
        all_killed = all(
            rc is None or rc == -9
            for i, rc in enumerate(rcs) if i in failed_ranks
        )
        if all_killed and stall:
            kind = "step_stall"
        elif all_killed and skew:
            detail["alert"] = "straggler"
            kind = "straggler"
        else:
            kind = "crash"
        return Incident(kind=kind, ranks=failed_ranks, rcs=rcs, detail=detail)

    # Every rank exited 0 or RESIZE_STATUS: the incarnation ENDED cleanly,
    # so mid-run evidence that something transient happened and RECOVERED —
    # a latched stall alert, a guard trip whose rollback succeeded, a blown
    # watchdog deadline the loop outlived — is detail, never a failure
    # class of its own (classifying it as one would restart a finished
    # job).
    if specific is not None:
        detail["bundle_reason"] = specific[2]
        detail["bundle_rank"] = specific[1]
    if stall:
        detail["transient_alerts"] = sorted(
            {str(a.get("type")) for a in stall}
        )
    if rcs and all(rc == RESIZE_STATUS for rc in rcs):
        return Incident(kind="resize", ranks=(), rcs=rcs, detail=detail)
    if any(rc != 0 for rc in rcs):
        # a mixed 0/RESIZE exit: the resize broadcast did not reach every
        # rank — treat as a crash of the resize-exiting ranks
        ranks = tuple(i for i, rc in enumerate(rcs) if rc != 0)
        detail["mixed_resize"] = True
        return Incident(kind="crash", ranks=ranks, rcs=rcs, detail=detail)
    return Incident(kind="healthy", ranks=(), rcs=rcs, detail=detail)
