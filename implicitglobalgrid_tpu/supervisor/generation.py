"""Generation tokens + fencing: the split-brain closure of the supervisor.

A supervised run is a sequence of *incarnations*: every (re)launch gets a
monotonically-increasing **generation token** (``IGG_GENERATION``, set by
`RunSupervisor` identically on every rank of the incarnation) and the
supervisor publishes the authoritative current token atomically as
``generation.json`` under the fence directory (``IGG_FENCE_DIR``, normally
the run's checkpoint/work directory).  The token is threaded through

* checkpoint meta (`utils.checkpoint.save_checkpoint` records it),
* telemetry event tags (every event line carries ``gen`` when set), and
* front-door control broadcasts (`serving.frontdoor` stamps and verifies).

**Fencing.**  A zombie rank — a process from a superseded incarnation that
a kill signal missed, or that woke from a stall after its replacement
launched — still believes it owns the run.  Every durable *publish* path
therefore checks the fence first: a process whose ``IGG_GENERATION`` is
older than the authoritative token is **refused** (`FenceError`), and the
refusal lands as a rank-tagged ``fence.rejected`` telemetry event plus the
``fence.rejected_total`` counter.  Fenced paths: `save_checkpoint`, the
front door's ``resize.json`` publish, and the liveplane/front-door
endpoint-file writes (advisory files: refused silently-but-evented via
`fence_refused` instead of raising out of a daemon thread).

The check is deliberately rank-uniform: every rank of one incarnation
carries the same token and reads the same fence file, so a fence decision
can never split an SPMD collective (the deadlock class
``analysis.collectives`` pins; see `supervisor.policy.recovery_plan`).
Unfenced runs (``IGG_GENERATION`` unset, the default) skip every check —
fencing is an opt-in contract between a supervisor and its children.
"""

from __future__ import annotations

import json
import os
import time

from ..utils import config as _config
from ..utils import telemetry as _telemetry

__all__ = [
    "FenceError",
    "GENERATION_FILE",
    "current_generation",
    "authoritative_generation",
    "publish_generation",
    "fence_refusal",
    "fence_refused",
    "check_fence",
]

#: the authoritative-token file the supervisor publishes under IGG_FENCE_DIR
GENERATION_FILE = "generation.json"


class FenceError(RuntimeError):
    """A write was refused because this process's generation is superseded."""

    def __init__(self, message: str, *, generation: int, authoritative: int):
        super().__init__(message)
        self.generation = generation
        self.authoritative = authoritative


def current_generation() -> int | None:
    """This incarnation's token (``IGG_GENERATION``; None = unfenced)."""
    return _config.generation_env()


def fence_dir() -> str | None:
    """Where the authoritative token lives (``IGG_FENCE_DIR``)."""
    return _config.fence_dir_env()


def authoritative_generation(directory: str | None = None) -> int | None:
    """The supervisor-published current token, or None when no fence file
    is readable (no supervisor, or a pre-fencing run directory).

    A fence file that is *present but unparseable* also reads as None —
    refusing every write over a torn file would wedge the run — but that
    state silently disarms zombie refusal, so it lands on the timeline as
    a ``fence.corrupt`` event (+ ``fence.corrupt_total``) instead of
    passing for "no supervisor"."""
    directory = directory if directory is not None else fence_dir()
    if not directory:
        return None
    path = os.path.join(directory, GENERATION_FILE)
    try:
        with open(path) as f:
            doc = json.load(f)
        return int(doc["generation"])
    except FileNotFoundError:
        return None
    except (OSError, ValueError, KeyError, TypeError):
        _telemetry.counter("fence.corrupt_total").inc()
        _telemetry.event("fence.corrupt", path=path)
        return None


def publish_generation(generation: int, directory: str | None = None,
                       **info) -> str:
    """Supervisor-side: atomically publish ``generation`` as the
    authoritative token (refuses to move the token backwards — the
    monotonicity that makes stale-token refusal sound)."""
    directory = directory if directory is not None else fence_dir()
    if not directory:
        raise ValueError(
            "publish_generation needs a fence directory (argument or "
            "IGG_FENCE_DIR)."
        )
    generation = int(generation)
    prev = authoritative_generation(directory)
    if prev is not None and generation < prev:
        raise ValueError(
            f"generation must be monotonic: refusing to publish "
            f"{generation} over the authoritative {prev}."
        )
    os.makedirs(directory, exist_ok=True)
    return _telemetry.atomic_write_json(
        os.path.join(directory, GENERATION_FILE),
        {"generation": generation, "ts": time.time(), **info},
    )


def fence_refusal(what: str) -> FenceError | None:
    """The fence decision for one publish attempt, WITHOUT raising.

    Returns a `FenceError` (already evented: one rank-tagged
    ``fence.rejected`` line + the ``fence.rejected_total`` counter) when
    this process carries a stale token, else None.  Rank-uniform by
    construction: the token is per-incarnation env state and the
    authoritative file is shared, so every rank of one incarnation reaches
    the same verdict.
    """
    gen = current_generation()
    if gen is None:
        return None
    auth = authoritative_generation()
    if auth is None or auth <= gen:
        return None
    _telemetry.counter("fence.rejected_total").inc()
    _telemetry.event(
        "fence.rejected", what=what, generation=gen, authoritative=auth
    )
    return FenceError(
        f"{what} refused: this process carries generation {gen} but the "
        f"supervisor has moved the run to generation {auth} — a superseded "
        f"(zombie) incarnation must not publish state.",
        generation=gen,
        authoritative=auth,
    )


def fence_refused(what: str) -> bool:
    """Non-raising fence check for advisory writes (endpoint files): True
    = refuse (the refusal is already evented)."""
    return fence_refusal(what) is not None


def check_fence(what: str) -> None:
    """Raising fence check for durable publishes (checkpoints, resize
    plans): raises the evented `FenceError` when superseded."""
    err = fence_refusal(what)
    if err is not None:
        raise err
