"""igg.supervisor — the self-healing run supervisor (docs/robustness.md).

The reference's contract is "crash one node and the job is lost"; this
subsystem is the opposite: a failure-domain manager that launches and OWNS
a multi-process run end to end, in four pieces forming the state machine
**detect → classify → policy → fence**:

* `generation` — monotonically-increasing generation tokens per
  incarnation, threaded through checkpoint meta / telemetry event tags /
  front-door control broadcasts, with **fencing** at every durable publish
  path (a zombie rank from a superseded generation is refused at
  `save_checkpoint`, the ``resize.json`` publish and the endpoint-file
  writes, and the refusal lands as a rank-tagged ``fence.rejected``
  event).
* `classify` — pure evidence → failure class: crash, step stall,
  straggler, corrupt checkpoint, guard trip, gather tripwire, resize.
* `policy` — pure incident → action: restart-in-place with
  `backoff_schedule` semantics, elastic shrink after
  ``IGG_SUPERVISE_MAX_RESTARTS`` strikes, scale-up reattempt when spares
  return, permanent quarantine of ranks that keep failing integrity; plus
  `recovery_plan`, the per-rank in-band collective schedule the
  ``collective-consistency`` analyzer censuses.
* `manager` — `RunSupervisor`, the orchestration loop: spawn, watch
  (process liveness + liveplane ``/healthz`` scrapes), ingest flight
  bundles and latched alerts, decide, fence, relaunch.  The soak
  ``elastic_failover``/``frontdoor``/``chaos`` drills are thin wrappers
  over it (`scripts/soak.py`).

Host-side only: this package never imports jax — it must keep working
while the fabric it supervises is wedged.
"""

from .classify import FAILURE_KINDS, Incident, classify, collect_evidence
from .generation import (
    FenceError,
    authoritative_generation,
    check_fence,
    current_generation,
    fence_refused,
    publish_generation,
)
from .manager import Incarnation, RunSupervisor, SupervisorReport
from .policy import (
    ACTIONS,
    Decision,
    RecoveryPolicy,
    SupervisorState,
    decide,
    recovery_plan,
)

__all__ = [
    "FAILURE_KINDS",
    "ACTIONS",
    "Incident",
    "classify",
    "collect_evidence",
    "FenceError",
    "current_generation",
    "authoritative_generation",
    "publish_generation",
    "check_fence",
    "fence_refused",
    "Decision",
    "RecoveryPolicy",
    "SupervisorState",
    "decide",
    "recovery_plan",
    "Incarnation",
    "RunSupervisor",
    "SupervisorReport",
]
