"""Admission control for the serving front door (ISSUE 12, docs/serving.md).

The front door must say *no* cheaply, for a reason, with a useful
``Retry-After`` — long before a request can hurt the pool.  Three gates,
evaluated in order over one live VIEW of the telemetry registry:

* **SLO** — while the pool is breaching its latency SLO, new work only
  deepens the breach.  Two signals flip this gate: an active CRITICAL
  anomaly alert (the `utils.liveplane` rule engine — admission runs its
  own scrape-time tick, so a wedged serving loop is seen from the
  admission thread "within one heartbeat" even though the loop itself
  cannot heartbeat), and the rolling ``serving.round_seconds`` p99 window
  exceeding ``IGG_FRONTDOOR_SLO_P99_S`` when that knob is set.
* **Backpressure** — the ``serving.queue_depth`` gauge at/above
  ``IGG_FRONTDOOR_QUEUE_MAX`` (default 4x the pool capacity): the queue is
  the elastic buffer, but an unbounded one just converts overload into
  unbounded latency.
* **Quota** — per-tenant token buckets (``IGG_TENANT_QUOTA`` =
  ``RATE[:BURST]`` requests/second): one tenant's burst must not starve
  the rest.  Buckets are cardinality-bounded like every per-tenant series
  (`telemetry.MAX_TENANTS_DEFAULT`); overflow tenants share one bucket.

`decide` is a PURE function of ``(view, policy)`` — deterministic given a
synthetic gauge snapshot, which is how tier-1 tests pin the accept/reject
matrix without a network (`tests/test_frontdoor.py`).  `AdmissionController`
owns the impure parts: building the view from the live registry, the
clock-driven buckets, and the telemetry ledger
(``frontdoor.admitted_total``, ``frontdoor.rejected_total`` plus
per-reason ``frontdoor.rejected.<reason>`` and per-tenant counters).

Rejections are cheap 429s whose ``Retry-After`` derives from the current
round cadence (`retry_after_s`): the p50 round latency times the work the
pool must shed before the gate can open again.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..utils import config as _config
from ..utils import liveplane as _liveplane
from ..utils import telemetry as _telemetry
from ..utils import tracing as _tracing

#: reject reasons, in evaluation order (docs/serving.md)
REASONS = ("slo", "backpressure", "quota")

#: fallback round cadence for Retry-After before any round has completed
DEFAULT_CADENCE_S = 0.25

#: bound on distinct per-tenant token buckets (overflow shares one bucket,
#: mirroring the telemetry tenant-series cap)
MAX_BUCKETS = 1024

#: how long `AdmissionController` reuses one registry view across requests
#: (a snapshot sorts every reservoir under the registry lock — one per
#: scrape is enough, per the RuleEngine contract; the alert bit is read
#: FRESH on every check, so breach visibility lags at most one TTL)
VIEW_TTL_S = 0.15


@dataclasses.dataclass(frozen=True)
class AdmissionPolicy:
    """The admission thresholds (all optional — None disables a gate).

    ``tenant_rate``/``tenant_burst``: token-bucket arrival limit per
    tenant; ``queue_max``: queue-depth backpressure threshold;
    ``slo_p99_s``: rolling round-p99 ceiling; ``reject_on_critical_alert``:
    whether an active CRITICAL anomaly alert flips the ``slo`` gate.
    """

    tenant_rate: float | None = None
    tenant_burst: float = 1.0
    queue_max: int | None = None
    slo_p99_s: float | None = None
    reject_on_critical_alert: bool = True

    @classmethod
    def from_env(cls, *, capacity: int | None = None) -> "AdmissionPolicy":
        """The env-knob tier (docs/usage.md): ``IGG_TENANT_QUOTA``,
        ``IGG_FRONTDOOR_QUEUE_MAX`` (default 4x ``capacity``),
        ``IGG_FRONTDOOR_SLO_P99_S``."""
        quota = _config.tenant_quota_env()
        rate, burst = quota if quota else (None, 1.0)
        qmax = _config.frontdoor_queue_max_env()
        if qmax is None and capacity:
            qmax = 4 * int(capacity)
        return cls(
            tenant_rate=rate,
            tenant_burst=burst,
            queue_max=qmax,
            slo_p99_s=_config.frontdoor_slo_p99_env(),
        )


class TokenBucket:
    """Classic token bucket; the caller supplies the clock, so refill math
    is deterministic under an injected time source (tests)."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t: float | None = None

    def refill(self, now: float) -> float:
        if self._t is not None and now > self._t:
            self.tokens = min(self.burst, self.tokens + (now - self._t) * self.rate)
        self._t = now
        return self.tokens

    def take(self) -> bool:
        """Consume one token if available (call `refill` first)."""
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def seconds_until_token(self) -> float:
        return max(0.0, (1.0 - self.tokens) / self.rate)


def decide(view: dict, policy: AdmissionPolicy) -> dict:
    """PURE admission verdict over a gauge view.

    ``view`` keys (all optional): ``queue_depth``, ``active_members``,
    ``capacity``, ``round_p50_s``, ``round_p99_s``, ``critical_alert``
    (bool), ``tenant_tokens`` (the tenant's refilled bucket level, or None
    when unmetered).  Returns ``{"admit": bool, "reason": None | one of
    `REASONS`}`` — same inputs, same verdict, no clocks, no globals.
    """
    if policy.reject_on_critical_alert and view.get("critical_alert"):
        return {"admit": False, "reason": "slo"}
    p99 = view.get("round_p99_s")
    if policy.slo_p99_s is not None and p99 is not None and p99 > policy.slo_p99_s:
        return {"admit": False, "reason": "slo"}
    queue_depth = int(view.get("queue_depth") or 0)
    if policy.queue_max is not None and queue_depth >= policy.queue_max:
        return {"admit": False, "reason": "backpressure"}
    tokens = view.get("tenant_tokens")
    if tokens is not None and tokens < 1.0:
        return {"admit": False, "reason": "quota"}
    return {"admit": True, "reason": None}


def retry_after_s(view: dict, policy: AdmissionPolicy, reason: str,
                  *, bucket_wait_s: float | None = None) -> float:
    """``Retry-After`` for a rejection, derived from the round cadence.

    One serving round retires at most ``capacity`` members and is the unit
    everything queues behind, so the p50 round latency is the natural time
    base: backpressure waits the rounds needed to sink the excess queue,
    an SLO breach waits a few rounds for the window to move, quota waits
    for the token refill (floored at one round).  Always >= the cadence
    and > 0 — a 429 that says "retry immediately" is a retry storm.
    """
    cadence = view.get("round_p50_s") or DEFAULT_CADENCE_S
    if reason == "quota" and bucket_wait_s is not None:
        return max(cadence, bucket_wait_s)
    if reason == "backpressure":
        queue_depth = int(view.get("queue_depth") or 0)
        over = max(1, queue_depth - (policy.queue_max or queue_depth) + 1)
        capacity = max(1, int(view.get("capacity") or 1))
        return cadence * max(1.0, over / capacity)
    # slo: give the rolling window a few rounds to recover
    return max(1.0, 4.0 * cadence)


@dataclasses.dataclass
class Decision:
    """One admission outcome: verdict, reason, Retry-After and the view it
    was decided on (returned so the HTTP layer can echo the evidence)."""

    admit: bool
    reason: str | None
    retry_after_s: float
    view: dict


def gauge_view(*, snap: dict | None = None, tick: bool = True) -> dict:
    """The live admission/autoscale VIEW from the telemetry registry.

    One registry snapshot feeds everything: the serving occupancy gauges,
    the rolling ``serving.round_seconds`` window (falling back to the
    published ``slo.*`` gauges), and — when ``tick`` — a scrape-source
    rule-engine evaluation over the SAME snapshot, so a stalled serving
    loop flips ``critical_alert`` at admission time without waiting for a
    heartbeat the stalled loop can never reach.
    """
    if snap is None:
        snap = _telemetry.snapshot()
    engine = _liveplane.get_engine()
    if tick and _telemetry.enabled():
        engine.tick("scrape", snap=snap)
    gauges = snap.get("gauges", {})
    win = snap.get("histograms", {}).get("serving.round_seconds", {}).get(
        "window"
    ) or {}
    return {
        # queue depth = the pool's queue PLUS the door's not-yet-synced
        # pending specs: the serving gauge only moves at control syncs, so
        # during a long/stalled round the pending deque is where overload
        # actually accumulates — the backpressure gate must see it
        "queue_depth": (
            gauges.get("serving.queue_depth", 0)
            + gauges.get("frontdoor.pending", 0)
        ),
        "active_members": gauges.get("serving.active_members", 0),
        "capacity": gauges.get("serving.capacity"),
        "round_p50_s": win.get("p50", gauges.get("slo.serving.round_seconds.p50")),
        "round_p99_s": win.get("p99", gauges.get("slo.serving.round_seconds.p99")),
        "critical_alert": any(
            a.get("severity") == "critical" for a in engine.active_alerts()
        ),
    }


class AdmissionController:
    """The impure shell around `decide`: live views, clocked token buckets,
    and the telemetry ledger.  Thread-safe — `check` runs on the front
    door's HTTP handler threads."""

    def __init__(self, policy: AdmissionPolicy | None = None, *,
                 capacity: int | None = None, clock=time.monotonic):
        self.policy = (
            policy if policy is not None
            else AdmissionPolicy.from_env(capacity=capacity)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        self._overflow: TokenBucket | None = None
        self._view: dict | None = None      # TTL-cached registry view
        self._view_at: float | None = None

    def _live_view(self, now: float) -> dict:
        """The registry view, TTL-cached (`VIEW_TTL_S`): under a 429 storm
        the "cheap" rejection path must not sort every histogram reservoir
        per request.  The snapshot-derived numbers age up to one TTL; the
        CRITICAL-alert bit is re-read from the engine on EVERY call (a
        lock + list copy — cheap), so an alert another tick raised is
        seen immediately and a breach the cached view predates is seen
        within one TTL of the next engine tick."""
        with self._lock:
            cached = (
                dict(self._view)
                if self._view is not None and self._view_at is not None
                and 0 <= now - self._view_at < VIEW_TTL_S
                else None
            )
        if cached is None:
            cached = gauge_view()  # one snapshot + scrape-source rule tick
            with self._lock:
                self._view, self._view_at = dict(cached), now
        cached["critical_alert"] = any(
            a.get("severity") == "critical"
            for a in _liveplane.get_engine().active_alerts()
        )
        return cached

    def _bucket(self, tenant: str) -> TokenBucket | None:
        rate = self.policy.tenant_rate
        if rate is None:
            return None
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                if len(self._buckets) >= MAX_BUCKETS:
                    if self._overflow is None:
                        self._overflow = TokenBucket(rate, self.policy.tenant_burst)
                    return self._overflow
                b = self._buckets[tenant] = TokenBucket(
                    rate, self.policy.tenant_burst
                )
            return b

    def check(self, tenant: str, *, now: float | None = None,
              view: dict | None = None) -> Decision:
        """Decide one request NOW: build the live view (or take the
        caller's), refill the tenant's bucket, run `decide`, consume a
        token only on admission, and account the outcome.  The decision
        runs under an ``igg.serving.admission`` span — inside a request
        context (the front door's submit path) it chains into the
        request's causal tree and the critical-path analyzer charges its
        time to the ``admission`` segment."""
        with _tracing.trace_span("igg.serving.admission", tenant=tenant):
            if now is None:
                now = self._clock()
            bucket = self._bucket(tenant)
            if view is None:
                view = self._live_view(now)
            wait = None
            if bucket is not None:
                # refill → decide → take under ONE lock acquisition: two
                # concurrent submits must not both observe the same token
                # and both admit (check-then-act) — `decide` is pure and
                # cheap, so holding the lock across it is fine
                with self._lock:
                    view = dict(view, tenant_tokens=bucket.refill(now))
                    verdict = decide(view, self.policy)
                    if verdict["admit"]:
                        bucket.take()
                    elif verdict["reason"] == "quota":
                        wait = bucket.seconds_until_token()
            else:
                verdict = decide(view, self.policy)
            retry = 0.0 if verdict["admit"] else retry_after_s(
                view, self.policy, verdict["reason"], bucket_wait_s=wait
            )
            self._account(tenant, verdict)
            return Decision(
                admit=verdict["admit"], reason=verdict["reason"],
                retry_after_s=retry, view=view,
            )

    def _account(self, tenant: str, verdict: dict) -> None:
        if verdict["admit"]:
            _telemetry.counter("frontdoor.admitted_total").inc()
            _telemetry.frontdoor_tenant_counter(tenant, "admitted").inc()
            _telemetry.gauge("frontdoor.backpressure").set(0)
        else:
            reason = verdict["reason"]
            _telemetry.counter("frontdoor.rejected_total").inc()
            _telemetry.counter(f"frontdoor.rejected.{reason}").inc()
            _telemetry.frontdoor_tenant_counter(tenant, "rejected").inc()
            _telemetry.gauge("frontdoor.backpressure").set(
                1 if reason in ("backpressure", "slo") else 0
            )
