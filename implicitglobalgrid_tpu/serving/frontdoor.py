"""igg.serving.frontdoor — the network-facing serving plane (ISSUE 12).

PR 8 built the engine (`serving.ServingLoop`), PR 10 the live SLO surface
it was designed to key on; this module is the door: requests arrive over
HTTP, admission is gated on live queue/SLO state, and the topology
grows/shrinks under load (ROADMAP item 3 — "make millions of users
literal").  docs/serving.md is the operator guide.

**HTTP surface** (stdlib ``http.server`` daemon thread, the
`utils.liveplane` pattern; ``IGG_SERVE_PORT``, 0 = ephemeral, bind
address ``IGG_SERVE_HOST``; rank 0 only — the front door is the cluster's
single network entry, the per-rank liveplane endpoints stay the
observability surface):

- ``POST /v1/submit`` — ``{"tenant", "model", "size", "params":
  {"ic_scale", "max_steps", "tol"}}`` → 202 ``{"request_id"}``.  Requests
  carry *parameters*, never arrays: every rank rebuilds the member's
  initial state locally from the spec (the model ``setup`` is a pure
  function of the implicit global grid), which is what lets one rank's
  network traffic drive an SPMD pool.  Invalid → 400; admission-rejected
  → a cheap 429 with a ``Retry-After`` derived from the current round
  cadence (`admission.retry_after_s`) and a machine-readable ``reason``
  (``quota`` | ``backpressure`` | ``slo``).
- ``GET /v1/result/<id>`` — ``pending`` | ``accepted`` | ``done`` (final
  status, step count, residual, and a per-field sha256 digest of the
  de-duplicated global state — computed collectively at retirement, so a
  client can verify bit-identity without shipping fields over HTTP).
- ``GET /v1/status`` — occupancy, admission/autoscaler state, request
  ledger counts.  ``GET /metrics`` / ``GET /healthz`` mirror the
  liveplane endpoints so one scrape of the front-door port sees the
  ``frontdoor.*`` ledger mid-run.
- ``POST /v1/shutdown`` — broadcast a clean stop (operator/supervisor
  surface).

**Control plane.**  `ServingLoop` state is SPMD: every rank must submit
the same members in the same order, yet only rank 0 hears the network.
`serve_rounds` therefore runs one control SYNC per iteration: rank 0
drains its pending specs (plus drain/resize/shutdown directives) into one
JSON message and broadcasts it — a two-phase host-side collective (scalar
length via `utils.tracing.all_ranks_value`, then a padded byte buffer
over the same scatter/pmax transport `skew_probe` rides) — and every rank
applies it identically.  Rank-local alerts still never drive collectives:
admission rejections are rank-0-local, and every cross-rank mutation
(admit, drain, resize, shutdown) travels through the broadcast.

**Elastic autoscaling.**  The `autoscale.Autoscaler` verdict (rank 0, at
heartbeat cadence, over the same gauge view admission uses) becomes a
``resize`` directive: every rank checkpoints the batched pool
(`utils.checkpoint.save_checkpoint` — slot metadata and the front-door
request ledger ride ``extra``), rank 0 atomically publishes
``resize.json``, and `serve_rounds` returns ``"resize"`` so the process
can exit with `RESIZE_STATUS` for its supervisor to relaunch at the
target topology — the supervised-restart mechanism the soak
``elastic_failover`` drill proves, pointed at growth.  On relaunch
`elastic_resume` validates the topology change
(`parallel.grid.elastic_topology_error`), reshards the pool through the
checkpoint's elastic path (leading ensemble axis included), re-`adopt`\\ s
every live member with its step count and budget intact, and rebuilds
still-queued members from their specs — zero members dropped across a
resize.  Scale-downs drain first: ``drain_above`` stops admissions into
retiring slots, in-flight rounds finish, then the reshard runs.
"""

from __future__ import annotations

import collections
import hashlib
import http.server
import json
import os
import socket
import threading
import time

import numpy as np

from ..utils import config as _config
from ..utils import liveplane as _liveplane
from ..utils import telemetry as _telemetry
from ..utils import tracing as _tracing
from . import admission as _admission
from .loop import Request, ServingLoop

__all__ = [
    "FrontDoor",
    "RESIZE_STATUS",
    "RESIZE_PLAN",
    "endpoint_filename",
    "state_digest",
]

#: exit status a serving process uses after writing a resize plan — the
#: supervisor's signal to relaunch at the plan's topology (distinct from
#: the fault injector's CRASH_STATUS 17)
RESIZE_STATUS = 19

#: the resize plan file rank 0 publishes into the checkpoint directory
RESIZE_PLAN = "resize.json"

#: request-body bound in bytes (``IGG_SERVE_MAX_BODY`` overrides): a POST
#: past it is refused with a structured 413 before the handler buffers it
MAX_BODY_DEFAULT = 1 << 20

#: per-connection socket timeout in seconds — a slow-loris client times
#: out and drops instead of pinning a rank-0 handler thread forever
SOCKET_TIMEOUT_S = 10

#: padding quantum of the control broadcast (bounds the compile cache)
_BCAST_PAD = 1024

_bcast_cache: dict = {}


def _clear_caches() -> None:
    """Drop the compiled broadcast fns (wired into `finalize_global_grid`
    like every sibling compiled-fn cache — entries close over the mesh)."""
    _bcast_cache.clear()


def endpoint_filename(rank: int) -> str:
    return f"frontdoor.p{rank}.json"


def _member_ctx(rec_trace: dict | None) -> dict | None:
    """The member-level trace context from a ledgered request's trace
    record (the shape checkpoints persist) — what a rebuilt `Request`
    carries so a restored member's rounds keep tagging its trace."""
    if rec_trace and rec_trace.get("member_span_id"):
        return {
            "trace_id": rec_trace["trace_id"],
            "span_id": rec_trace["member_span_id"],
        }
    return None


def state_digest(state) -> dict | None:
    """Per-field sha256 of the de-duplicated GLOBAL state.

    COLLECTIVE (rides `ops.gather.gather(dedup=True)`): every rank must
    call it together; returns the digest dict on rank 0 and None
    elsewhere.  Two runs produce identical digests iff their global fields
    are bit-identical — the cross-topology acceptance check of the soak
    ``frontdoor`` drill.
    """
    from ..ops import gather as _gather

    hashes = []
    on_root = True
    for A in state:
        dd = _gather.gather(A, dedup=True, root=0)
        if dd is None:
            on_root = False
            continue
        h = hashlib.sha256()
        h.update(str((tuple(dd.shape), str(dd.dtype))).encode())
        h.update(np.ascontiguousarray(dd).tobytes())
        hashes.append(h.hexdigest())
    if not on_root:
        return None
    return {"algo": "sha256", "fields": hashes}


# -- control-plane broadcast --------------------------------------------------


def _bcast_fn(gg, n: int):
    """Compiled rank-0→all byte broadcast: every block contributes a
    ``(1,1,1,n)`` f32 slab (rank 0's carry the payload, everyone else
    zeros) and an all-axes ``pmax`` replicates the payload — the same
    host-dispatched scatter/reduce transport shape as
    `tracing.skew_probe`, proven on every supported backend."""
    key = (gg.epoch, n)
    fn = _bcast_cache.get(key)
    if fn is not None:
        return fn
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES
    from ..utils.compat import shard_map

    def per_block(x):
        return lax.pmax(x, AXIS_NAMES)

    mapped = shard_map(
        per_block,
        mesh=gg.mesh,
        in_specs=P(*AXIS_NAMES, None),
        out_specs=P(),
        check_vma=False,
    )
    fn = jax.jit(mapped)
    _bcast_cache[key] = fn
    return fn


def broadcast_control(doc: dict | None) -> dict:
    """Share rank 0's control message with every rank (rank 0 passes the
    message, everyone else None).  COLLECTIVE at a deterministic cadence:
    `FrontDoor.serve_rounds` calls it exactly once per iteration on every
    rank.  Single-process grids return the message directly.  Two phases:
    a scalar length share (empty message = length 0 ends the exchange),
    then a `_BCAST_PAD`-padded byte buffer — bytes ride f32 exactly."""
    from ..parallel import grid as _grid

    if _telemetry.process_count() == 1:
        return doc or {}
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES

    gg = _grid.global_grid()
    is_root = jax.process_index() == 0
    data = (
        json.dumps(doc, default=str).encode() if (is_root and doc) else b""
    )
    vals = _tracing.all_ranks_value(float(len(data)))
    length = int(np.max(vals))
    if length == 0:
        return {}
    n = -(-length // _BCAST_PAD) * _BCAST_PAD
    payload = np.zeros((1, 1, 1, n), np.float32)
    if data:
        payload[0, 0, 0, :length] = np.frombuffer(data, np.uint8)

    def _block(index, payload=payload, root=is_root):
        return payload if root else np.zeros_like(payload)

    sharding = NamedSharding(gg.mesh, P(*AXIS_NAMES, None))
    arr = jax.make_array_from_callback((*gg.dims, n), sharding, _block)
    out = np.asarray(_bcast_fn(gg, n)(arr)).reshape(-1)[:length]
    return json.loads(out.astype(np.uint8).tobytes().decode())


# -- the HTTP layer -----------------------------------------------------------


def _make_handler(fd: "FrontDoor"):
    class _Handler(http.server.BaseHTTPRequestHandler):
        server_version = "igg-frontdoor/1"
        timeout = SOCKET_TIMEOUT_S

        def _reply(self, code: int, body: dict, headers: dict | None = None,
                   raw: bytes | None = None, ctype: str = "application/json"):
            data = raw if raw is not None else json.dumps(
                body, default=str
            ).encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            try:
                if path.startswith("/v1/result/"):
                    rid = path[len("/v1/result/"):]
                    doc = fd.result_view(rid)
                    hdrs = fd.trace_header(rid)
                    if doc is None:
                        self._reply(404, {"error": f"unknown request {rid!r}"})
                    elif doc.get("status") == "expired":
                        # pruned by IGG_RESULT_KEEP / IGG_RESULT_TTL_S:
                        # Gone, with the knobs named so the client knows
                        # which retention bound to raise
                        _telemetry.counter("frontdoor.results_expired").inc()
                        self._reply(410, {
                            "error": f"result {rid!r} expired",
                            "status": "expired",
                            "detail": "pruned under IGG_RESULT_KEEP/"
                                      "IGG_RESULT_TTL_S retention",
                        })
                    else:
                        self._reply(200, doc, headers=hdrs)
                elif path == "/v1/status":
                    self._reply(200, fd.status_view())
                elif path == "/metrics":
                    self._reply(
                        200, {}, raw=_telemetry.prometheus_text().encode(),
                        ctype="text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/healthz":
                    snap = _telemetry.snapshot()
                    _liveplane.get_engine().tick("scrape", snap=snap)
                    self._reply(200, _liveplane.health_snapshot(snap))
                else:
                    self.send_error(404, "unknown endpoint")
            except Exception as e:  # a scrape must never kill the server
                self.send_error(500, repr(e))

        def do_POST(self):  # noqa: N802
            path = self.path.split("?", 1)[0]
            try:
                # Request hardening (docs/serving.md): a malformed length
                # header or an oversize body is a cheap structured refusal,
                # never a 500 and never an unbounded buffer.
                raw_len = self.headers.get("Content-Length")
                try:
                    length = int(raw_len) if raw_len is not None else 0
                except ValueError:
                    self._reply(400, {
                        "error": f"malformed Content-Length {raw_len!r}",
                    })
                    return
                if length < 0:
                    self._reply(400, {
                        "error": f"negative Content-Length {length}",
                    })
                    return
                max_body = _config.serve_max_body_env() or MAX_BODY_DEFAULT
                if length > max_body:
                    _telemetry.counter("frontdoor.oversize_total").inc()
                    self._reply(413, {
                        "error": "request body too large",
                        "bytes": length,
                        "max_bytes": max_body,
                    })
                    return
                # Chunked read under a TOTAL wall-clock budget: the socket
                # timeout alone only bounds per-recv idle time — a client
                # trickling one byte per 9 s would reset it forever.  The
                # budget bounds the whole body, so a slow-loris gets a
                # structured 408 (best effort — it may be gone) and its
                # connection dropped; never the generic 500.
                body = b""
                deadline = time.monotonic() + SOCKET_TIMEOUT_S
                try:
                    while len(body) < length:
                        if time.monotonic() > deadline:
                            raise TimeoutError
                        # read1 = at most ONE underlying recv (a plain
                        # read(n) would loop recv until n bytes, resetting
                        # the socket timer per byte — the loris hole again)
                        chunk = self.rfile.read1(
                            min(64 << 10, length - len(body))
                        )
                        if not chunk:
                            break  # client hung up: the truncated-body 400
                        body += chunk
                except TimeoutError:
                    self._reply(408, {
                        "error": (
                            f"body read exceeded the {SOCKET_TIMEOUT_S}s "
                            f"budget ({len(body)} of {length} declared "
                            f"bytes arrived)"
                        ),
                    })
                    self.close_connection = True
                    return
                if len(body) < length:
                    # the client hung up mid-body: a truncated document
                    self._reply(400, {
                        "error": (
                            f"truncated body: {len(body)} of {length} "
                            f"declared bytes arrived"
                        ),
                    })
                    return
                if path == "/v1/submit":
                    try:
                        doc = json.loads(body.decode() or "{}")
                        if not isinstance(doc, dict):
                            raise ValueError("body must be a JSON object")
                    except (ValueError, UnicodeDecodeError) as e:
                        self._reply(400, {"error": f"bad JSON body: {e}"})
                        return
                    self._reply(*fd.handle_submit(
                        doc, traceparent=self.headers.get("traceparent")
                    ))
                elif path == "/v1/shutdown":
                    fd.request_shutdown()
                    self._reply(200, {"ok": True})
                else:
                    self.send_error(404, "unknown endpoint")
            except Exception as e:
                self.send_error(500, repr(e))

        def log_message(self, *args):  # requests must not spam stderr
            pass

    return _Handler


# -- the front door -----------------------------------------------------------


class FrontDoor:
    """One network entry in front of one `ServingLoop` (module docstring).

    ``loop`` — the pool; ``admission`` — an `admission.AdmissionController`
    (default: env-policy for the pool's capacity); ``autoscaler`` — an
    `autoscale.Autoscaler` (None = fixed capacity); ``checkpoint_dir`` —
    where resizes checkpoint and `elastic_resume` restores (required for
    autoscaling); ``setup_kwargs`` — extra model ``setup`` kwargs every
    member spec shares (``npt`` for porous, dtype overrides...);
    ``digest_results`` — compute the collective per-field digest at each
    retirement; ``port``/``host`` — override ``IGG_SERVE_PORT`` /
    ``IGG_SERVE_HOST``.
    """

    def __init__(self, loop: ServingLoop, *, admission=None, autoscaler=None,
                 port: int | None = None, host: str | None = None,
                 checkpoint_dir: str | None = None,
                 setup_kwargs: dict | None = None,
                 digest_results: bool = True):
        self.loop = loop
        self.model = loop.model
        self.checkpoint_dir = checkpoint_dir or loop.checkpoint_dir
        self.admission = (
            admission if admission is not None
            else _admission.AdmissionController(capacity=loop.capacity)
        )
        self.autoscaler = autoscaler
        self.setup_kwargs = dict(setup_kwargs or {})
        self.digest_results = digest_results
        self._lock = threading.RLock()
        self._pending: collections.deque = collections.deque()
        self._requests: dict[str, dict] = {}
        self._next_request = 0
        self._seen_results: set[int] = set()
        # Bounded result retention (ISSUE 16 satellite): request ids are
        # monotonic, so one integer horizon distinguishes "expired under
        # IGG_RESULT_KEEP / IGG_RESULT_TTL_S" (structured 410) from
        # "never existed" (404) without keeping a tombstone per request.
        self._expired_before = 0
        self._shutdown = False
        self._refusing: str | None = None  # "resizing": reject all submits
        self._drain_target: dict | None = None
        self._as_round = -1
        self._as_t = 0.0
        self._httpd = None
        self._thread = None
        self.port: int | None = None
        self.rank = _telemetry._proc_index()
        if self.autoscaler is not None:
            if not self.checkpoint_dir:
                raise ValueError(
                    "autoscaling needs a checkpoint_dir: a resize IS a "
                    "checkpoint + supervised restart."
                )
            # the RunGuard subscription mechanism: anomaly alerts reach the
            # autoscaler's status view through the rule engine
            _liveplane.subscribe(self.autoscaler.on_alert)
        if self.rank == 0:
            self._start_server(port, host)

    # - server lifecycle -

    def _start_server(self, port: int | None, host: str | None) -> None:
        if host is None:
            host = _config.serve_host_env() or "127.0.0.1"
        if port is None:
            port = _config.serve_port_env() or 0
        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), _make_handler(self)
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="igg-frontdoor",
            daemon=True,
        )
        self._thread.start()
        _telemetry.gauge("frontdoor.port").set(self.port)
        _telemetry.event("frontdoor.start", host=host, port=self.port)
        from ..supervisor import generation as _generation

        directory = _config.telemetry_dir_env()
        if _generation.fence_refused("frontdoor.endpoint"):
            # a superseded incarnation must not steal the discovery file
            # from the door that replaced it (advisory path: refuse, the
            # fence.rejected event is already on the timeline)
            directory = None
        if directory:
            pub_host = socket.gethostname() if host in ("0.0.0.0", "::") else host
            doc = {"rank": self.rank, "pid": os.getpid(), "host": pub_host,
                   "port": self.port, "ts": time.time()}
            try:
                os.makedirs(directory, exist_ok=True)
                _telemetry.atomic_write_json(
                    os.path.join(directory, endpoint_filename(self.rank)),
                    doc, fsync=False,  # advisory discovery file
                )
            except OSError:
                pass  # an unwritable dir must not take serving down

    def close(self) -> None:
        """Stop the HTTP server and drop the engine subscription (the pool
        itself is untouched — a closed door does not evict anyone)."""
        if self.autoscaler is not None:
            _liveplane.unsubscribe(self.autoscaler.on_alert)
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    # - HTTP-side (rank 0, handler threads) -

    def _validate(self, doc: dict) -> str | None:
        from ..parallel import grid as _grid

        model = doc.get("model")
        if model is not None and model != self.loop.model_name:
            return (
                f"this pool serves {self.loop.model_name!r}, not {model!r}"
            )
        size = doc.get("size")
        if size is not None:
            gg = _grid.global_grid()
            if list(size) != list(gg.nxyz_g):
                return (
                    f"size {list(size)} does not match the pool's global "
                    f"grid {list(gg.nxyz_g)}"
                )
        params = doc.get("params")
        if not isinstance(params, dict):
            return "params must be an object with at least max_steps"
        try:
            if int(params.get("max_steps", 0)) < 1:
                return f"params.max_steps must be >= 1 (got {params.get('max_steps')!r})"
        except (TypeError, ValueError):
            return f"params.max_steps must be an integer (got {params.get('max_steps')!r})"
        tol = params.get("tol")
        if tol is not None:
            if not self.loop.info["residual"]:
                return (
                    f"{self.loop.model_name} has no PT residual; tol applies "
                    f"to residual models only (use max_steps)"
                )
            try:
                float(tol)
            except (TypeError, ValueError):
                return f"params.tol must be a number (got {tol!r})"
        ic = params.get("ic_scale", 1.0)
        try:
            float(ic)
        except (TypeError, ValueError):
            return f"params.ic_scale must be a number (got {ic!r})"
        return None

    def handle_submit(self, doc: dict, *, traceparent: str | None = None):
        """One ``POST /v1/submit`` → ``(code, body, headers)``.  Validation
        → 400 before admission ever runs; admission → 429 with
        ``Retry-After``; accepted specs land in the pending queue the next
        control sync broadcasts.

        Trace context: an inbound ``doc["trace"]`` (a router-forwarded or
        replayed spec — wins) or W3C ``traceparent`` header is adopted;
        otherwise one is minted, head-sampled (``IGG_TRACE_SAMPLE``).  A
        traced request's every response carries ``traceparent`` back; the
        accepted spec carries a member-level child context into the
        control broadcast, so every rank's serving rounds tag the
        request.  Untraced requests pay nothing beyond echoing an inbound
        header verbatim."""
        tenant = str(doc.get("tenant") or "default")
        _telemetry.counter("frontdoor.requests_total").inc()
        inbound = doc.get("trace") if isinstance(doc.get("trace"), dict) \
            else None
        if inbound is None:
            inbound = _tracing.parse_traceparent(traceparent)
        ctx = None
        t0 = 0.0
        if _tracing.enabled() and (
            inbound is not None or _tracing.should_sample()
        ):
            tid = inbound["trace_id"] if inbound else _tracing.new_trace_id()
            ctx = {"trace_id": tid, "span_id": _tracing.new_span_id()}
            if inbound and inbound.get("span_id"):
                ctx["parent_id"] = inbound["span_id"]
            t0 = time.perf_counter()
        if ctx is not None:
            echo = {"traceparent": _tracing.format_traceparent(ctx)}
        elif traceparent:
            echo = {"traceparent": str(traceparent)}  # pure passthrough
        else:
            echo = {}
        err = self._validate(doc)
        if err is not None:
            _telemetry.counter("frontdoor.invalid_total").inc()
            return 400, {"error": err}, echo
        # Decision + append run under the SAME lock `_directives` holds
        # when it flips `_refusing` and drains pending: every request is
        # accounted exactly once (admitted XOR rejected), and every 202
        # ever issued is either in the resize drain or was refused — the
        # admission check is cheap here (TTL-cached view), so holding the
        # door lock across it costs microseconds, not a snapshot.
        with self._lock:
            if self._refusing:
                code, body, hdrs = self._reject_resizing(tenant)
                return code, body, {**hdrs, **echo}
            with _tracing.use_context(ctx):
                decision = self.admission.check(tenant)
            if not decision.admit:
                _telemetry.event(
                    "frontdoor.reject", tenant=tenant, reason=decision.reason,
                    retry_after_s=round(decision.retry_after_s, 3),
                )
                return (
                    429,
                    {
                        "error": "admission rejected",
                        "reason": decision.reason,
                        "retry_after_s": round(decision.retry_after_s, 3),
                    },
                    {"Retry-After": str(max(1, int(-(-decision.retry_after_s // 1)))),
                     **echo},
                )
            params = doc.get("params", {})
            spec = {
                "tenant": tenant,
                "params": {
                    "max_steps": int(params["max_steps"]),
                    "ic_scale": float(params.get("ic_scale", 1.0)),
                    "tol": None if params.get("tol") is None else float(params["tol"]),
                },
            }
            rid = f"r{self._next_request:06d}"
            self._next_request += 1
            spec["id"] = rid
            rec_trace = None
            if ctx is not None:
                # The member-level child context: rides the spec through
                # the control broadcast (every rank tags its rounds with
                # it), the checkpoint slot metadata (a trace survives a
                # generation bump) and any re-routed replay of the spec.
                member_ctx = {
                    "trace_id": ctx["trace_id"],
                    "span_id": _tracing.new_span_id(),
                }
                spec["trace"] = member_ctx
                rec_trace = {**ctx, "member_span_id": member_ctx["span_id"]}
            self._requests[rid] = {
                "id": rid, "tenant": tenant, "params": spec["params"],
                "submitted_ts": time.time(), "member": None, "done": None,
                "trace": rec_trace,
            }
            self._pending.append(spec)
            _telemetry.gauge("frontdoor.pending").set(len(self._pending))
        self._publish_oldest_gauge()
        trace_tags = {"trace_id": ctx["trace_id"]} if ctx else {}
        _telemetry.event("frontdoor.admit", request=rid, tenant=tenant,
                         **spec["params"], **trace_tags)
        if ctx is not None:
            # The HTTP-handler hop (validation + admission + enqueue),
            # chained under the request span recorded at harvest.
            _tracing.record_span(
                "igg.frontdoor.submit",
                t0=t0, dur=time.perf_counter() - t0,
                parent={"trace_id": ctx["trace_id"],
                        "span_id": ctx["span_id"]},
                request=rid, tenant=tenant,
            )
        return 202, {"request_id": rid}, echo

    def trace_header(self, rid: str) -> dict | None:
        """The ``traceparent`` echo header for a ledgered request (None
        when unknown or untraced)."""
        with self._lock:
            rec = self._requests.get(rid)
            tr = rec.get("trace") if rec else None
        if not tr:
            return None
        return {
            "traceparent": _tracing.format_traceparent(
                {"trace_id": tr["trace_id"], "span_id": tr["span_id"]}
            )
        }

    def _reject_resizing(self, tenant: str):
        """Mid-resize 429: the pool is checkpointing for a restart — turn
        traffic away cheaply (same ledger as every admission rejection)
        until the relaunched door opens."""
        retry = 5.0
        _telemetry.counter("frontdoor.rejected_total").inc()
        _telemetry.counter("frontdoor.rejected.resizing").inc()
        _telemetry.frontdoor_tenant_counter(tenant, "rejected").inc()
        _telemetry.gauge("frontdoor.backpressure").set(1)
        _telemetry.event("frontdoor.reject", tenant=tenant,
                         reason="resizing", retry_after_s=retry)
        return (
            429,
            {"error": "resizing", "reason": "resizing",
             "retry_after_s": retry},
            {"Retry-After": str(int(-(-retry // 1)))},
        )

    def request_shutdown(self) -> None:
        self._shutdown = True

    def result_view(self, rid: str) -> dict | None:
        with self._lock:
            rec = self._requests.get(rid)
            if rec is None:
                try:
                    n = int(rid.lstrip("r"))
                except ValueError:
                    return None
                if rid.startswith("r") and n < self._expired_before:
                    # pruned under the retention knobs: a structured 410,
                    # distinct from "never existed"
                    return {"request_id": rid, "status": "expired"}
                return None
            if rec["done"] is not None:
                return {"request_id": rid, "status": "done", **rec["done"]}
            if rec["member"] is None:
                return {"request_id": rid, "status": "pending"}
            return {
                "request_id": rid, "status": "accepted",
                "member": rec["member"],
            }

    def status_view(self) -> dict:
        with self._lock:
            total = len(self._requests)
            done = sum(1 for r in self._requests.values() if r["done"])
            pending = len(self._pending)
        doc = {
            "rank": self.rank,
            "model": self.loop.model_name,
            "rounds": self.loop.rounds,
            "capacity": self.loop.capacity,
            "queue_depth": len(self.loop.queue),
            "active_members": self.loop.active_members,
            "pending": pending,
            "requests": {"total": total, "done": done},
            "draining": self._drain_target,
            "resizing": bool(self._refusing),
        }
        if self.autoscaler is not None:
            doc["autoscaler"] = self.autoscaler.status()
        return doc

    # - the serving thread (every rank) -

    def _build_state(self, ic_scale: float) -> tuple:
        from ..parallel import grid as _grid

        gg = _grid.global_grid()
        state, _params = self.model.setup(
            *gg.nxyz, init_grid=False, ic_scale=float(ic_scale),
            **self.setup_kwargs,
        )
        return tuple(state)

    def _directives(self) -> dict | None:
        """Rank 0: compose this iteration's control message."""
        doc: dict = {}
        resize = self._maybe_autoscale()
        with self._lock:
            if resize is not None and "resize" in resize:
                # refuse new submissions UNDER THE SAME LOCK that drains
                # pending: `handle_submit` re-checks `_refusing` inside its
                # locked append, so every 202 ever issued is either in this
                # drain or was refused — nothing can slip into the gap
                # behind the checkpointed ledger
                self._refusing = "resizing"
            if self._pending:
                doc["admit"] = list(self._pending)
                self._pending.clear()
                _telemetry.gauge("frontdoor.pending").set(0)
        if resize is not None:
            doc.update(resize)
        if self._shutdown:
            doc["shutdown"] = True
        if doc:
            # thread the incarnation's generation token through the
            # control plane (docs/robustness.md): receivers verify it in
            # `_apply` — a directive from another incarnation is refused
            from ..supervisor import generation as _generation

            gen = _generation.current_generation()
            if gen is not None:
                doc["gen"] = gen
        return doc or None

    def _maybe_autoscale(self) -> dict | None:
        """Rank 0, heartbeat cadence: one autoscaler observation over the
        live gauge view; returns ``{"drain": cap}`` or ``{"resize": plan}``
        directives (or None)."""
        if self.autoscaler is None:
            return None
        now = time.monotonic()
        if self.loop.rounds == self._as_round and now - self._as_t < 0.25:
            return None
        self._as_round, self._as_t = self.loop.rounds, now
        view = _admission.gauge_view(tick=False)
        if self._drain_target is not None:
            target = self._drain_target
            # drained() = no member left in a retiring slot (which implies
            # occupancy fits the target): the documented "stop admitting,
            # finish in-flight, then reshard" readiness
            if self.loop.drained(int(target["capacity"])):
                plan = dict(target, reason="scale_down_drained")
                return {"resize": plan}
            return None
        action = self.autoscaler.observe(view)
        if action is None:
            return None
        plan = {
            "nproc": action["target"]["nproc"],
            "capacity": action["target"]["capacity"],
            "rung": action["rung"],
            "reason": action["action"],
            "evidence": action["evidence"],
        }
        if action["action"] == "up":
            return {"resize": plan}
        # scale-down: drain first — stop admitting into retiring slots,
        # let in-flight members finish, resize once occupancy fits
        return {"drain": plan}

    def _apply(self, msg: dict) -> str | None:
        """Every rank: apply one control message in a fixed order
        (admissions → drain → resize → shutdown).  A message stamped with
        a DIFFERENT generation than this incarnation's is refused whole —
        rank-uniformly (every rank of one incarnation carries the same
        token and reads the same stamp), so the refusal can never split
        the collectives a directive implies (`supervisor.policy.
        recovery_plan` is the censused statement of that contract)."""
        from ..supervisor import generation as _generation

        gen = _generation.current_generation()
        msg_gen = msg.get("gen")
        if gen is not None and msg_gen is not None and msg_gen != gen:
            _telemetry.counter("fence.rejected_total").inc()
            _telemetry.event(
                "fence.rejected", what="frontdoor.control",
                generation=gen, authoritative=msg_gen,
            )
            return None
        for spec in msg.get("admit", []):
            self._admit_spec(spec)
        if "drain" in msg:
            plan = msg["drain"]
            self.loop.drain_above = int(plan["capacity"])
            if self.rank == 0:
                self._drain_target = plan
            _telemetry.event("frontdoor.drain", **{
                k: plan[k] for k in ("nproc", "capacity", "reason")
                if k in plan
            })
        if "resize" in msg:
            self._execute_resize(msg["resize"])
            return "resize"
        if msg.get("shutdown"):
            _telemetry.event("frontdoor.shutdown")
            return "shutdown"
        return None

    def _admit_spec(self, spec: dict) -> None:
        params = spec["params"]
        trace = spec.get("trace") if isinstance(spec.get("trace"), dict) \
            else None
        state = self._build_state(params.get("ic_scale", 1.0))
        request = Request(
            state=state,
            max_steps=int(params["max_steps"]),
            tenant=spec.get("tenant", "default"),
            tol=params.get("tol"),
            trace=trace,
        )
        member = self.loop.submit(request)
        if self.rank == 0:
            rec = None
            with self._lock:
                rec = self._requests.get(spec.get("id"))
                if rec is not None:
                    rec["member"] = member
            rtr = rec.get("trace") if rec else None
            if trace is not None and rtr is not None:
                # Queue wait, retroactively: submit→admission-into-a-slot,
                # recorded under the PRE-BROADCAST member span id so every
                # rank's round spans (which carry the same member context)
                # parent here without any cross-process id exchange.
                wait = time.time() - rec["submitted_ts"]
                _tracing.record_span(
                    "igg.frontdoor.admit",
                    t0=time.perf_counter() - wait, dur=wait,
                    parent={"trace_id": rtr["trace_id"],
                            "span_id": rtr.get("span_id")},
                    span_id=trace.get("span_id"),
                    request=spec.get("id"), member=member,
                    tenant=spec.get("tenant", "default"),
                )

    def _harvest(self) -> None:
        """Collect newly retired members: the collective digest, the
        request ledger update and the latency ledger.  Iteration order is
        the member id — deterministic on every rank, so the digest
        collectives stay aligned."""
        fresh = sorted(
            m for m in self.loop.results if m not in self._seen_results
        )
        for member in fresh:
            self._seen_results.add(member)
            res = self.loop.results[member]
            digest = None
            if self.digest_results and res.state is not None:
                digest = state_digest(res.state)
            # Every rank consumed the result (the digest is the read):
            # under the retention knobs the pool may now prune the member
            # state at the next round end, uniformly across ranks.
            self.loop.mark_consumed(member)
            if self.rank != 0:
                continue
            with self._lock:
                rec = next(
                    (r for r in self._requests.values()
                     if r["member"] == member),
                    None,
                )
            if rec is None:
                continue
            latency = time.time() - rec["submitted_ts"]
            rec["done_ts"] = time.time()
            rec["done"] = {
                "result": res.status,
                "steps": res.steps,
                "residual": res.residual,
                "digest": digest,
                "latency_s": round(latency, 6),
            }
            _telemetry.counter("frontdoor.completed_total").inc()
            _telemetry.histogram("frontdoor.request_seconds").record(latency)
            _telemetry.tenant_histogram(rec["tenant"]).record(latency)
            tr = rec.get("trace")
            trace_tags = {"trace_id": tr["trace_id"]} if tr else {}
            _telemetry.event(
                "frontdoor.complete", request=rec["id"], member=member,
                tenant=rec["tenant"], result=res.status, steps=res.steps,
                latency_s=round(latency, 6), **trace_tags,
            )
            if tr is not None:
                # The request's root-side span: submit→result on the door,
                # recorded retroactively under the ledgered S_req id so the
                # whole tree (submit hop, queue wait, rounds on every rank,
                # re-routes) hangs off one span.
                _tracing.record_span(
                    "igg.frontdoor.request",
                    t0=time.perf_counter() - latency, dur=latency,
                    parent={"trace_id": tr["trace_id"],
                            "span_id": tr.get("parent_id")},
                    span_id=tr["span_id"],
                    request=rec["id"], member=member, tenant=rec["tenant"],
                    result=res.status,
                )
        # The loop prunes consumed member states at round end; mirror the
        # bound here so a request flood cannot grow the door either —
        # member ids never repeat, so the intersection is monotone-safe.
        self._seen_results &= set(self.loop.results)
        if self.rank == 0:
            self._prune_requests()
            self._publish_oldest_gauge()

    def _publish_oldest_gauge(self) -> None:
        """Rank 0: publish the oldest in-flight submit timestamp as the
        ``frontdoor.oldest_submitted_ts`` gauge (0 = nothing in flight).
        ``/healthz`` and ``igg_top`` turn it into the worst in-flight
        request AGE at scrape time — publishing the timestamp rather than
        a precomputed age keeps the reading fresh between publishes."""
        if self.rank != 0:
            return
        with self._lock:
            inflight = [
                r["submitted_ts"] for r in self._requests.values()
                if r["done"] is None
            ]
        _telemetry.gauge("frontdoor.oldest_submitted_ts").set(
            min(inflight) if inflight else 0
        )

    def _prune_requests(self) -> None:
        """Expire DONE ledger records under the retention knobs (rank 0).

        Same bounds as `ServingLoop._prune_results` — ``IGG_RESULT_KEEP``
        keeps the newest N done records, ``IGG_RESULT_TTL_S`` drops done
        records older than the bound — and the same invariant: a record
        nobody could still need (done = the result has been delivered into
        the ledger) is the only thing ever dropped; pending/accepted
        records are immortal until they complete.  Expired rids advance
        `_expired_before`, so a late fetch gets a structured 410 instead
        of a lying 404.
        """
        keep = _config.result_keep_env() or 0
        ttl = _config.result_ttl_env()
        if not keep and ttl is None:
            return
        with self._lock:
            done = sorted(
                (r for r in self._requests.values() if r["done"] is not None),
                key=lambda r: r["id"],
            )
            doomed = []
            if ttl is not None:
                now = time.time()
                doomed = [
                    r for r in done if now - r.get("done_ts", now) > ttl
                ]
            if keep:
                fresh = [r for r in done if r not in doomed]
                if len(fresh) > keep:
                    doomed += fresh[:-keep]
            for rec in doomed:
                del self._requests[rec["id"]]
                self._expired_before = max(
                    self._expired_before, int(rec["id"][1:]) + 1
                )
        if doomed:
            _telemetry.counter("frontdoor.requests_pruned_total").inc(
                len(doomed)
            )
            _telemetry.event(
                "frontdoor.requests_pruned",
                requests=[r["id"] for r in doomed],
                horizon=self._expired_before,
            )

    def serve_rounds(self, max_rounds: int | None = None, *,
                     idle_sleep: float = 0.02) -> str:
        """Drive the pool until a directive ends it: returns ``"shutdown"``,
        ``"resize"`` (checkpoint + plan written — exit with `RESIZE_STATUS`)
        or ``"rounds"`` (``max_rounds`` iterations elapsed).  One control
        sync per iteration on EVERY rank — the collective cadence is the
        iteration count, which the synced state keeps rank-uniform.
        """
        from ..utils import resilience as _resilience

        n = 0
        while True:
            directive = self._directives() if self.rank == 0 else None
            msg = broadcast_control(directive)
            outcome = self._apply(msg)
            if outcome is not None:
                return outcome
            if self.loop.queue or self.loop.active_members:
                # the stall injector hook (`IGG_FAULT_INJECT=stall:stepN`):
                # the SLO-breach drill wedges the serving thread HERE and
                # the admission thread must flip to 429s on its own
                _resilience.get_fault_injector().maybe_stall(self.loop.rounds)
                self.loop.run_round()
                self._harvest()
            else:
                # a drained pool is idle, not stalled: keep the step-stall
                # rule quiet while the door waits for traffic
                if _telemetry.enabled():
                    _telemetry.note_progress(
                        "serving.round", self.loop.rounds, done=True
                    )
                time.sleep(idle_sleep)
            n += 1
            if max_rounds is not None and n >= max_rounds:
                return "rounds"

    # - resize execution + elastic resume -

    def _frontdoor_meta(self) -> dict:
        with self._lock:
            return {
                "next_request": self._next_request,
                "expired_before": self._expired_before,
                "requests": {
                    rid: {
                        "tenant": r["tenant"], "params": r["params"],
                        "submitted_ts": r["submitted_ts"],
                        "member": r["member"], "done": r["done"],
                        "trace": r.get("trace"),
                    }
                    for rid, r in self._requests.items()
                },
            }

    def _execute_resize(self, plan: dict) -> None:
        """Every rank: checkpoint the pool + ledgers, publish the plan
        (rank 0, atomically), stop the HTTP server.  The caller exits with
        `RESIZE_STATUS`; the supervisor relaunches at ``plan``'s topology
        and the new process runs `elastic_resume`."""
        from ..supervisor import generation as _generation
        from ..utils import checkpoint as _checkpoint

        # Generation fence: a zombie incarnation publishing a resize plan
        # would steer the supervisor at a topology the LIVE incarnation
        # never asked for — the split-brain hole fencing closes.  Checked
        # before the checkpoint too (save_checkpoint re-checks; this names
        # the resize in the refusal).  Rank-uniform, so the raise cannot
        # split the collective save.
        _generation.check_fence("frontdoor.resize")
        with _tracing.trace_span("igg.frontdoor.resize",
                                 nproc=plan.get("nproc"),
                                 capacity=plan.get("capacity")):
            if self.loop._state is None:
                # an empty pool still resizes (scale-down at idle): prime
                # it so there is a (blank) pool to checkpoint and restore
                self.loop.prime(self._build_state(1.0))
            extra = {
                **self.loop._serving_meta(),
                "frontdoor": self._frontdoor_meta(),
                "resize": {k: plan[k] for k in ("nproc", "capacity", "rung",
                                                "reason") if k in plan},
            }
            path = _checkpoint.save_checkpoint(
                self.checkpoint_dir, self.loop._state, self.loop.rounds,
                extra=extra,
            )
            if self.rank == 0:
                plan_doc = {
                    **{k: plan[k] for k in ("nproc", "capacity", "rung",
                                            "reason") if k in plan},
                    "checkpoint": path,
                    "rounds": self.loop.rounds,
                    "ts": time.time(),
                }
                # fsync'd: the supervisor's ONLY relaunch instruction — it
                # must never be readable half-written after a power cut
                _telemetry.atomic_write_json(
                    os.path.join(self.checkpoint_dir, RESIZE_PLAN), plan_doc
                )
            _telemetry.counter("frontdoor.resizes_total").inc()
            _telemetry.event(
                "frontdoor.resize", checkpoint=path,
                **{k: plan[k] for k in ("nproc", "capacity", "rung", "reason")
                   if k in plan},
            )
        self.close()

    def elastic_resume(self) -> bool:
        """Restore pool + ledgers from the newest valid checkpoint onto the
        CURRENT topology/capacity (module docstring).  Every rank calls it
        (the restore and re-admissions are collective-bearing and driven
        from the shared checkpoint metadata, so they are rank-uniform by
        construction).  Returns False when no checkpoint exists."""
        import jax
        import jax.numpy as jnp

        from ..models import _batched
        from ..parallel import grid as _grid
        from ..utils import checkpoint as _checkpoint

        if not self.checkpoint_dir:
            raise ValueError("elastic_resume needs a checkpoint_dir")
        latest = _checkpoint.latest_checkpoint(self.checkpoint_dir)
        if latest is None:
            return False
        meta = _checkpoint.checkpoint_meta(latest)
        serving_meta = meta.get("extra", {}).get("serving", {})
        if serving_meta.get("model") != self.loop.model_name:
            raise ValueError(
                f"checkpoint {latest!r} is a {serving_meta.get('model')!r} "
                f"pool; this loop serves {self.loop.model_name!r}"
            )
        gg = _grid.global_grid()
        err = _grid.elastic_topology_error(meta["grid"], gg.checkpoint_meta())
        if err is not None:
            raise ValueError(
                f"checkpoint {latest!r} cannot be elastically restored on "
                f"the current grid: {err}"
            )
        saved_slots = serving_meta.get("slots", [])
        blank = self._build_state(1.0)
        self.loop.prime(blank)
        zeros = tuple(jax.jit(jnp.zeros_like)(A) for A in blank)
        like = _batched.stack_states([zeros] * max(1, len(saved_slots)))
        state, step, extra = _checkpoint.restore_checkpoint(
            latest, like=like, strict=False, verify=True
        )
        active = [
            (k, rec) for k, rec in enumerate(extra["serving"]["slots"])
            if rec["active"]
        ]
        if len(active) > self.loop.capacity:
            raise RuntimeError(
                f"checkpoint holds {len(active)} live member(s) but the "
                f"resized pool has capacity {self.loop.capacity} — drain "
                f"below the target before scaling down."
            )
        for k, rec in active:
            self.loop.adopt(rec, _batched.member_state(state, k))
        self.loop.rounds = int(step)
        fd_meta = extra.get("frontdoor", {})
        adopted = {int(rec["member"]) for _, rec in active}
        requests = fd_meta.get("requests", {})
        # Still-QUEUED members (admitted by the door, never slotted, not
        # done) are rebuilt from their specs under their original ids —
        # the member state is a pure function of (grid, ic_scale), so
        # nothing is lost with the queue.  Sorted by member id: the
        # rank-uniform order every rank replays identically.
        queued = sorted(
            (
                (int(rec["member"]), rid, rec)
                for rid, rec in requests.items()
                if rec.get("member") is not None
                and rec.get("done") is None
                and int(rec["member"]) not in adopted
            ),
        )
        for member, _rid, rec in queued:
            params = rec["params"]
            self.loop.enqueue_restored(
                member,
                Request(
                    state=self._build_state(params.get("ic_scale", 1.0)),
                    max_steps=int(params["max_steps"]),
                    tenant=rec.get("tenant", "default"),
                    tol=params.get("tol"),
                    trace=_member_ctx(rec.get("trace")),
                ),
            )
        self.loop._next_member = max(
            self.loop._next_member,
            int(serving_meta.get("next_member", 0)),
        )
        # Belt and braces: a 202-accepted request with NO member yet (its
        # spec was still pending when the resize checkpointed — the drain
        # normally empties that set under the refusal lock) is submitted
        # fresh from its spec; member-id assignment is deterministic, so
        # every rank replaying the same sorted ledger agrees.
        unsynced = sorted(
            (rid, rec) for rid, rec in requests.items()
            if rec.get("member") is None and rec.get("done") is None
        )
        for rid, rec in unsynced:
            params = rec["params"]
            member = self.loop.submit(Request(
                state=self._build_state(params.get("ic_scale", 1.0)),
                max_steps=int(params["max_steps"]),
                tenant=rec.get("tenant", "default"),
                tol=params.get("tol"),
                trace=_member_ctx(rec.get("trace")),
            ))
            rec["member"] = member
        if self.rank == 0:
            with self._lock:
                self._next_request = max(
                    self._next_request, int(fd_meta.get("next_request", 0))
                )
                self._expired_before = max(
                    self._expired_before,
                    int(fd_meta.get("expired_before", 0)),
                )
                for rid, rec in requests.items():
                    self._requests[rid] = {
                        "id": rid,
                        "tenant": rec.get("tenant", "default"),
                        "params": rec["params"],
                        "submitted_ts": rec.get("submitted_ts", time.time()),
                        "member": rec.get("member"),
                        "done": rec.get("done"),
                        "trace": rec.get("trace"),
                    }
            # members that already retired stay harvested; the restored
            # ledger answers /v1/result for them without their states
        self._seen_results.update(
            int(rec["member"]) for rec in requests.values()
            if rec.get("done") is not None and rec.get("member") is not None
        )
        self._publish_oldest_gauge()
        _telemetry.counter("frontdoor.resumes_total").inc()
        _telemetry.event(
            "frontdoor.resume", checkpoint=latest, mode="elastic",
            adopted=len(active), requeued=len(queued), rounds=int(step),
        )
        return True
