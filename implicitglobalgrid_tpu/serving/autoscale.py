"""Elastic autoscaling policy for the serving plane (ISSUE 12).

Capacity was frozen at `ServingLoop` construction; this module decides
when it should not be.  The mechanism is deliberately split:

* `decide` — a PURE function ``(view, policy, rung) -> "up"|"down"|"hold"``
  over the same gauge view admission control uses
  (`admission.gauge_view`): sustained queue growth or a round-p99 breach
  votes ``up``; an empty queue with occupancy that fits the next rung down
  votes ``down``.  Deterministic given a synthetic snapshot — the tier-1
  contract (`tests/test_frontdoor.py`).
* `Autoscaler` — the stateful shell: a ladder of `Rung`\\ s (process count
  x slot capacity), a sustain counter (``IGG_AUTOSCALE_SUSTAIN``
  consecutive identical verdicts before anything moves — one bursty
  heartbeat must not resize a cluster), and the drain bookkeeping for
  scale-downs.  It subscribes to the `utils.liveplane` rule engine the
  same way `resilience.RunGuard` does (`FrontDoor` wires it), so anomaly
  alerts are visible in its status even though resize verdicts come only
  from the sustained gauge policy.

Execution is NOT here: a resize changes the process topology, which a
live process cannot do to itself.  The verdict travels rank-0 → everyone
through the front door's control-plane broadcast, every rank writes the
batched checkpoint (`utils.checkpoint.save_checkpoint`), rank 0 publishes
a ``resize.json`` plan, and all ranks exit with
`frontdoor.RESIZE_STATUS` for the supervisor to relaunch at the target
topology — the same supervised-restart mechanism the soak
``elastic_failover`` drill proves, pointed at growth instead of failure
(`scripts/soak.py` ``frontdoor`` scenario; docs/serving.md has the state
machine).
"""

from __future__ import annotations

import dataclasses

from ..utils import config as _config

#: verdicts of `decide`
VERDICTS = ("up", "down", "hold")


@dataclasses.dataclass(frozen=True)
class Rung:
    """One capacity rung: process topology x slot-pool capacity."""

    nproc: int
    capacity: int


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The resize thresholds.

    ``ladder`` — ascending `Rung` tuple; the autoscaler only ever moves one
    rung at a time.  ``queue_high`` — queue depth that votes ``up`` (None =
    the live pool capacity).  ``p99_high_s`` — round-latency p99 that votes
    ``up`` (None = queue-only).  ``sustain`` — consecutive identical
    non-hold verdicts before the move commits.
    """

    ladder: tuple[Rung, ...]
    queue_high: int | None = None
    p99_high_s: float | None = None
    sustain: int = 2

    def __post_init__(self):
        if not self.ladder:
            raise ValueError("AutoscalePolicy needs a non-empty ladder")
        if self.sustain < 1:
            raise ValueError(f"sustain must be >= 1 (got {self.sustain})")

    @classmethod
    def from_env(cls, ladder, **kw) -> "AutoscalePolicy":
        """Env tier: ``IGG_AUTOSCALE_QUEUE_HIGH``, ``IGG_AUTOSCALE_SUSTAIN``
        (explicit kwargs win, the config precedence)."""
        kw.setdefault("queue_high", _config.autoscale_queue_high_env())
        kw.setdefault("sustain", _config.autoscale_sustain_env() or 2)
        return cls(ladder=tuple(ladder), **kw)


def decide(view: dict, policy: AutoscalePolicy, rung: int) -> str:
    """PURE one-observation verdict: ``"up"``, ``"down"`` or ``"hold"``.

    ``view`` is an `admission.gauge_view`-shaped dict (``queue_depth``,
    ``active_members``, ``capacity``, ``round_p99_s``).  ``up`` needs a
    higher rung to exist and either the queue at/above ``queue_high`` or
    the round p99 past ``p99_high_s``; ``down`` needs a lower rung, an
    empty queue, and occupancy that fits that rung's capacity.  No clocks,
    no globals — same inputs, same verdict.
    """
    if not 0 <= rung < len(policy.ladder):
        raise ValueError(
            f"rung {rung} outside the ladder (len {len(policy.ladder)})"
        )
    queue_depth = int(view.get("queue_depth") or 0)
    active = int(view.get("active_members") or 0)
    queue_high = policy.queue_high
    if queue_high is None:
        queue_high = max(1, int(view.get("capacity") or 1))
    p99 = view.get("round_p99_s")
    if rung + 1 < len(policy.ladder) and (
        queue_depth >= queue_high
        or (policy.p99_high_s is not None and p99 is not None
            and p99 > policy.p99_high_s)
    ):
        return "up"
    if (
        rung > 0
        and queue_depth == 0
        and active <= policy.ladder[rung - 1].capacity
    ):
        return "down"
    return "hold"


class Autoscaler:
    """Sustain-gated ladder walker (module docstring).

    `observe` is called at heartbeat cadence with a gauge view; once
    ``policy.sustain`` consecutive observations agree on a non-hold
    verdict it returns an action dict ``{"action", "target": Rung,
    "rung": target index, "evidence": view}`` — exactly once per episode
    (the streak resets after committing).  The caller owns execution and
    the drain handshake (`FrontDoor`); ``rung`` is fixed per process
    lifetime because a rung change IS a process restart.
    """

    def __init__(self, policy: AutoscalePolicy, rung: int = 0):
        if not 0 <= rung < len(policy.ladder):
            raise ValueError(
                f"rung {rung} outside the ladder (len {len(policy.ladder)})"
            )
        self.policy = policy
        self.rung = int(rung)
        self._streak_verdict = "hold"
        self._streak = 0
        self.last_alert: dict | None = None
        self.last_verdict = "hold"

    @property
    def current(self) -> Rung:
        return self.policy.ladder[self.rung]

    def on_alert(self, alert: dict) -> None:
        """Rule-engine subscription surface (the RunGuard mechanism):
        alerts inform the status view; resizes stay gauge-driven."""
        self.last_alert = alert

    def observe(self, view: dict) -> dict | None:
        verdict = decide(view, self.policy, self.rung)
        self.last_verdict = verdict
        if verdict == self._streak_verdict:
            self._streak += 1
        else:
            self._streak_verdict = verdict
            self._streak = 1
        if verdict == "hold" or self._streak < self.policy.sustain:
            return None
        self._streak_verdict, self._streak = "hold", 0
        target_rung = self.rung + (1 if verdict == "up" else -1)
        target = self.policy.ladder[target_rung]
        return {
            "action": verdict,
            "rung": target_rung,
            "target": {"nproc": target.nproc, "capacity": target.capacity},
            "evidence": dict(view),
        }

    def status(self) -> dict:
        # the incarnation's generation token rides the status view so a
        # supervisor (or /v1/status reader) can attribute a resize verdict
        # to the incarnation that produced it (docs/robustness.md)
        return {
            "rung": self.rung,
            "generation": _config.generation_env(),
            "nproc": self.current.nproc,
            "capacity": self.current.capacity,
            "ladder": [
                {"nproc": r.nproc, "capacity": r.capacity}
                for r in self.policy.ladder
            ],
            "sustain": self.policy.sustain,
            "last_verdict": self.last_verdict,
            "streak": self._streak,
            "last_alert": self.last_alert,
        }
