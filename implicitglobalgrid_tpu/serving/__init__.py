"""Batched multi-simulation serving (ISSUE 8 + 12; ROADMAP item 3).

The steady-state loop for the million-users workload: a fixed-capacity slot
pool holds B independent simulations batched along a leading ensemble axis
(`models._batched`), one vmapped SPMD step advances every active member per
round at ONE collective pair per exchanged dimension (B for the price of
1), and a request queue admits/retires members MID-FLIGHT — per-member
step budgets, per-member convergence masks (the porous PT residual), and
per-member guard handling (a NaN in member k evicts or rolls back member
k, never the batch).

Since ISSUE 12 the pool speaks to the outside world: `FrontDoor` is the
HTTP entry (``POST /v1/submit`` → `AdmissionController` — per-tenant
token-bucket quotas, queue/SLO backpressure, cheap 429s with a
cadence-derived ``Retry-After``), and `Autoscaler` grows/shrinks the
topology under load through checkpoint + supervised restart + elastic
resume (docs/serving.md).

Public surface: `Request`, `MemberResult`, `ServingLoop` (see
`serving.loop`); `FrontDoor` (`serving.frontdoor`); `AdmissionController`,
`AdmissionPolicy` (`serving.admission`); `Autoscaler`, `AutoscalePolicy`,
`Rung` (`serving.autoscale`).  Telemetry names and the event schema are
documented in docs/observability.md, the knobs (``IGG_BATCH``,
``IGG_BATCH_ROUND_STEPS``, ``IGG_SERVE_PORT``, ``IGG_TENANT_QUOTA``, ...)
in docs/usage.md.
"""

from .admission import AdmissionController, AdmissionPolicy
from .autoscale import AutoscalePolicy, Autoscaler, Rung
from .frontdoor import RESIZE_STATUS, FrontDoor
from .loop import MemberResult, Request, ServingLoop

__all__ = [
    "Request",
    "MemberResult",
    "ServingLoop",
    "FrontDoor",
    "RESIZE_STATUS",
    "AdmissionController",
    "AdmissionPolicy",
    "Autoscaler",
    "AutoscalePolicy",
    "Rung",
]
