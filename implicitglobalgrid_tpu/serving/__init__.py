"""Batched multi-simulation serving (ISSUE 8; ROADMAP item 1).

The steady-state loop for the million-users workload: a fixed-capacity slot
pool holds B independent simulations batched along a leading ensemble axis
(`models._batched`), one vmapped SPMD step advances every active member per
round at ONE collective pair per exchanged dimension (B for the price of
1), and a request queue admits/retires members MID-FLIGHT — per-member
step budgets, per-member convergence masks (the porous PT residual), and
per-member guard handling (a NaN in member k evicts or rolls back member
k, never the batch).

Public surface: `Request`, `MemberResult`, `ServingLoop` (see
`serving.loop`); telemetry names and the event schema are documented in
docs/observability.md, the knobs (``IGG_BATCH``,
``IGG_BATCH_ROUND_STEPS``) in docs/usage.md.
"""

from .loop import MemberResult, Request, ServingLoop

__all__ = ["Request", "MemberResult", "ServingLoop"]
