"""The serving loop: slot pool + request queue over one batched SPMD step.

Design (ISSUE 8 tentpole):

* **Slot pool** — the batched state ``(B, *block)`` per field IS the pool;
  slot ``k`` holds member ``k``'s fields (zeros when free).  Admission
  writes a member's state into its slot on device
  (`models._batched.set_member_state`), retirement slices it back out
  (`member_state`) — members enter and leave MID-FLIGHT while the others
  keep stepping.
* **One step, every member** — each round advances the whole pool through
  ONE compiled vmapped multi-step (`make_multi_step(..., batch=True)`):
  the collective budget is B-invariant, so a full pool costs the same
  fabric traffic as a single simulation.  Members that must not advance
  (free slots, converged members) are masked AFTER the step
  (`select_members`): their state is bit-frozen, the reference semantics
  of "this member is not running".
* **Per-member convergence** — the porous PT residual criterion
  (`porous_convection3d.make_batched_residual`) retires member ``k`` when
  its residual drops under ``Request.tol``; diffusion/acoustic members
  retire on their step budget (``Request.max_steps``).
* **Per-member guards** — one batched finite probe per round
  (`check_members_finite`); a non-finite member is rolled back to its last
  good per-slot snapshot (``guard_policy="rollback"``) or evicted
  (``"evict"``, the default) — the batch never pays for one member's NaN.
* **Batched checkpoints** — ``checkpoint_every=N`` rounds writes the whole
  pool (plus the serving metadata needed to resume: per-slot member ids,
  tenants, step counts) through `utils.checkpoint.save_checkpoint`; a new
  loop pointed at the same directory resumes mid-flight members.

Telemetry (docs/observability.md): gauges ``serving.active_members``,
``serving.queue_depth``; counters ``serving.admitted_total``,
``serving.retired_total``, ``serving.converged_total``,
``serving.evicted_total``, ``serving.rollbacks_total``,
``serving.rounds``, ``serving.tenant.<tenant>.steps`` (cardinality-capped
via `telemetry.tenant_counter`: past ``IGG_TELEMETRY_MAX_TENANTS``
distinct tenants, overflow folds into ``serving.tenant.__other__.steps``
— tenant strings arrive from requests, so the series count must be
bounded); histogram ``serving.member_t_eff_gbs`` (per-member T_eff: the
member's must-stream bytes over the round wall time — every member of a
round shares the wall time, which is the point of batching).  Events:
``serving.admit`` / ``serving.retire`` / ``serving.converged`` /
``serving.evict`` / ``serving.rollback``, each tagged with member id,
slot, tenant and step count.  Each round runs inside an
``igg.serving.round`` host span (member/slot/tenant-tagged) and, at the
``IGG_HEARTBEAT_EVERY`` round cadence on multi-process grids, drives the
all-ranks skew probe (`utils.tracing.skew_probe`).

Live plane (ISSUE 11, `utils.liveplane`): construction brings the
per-rank scrape server up when ``IGG_METRICS_PORT`` is set; every round
records the ``serving.round_seconds`` histogram (whose rolling window
becomes the ``slo.serving.round_seconds.*`` gauges — the SLO latency
surface admission control will key on), each convergence sweep publishes
the ``serving.pt_residual_min`` gauge (the convergence-stall rule's
input), the heartbeat-cadence rounds run the anomaly-rule tick, and the
loop polls the alert stream: a CRITICAL alert fires a
``serving.alert_escalation`` event and — on single-process pools — an
immediate out-of-cadence member-finite sweep through the existing evict
machinery.  Multi-process pools stop at the event: slot mutations keyed
on a rank-LOCAL alert would diverge the SPMD pool state across ranks
(exactly the deadlock class ``igg.analysis``'s collective-consistency
pass exists to catch), so cross-rank escalation stays an operator
decision made on the `scripts/igg_top.py` cluster view.
"""

from __future__ import annotations

import collections
import dataclasses
import time

import numpy as np

from ..models import _batched
from ..utils import config as _config
from ..utils import liveplane as _liveplane
from ..utils import telemetry as _telemetry
from ..utils import tracing as _tracing
from ..utils.telemetry import process_count as _process_count

#: Per-model serving adapter: state field names and which fields the
#: per-member T_eff bytes model counts (`telemetry.teff_bytes` convention),
#: plus whether the model has a PT residual to mask convergence on.
_MODEL_INFO = {
    "diffusion3d": dict(names=("T", "Cp"), stream=slice(0, 1), residual=False),
    "acoustic3d": dict(
        names=("P", "Vx", "Vy", "Vz"), stream=slice(0, 4), residual=False
    ),
    "porous_convection3d": dict(
        names=("T", "Pf", "qDx", "qDy", "qDz"), stream=slice(0, 5),
        residual=True,
    ),
}


@dataclasses.dataclass
class Request:
    """One tenant's simulation request.

    ``state`` is the member's initial state tuple (unbatched global-block
    fields matching the loop's model); ``max_steps`` the retirement budget
    (>= 1); ``tol`` (models with a residual) retires early once the
    per-member PT residual drops below it.

    Budgets retire at ROUND granularity: the pool advances
    ``steps_per_round`` steps per round for every active member, so a
    member retires at the first round boundary where ``steps >=
    max_steps`` — up to ``steps_per_round - 1`` steps past the budget
    (``MemberResult.steps`` reports the actual count).  Pick a
    ``steps_per_round`` that divides your budgets for exact step counts.

    ``trace`` (optional): the request's member-level trace context
    (``{"trace_id", "span_id"}`` — `utils.tracing`); it rides the slot,
    the round spans' member tags and the checkpoint slot metadata, so a
    traced request stays reconstructable across rounds and restarts.
    """

    state: tuple
    max_steps: int
    tenant: str = "default"
    tol: float | None = None
    trace: dict | None = None


@dataclasses.dataclass
class MemberResult:
    """A retired member: final state + how it ended.

    ``status``: ``"completed"`` (step budget reached), ``"converged"``
    (residual under ``tol``), or ``"evicted"`` (non-finite state; ``state``
    is None — poisoned fields are not handed back).
    """

    member: int
    tenant: str
    status: str
    steps: int
    state: tuple | None
    residual: float | None = None


@dataclasses.dataclass
class _Slot:
    member: int = -1
    tenant: str = ""
    max_steps: int = 0
    tol: float | None = None
    steps: int = 0
    active: bool = False
    snapshot: tuple | None = None
    snapshot_steps: int = 0
    rollbacks: int = 0
    trace: dict | None = None


class ServingLoop:
    """Fixed-capacity batched serving of one model (module docstring).

    ``model`` is a model module (`models.diffusion3d` / `acoustic3d` /
    `porous_convection3d`); ``params`` its `Params` (one physics/numerics
    config per pool — members vary by state, the ensemble contract).
    ``capacity`` defaults to ``IGG_BATCH`` (env) else 4;
    ``steps_per_round`` to ``IGG_BATCH_ROUND_STEPS`` else 1.
    ``step_kwargs`` pass through to ``make_multi_step`` (``exchange_every``,
    ``fused_k``, ...).  ``guard_policy``: ``"evict"`` | ``"rollback"`` |
    ``"off"``.  ``max_rollbacks`` bounds per-member rollbacks before the
    member is evicted anyway (a deterministic fault re-trips forever).
    """

    def __init__(
        self,
        model,
        params,
        *,
        capacity: int | None = None,
        steps_per_round: int | None = None,
        guard_policy: str = "evict",
        max_rollbacks: int = 3,
        checkpoint_every: int = 0,
        checkpoint_dir: str | None = None,
        step_kwargs: dict | None = None,
    ):
        name = model.__name__.rsplit(".", 1)[-1]
        if name not in _MODEL_INFO:
            raise ValueError(
                f"ServingLoop supports {sorted(_MODEL_INFO)}, got {name!r}"
            )
        if guard_policy not in ("evict", "rollback", "off"):
            raise ValueError(
                f"guard_policy must be 'evict', 'rollback' or 'off', got "
                f"{guard_policy!r}"
            )
        if capacity is None:
            capacity = _config.batch_env() or 4
        if steps_per_round is None:
            steps_per_round = _config.batch_round_steps_env() or 1
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1 (got {capacity})")
        if steps_per_round < 1:
            raise ValueError(
                f"steps_per_round must be >= 1 (got {steps_per_round})"
            )
        if checkpoint_every and not checkpoint_dir:
            raise ValueError(
                "checkpoint_every > 0 requires a checkpoint_dir"
            )
        self.model = model
        self.model_name = name
        self.info = _MODEL_INFO[name]
        self.params = params
        self.capacity = int(capacity)
        self.steps_per_round = int(steps_per_round)
        self.guard_policy = guard_policy
        self.max_rollbacks = int(max_rollbacks)
        self.checkpoint_every = int(checkpoint_every)
        self.checkpoint_dir = checkpoint_dir
        # Shadow-step audit cadence (the integrity plane; docs/
        # robustness.md).  A batched pool audits ONE sampled member per
        # audited round: the whole pool's round is re-executed through the
        # SAME compiled multi-step (no second program) and the sample is
        # bit-compared — round-robin over active slots, so a lying core
        # is caught within `capacity` audited rounds.  ``IGG_INTEGRITY=0``
        # force-disables, same pin as the run guard.
        every = _config.integrity_every_env() or 0
        if _config.integrity_enabled_env() is False:
            every = 0
        self.integrity_every = int(every)
        # donate=False: the raw step's inputs survive for the post-step
        # mask select (which donates both and recycles the buffers).
        self._step = model.make_multi_step(
            params, self.steps_per_round, donate=False, batch=True,
            **(step_kwargs or {}),
        )
        self._residual_fn = (
            model.make_batched_residual(params) if self.info["residual"]
            else None
        )
        self.slots = [_Slot() for _ in range(self.capacity)]
        # (member id, request) pairs awaiting a free slot
        self.queue: collections.deque[tuple[int, Request]] = collections.deque()
        self.results: dict[int, MemberResult] = {}
        # Bounded result retention (ISSUE 16 satellite): members whose
        # result a consumer has read (`mark_consumed` — the front door's
        # harvest calls it) become prunable; `_prune_results` applies the
        # IGG_RESULT_KEEP depth / IGG_RESULT_TTL_S age bound at each round
        # end.  Unconsumed results are never pruned — a retention knob
        # must not lose a result nobody has read yet.
        self._consumed: set[int] = set()
        self._result_ts: dict[int, float] = {}
        self.rounds = 0
        # Graceful drain (ISSUE 12, `serving.frontdoor`): when set, slots
        # with index >= drain_above are RETIRING — `_admit_from_queue`
        # stops placing members there, in-flight members finish normally,
        # and once `drained(capacity)` holds the pool can reshard down to
        # that capacity without dropping anyone.
        self.drain_above: int | None = None
        self._next_member = 0
        self._state = None  # built lazily from the first admitted state
        self._blank = None  # zero member state for freed slots
        self._sig = None    # pool field signature: ((global shape, dtype), ...)
        # Live plane (docs/observability.md): scrape endpoint up as soon as
        # the pool exists (no-op unless IGG_METRICS_PORT is set), alert
        # stream polled from this cursor each round.  The cursor starts at
        # the engine's CURRENT seq: alerts fired before this pool existed
        # belong to earlier runs and must not replay as escalations.
        self._alert_seq, _ = _liveplane.alerts_since(float("inf"))
        _liveplane.ensure_server()
        self._publish_gauges()

    # -- pool state -----------------------------------------------------------

    @property
    def active_members(self) -> int:
        return sum(s.active for s in self.slots)

    def _publish_gauges(self) -> None:
        """The ONE writer of the pool-occupancy gauge family (ISSUE 12
        satellite: ``serving.queue_depth`` used to be set in both `submit`
        and the admit path — every mutation now routes through here, and
        retirement updates the gauges immediately instead of at the next
        admit).  ``/healthz`` serves these in its ``serving`` section; the
        front door's admission controller and autoscaler key on them."""
        _telemetry.gauge("serving.queue_depth").set(len(self.queue))
        _telemetry.gauge("serving.active_members").set(self.active_members)
        _telemetry.gauge("serving.capacity").set(self.capacity)

    def drained(self, capacity: int) -> bool:
        """No member occupies a slot at/above ``capacity`` — the scale-down
        readiness check (`serving.autoscale`)."""
        return all(not s.active for s in self.slots[capacity:])

    def _ensure_pool(self, like_state: tuple) -> None:
        """Build the B-slot pool from the first member's field signature."""
        if self._state is not None:
            return
        import jax
        import jax.numpy as jnp

        zeros = tuple(
            jax.jit(jnp.zeros_like)(A) for A in like_state
        )
        self._blank = zeros
        self._state = _batched.stack_states([zeros] * self.capacity)
        if self._sig is None:
            # prime() path: the pool's signature comes from the priming
            # state, so the FIRST submit after a resume is validated
            # against the actual pool, not adopted blindly.
            self._sig = self._state_sig(like_state)

    def _mask(self) -> np.ndarray:
        return np.asarray([s.active for s in self.slots], bool)

    @staticmethod
    def _state_sig(state) -> tuple:
        return tuple(
            (tuple(np.shape(A)), str(getattr(A, "dtype", type(A))))
            for A in state
        )

    def _check_signature(self, state) -> None:
        """Reject a member state that does not match the pool's field
        signature AT SUBMIT TIME: `set_member_state` zips fields (silent
        truncation) and casts dtypes (silently breaking bit-exactness), so
        a mismatch must never reach admission.  The first state seen
        (first submit or `prime`) defines the signature."""
        sig = self._state_sig(state)
        if self._sig is None:
            nf = len(self.info["names"])
            if len(sig) != nf:
                raise ValueError(
                    f"{self.model_name} state has fields "
                    f"{self.info['names']}; got {len(sig)} field(s)."
                )
            self._sig = sig
            return
        if sig != self._sig:
            raise ValueError(
                f"request state signature {sig} does not match the pool's "
                f"{self._sig} — one pool serves one field signature "
                f"(same grid, same dtype)."
            )

    # -- admission ------------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Queue one request; returns its member id.  Admission into a free
        slot happens immediately when one is available, else at the next
        round boundary after a retirement frees one.  Invalid requests are
        rejected HERE, before anything is queued or written into the pool
        — a bad request must never detonate mid-service half-admitted."""
        if request.tol is not None and not self.info["residual"]:
            raise ValueError(
                f"{self.model_name} has no PT residual; tol applies to "
                f"porous members only (use max_steps)."
            )
        if int(request.max_steps) < 1:
            raise ValueError(
                f"max_steps must be >= 1 (got {request.max_steps})"
            )
        self._check_signature(request.state)
        member = self._next_member
        self._next_member += 1
        self.queue.append((member, request))
        self._admit_from_queue()
        return member

    def enqueue_restored(self, member: int, request: Request) -> None:
        """Re-queue a member under its ORIGINAL id (the front door's
        elastic-resume path: members that were still queued when a resize
        checkpointed are rebuilt from their request parameters and must
        keep their ids so results stay addressable).  Validation mirrors
        `submit`; the id counter advances past the restored id."""
        if int(request.max_steps) < 1:
            raise ValueError(
                f"max_steps must be >= 1 (got {request.max_steps})"
            )
        self._check_signature(request.state)
        self._next_member = max(self._next_member, int(member) + 1)
        self.queue.append((int(member), request))
        self._admit_from_queue()

    def adopt(self, rec: dict, state: tuple) -> int:
        """Place a RESTORED member (slot metadata dict from
        `_serving_meta`, state sliced out of a restored pool) into the
        first free non-retiring slot, preserving its member id, tenant,
        step count and budget — the elastic-resume path that re-admits
        live members into a resized pool without losing convergence
        state.  Returns the slot index; raises when no slot is free."""
        self._check_signature(tuple(state))
        self._ensure_pool(tuple(state))
        for k, slot in enumerate(self.slots):
            if slot.active:
                continue
            if self.drain_above is not None and k >= self.drain_above:
                continue
            self._state = _batched.set_member_state(
                self._state, tuple(state), k
            )
            self.slots[k] = _Slot(
                member=int(rec["member"]), tenant=rec.get("tenant", ""),
                max_steps=int(rec["max_steps"]), tol=rec.get("tol"),
                steps=int(rec.get("steps", 0)), active=True,
                trace=rec.get("trace"),
            )
            if self.guard_policy == "rollback":
                self.slots[k].snapshot = _batched.member_state(self._state, k)
                self.slots[k].snapshot_steps = self.slots[k].steps
            self._next_member = max(self._next_member, int(rec["member"]) + 1)
            _telemetry.event(
                "serving.admit", member=int(rec["member"]), slot=k,
                tenant=rec.get("tenant", ""), max_steps=int(rec["max_steps"]),
                tol=rec.get("tol"), resumed=True,
            )
            self._publish_gauges()
            return k
        raise RuntimeError(
            f"adopt: no free slot for restored member {rec.get('member')} "
            f"(capacity {self.capacity}, drain_above {self.drain_above}) — "
            f"drain the pool below the target capacity before resizing down."
        )

    def _admit_from_queue(self) -> None:
        for k, slot in enumerate(self.slots):
            if not self.queue:
                break
            if slot.active:
                continue
            if self.drain_above is not None and k >= self.drain_above:
                continue  # retiring slot: never admit into it again
            member, req = self.queue.popleft()
            self._ensure_pool(req.state)
            self._state = _batched.set_member_state(
                self._state, req.state, k
            )
            tol = req.tol
            self.slots[k] = _Slot(
                member=member, tenant=req.tenant,
                max_steps=int(req.max_steps), tol=tol, active=True,
                trace=req.trace,
            )
            if self.guard_policy == "rollback":
                self.slots[k].snapshot = _batched.member_state(self._state, k)
                self.slots[k].snapshot_steps = 0
            _telemetry.counter("serving.admitted_total").inc()
            _telemetry.event(
                "serving.admit", member=member, slot=k, tenant=req.tenant,
                max_steps=int(req.max_steps), tol=tol,
            )
        self._publish_gauges()

    # -- retirement -----------------------------------------------------------

    def _retire(self, k: int, status: str, residual: float | None = None):
        slot = self.slots[k]
        state = (
            None if status == "evicted"
            else _batched.member_state(self._state, k)
        )
        self.results[slot.member] = MemberResult(
            member=slot.member, tenant=slot.tenant, status=status,
            steps=slot.steps, state=state, residual=residual,
        )
        self._result_ts[slot.member] = time.monotonic()
        _telemetry.counter("serving.retired_total").inc()
        etype = {
            "completed": "serving.retire",
            "converged": "serving.converged",
            "evicted": "serving.evict",
        }[status]
        if status == "converged":
            _telemetry.counter("serving.converged_total").inc()
        if status == "evicted":
            _telemetry.counter("serving.evicted_total").inc()
        _telemetry.event(
            etype, member=slot.member, slot=k, tenant=slot.tenant,
            steps=slot.steps, status=status, residual=residual,
        )
        # Free the slot: blank state so an idle slot can never leak the
        # retired member's fields into a future snapshot/result.
        self._state = _batched.set_member_state(self._state, self._blank, k)
        self.slots[k] = _Slot()
        self._publish_gauges()
        self._maybe_disarm_convergence()

    def mark_consumed(self, member: int) -> None:
        """Declare ``member``'s result read: it becomes prunable under the
        ``IGG_RESULT_KEEP`` / ``IGG_RESULT_TTL_S`` retention bounds.  The
        front door's harvest calls this per retirement; a standalone
        consumer that wants a bounded pool opts in the same way."""
        if member in self.results:
            self._consumed.add(member)

    def _prune_results(self) -> None:
        """Apply the retention bounds to CONSUMED results (round end).

        ``IGG_RESULT_KEEP`` keeps the newest N consumed results (0/unset
        = keep all, the pre-fleet behavior); ``IGG_RESULT_TTL_S`` drops a
        consumed result older than the bound regardless of the depth.
        Read per prune, like the other resilience knobs.  A member's full
        field state is the payload here — on a long-lived pool this dict
        IS the per-request memory leak the bounds close.
        """
        keep = _config.result_keep_env() or 0
        ttl = _config.result_ttl_env()
        if not keep and ttl is None:
            return
        consumed = sorted(m for m in self.results if m in self._consumed)
        doomed: list[int] = []
        if ttl is not None:
            now = time.monotonic()
            doomed += [
                m for m in consumed
                if now - self._result_ts.get(m, now) > ttl
            ]
        if keep:
            fresh = [m for m in consumed if m not in set(doomed)]
            if len(fresh) > keep:
                doomed += fresh[:-keep]
        for m in doomed:
            del self.results[m]
            self._consumed.discard(m)
            self._result_ts.pop(m, None)
        if doomed:
            _telemetry.counter("serving.results_pruned_total").inc(
                len(doomed)
            )
            _telemetry.event(
                "serving.results_pruned", members=doomed,
                kept=len(self.results),
            )

    def _maybe_disarm_convergence(self) -> None:
        if self._residual_fn is not None and not any(
            s.active and s.tol is not None for s in self.slots
        ):
            # The last tol-watched member just left: disarm the
            # convergence-stall rule (its input gauge would otherwise
            # freeze at the retiree's final residual).
            _telemetry.gauge("serving.pt_residual_watched").set(0)

    # -- the round ------------------------------------------------------------

    def run_round(self) -> None:
        """One serving round: step active members, guard, retire, admit.

        The round is wrapped in an ``igg.serving.round`` host span tagged
        with the active (member, slot, tenant) triples, and — at the
        ``IGG_HEARTBEAT_EVERY`` round cadence on multi-process grids —
        runs the all-ranks skew probe over the round wall time
        (`utils.tracing.skew_probe`; every rank drives the identical
        round sequence, so the probe's collective cadence agrees by
        construction).
        """
        self._admit_from_queue()
        mask = self._mask()
        members = [
            {
                "member": s.member, "slot": k, "tenant": s.tenant,
                **({"trace": s.trace} if s.trace else {}),
            }
            for k, s in enumerate(self.slots)
            if s.active
        ]
        # The round advances MANY requests at once: the span carries every
        # active request's context (the multi-request form), so anything
        # nested under the round — checkpoint saves, audits, host-side
        # exchanges — inherits the same trace_ids ambiently.
        round_ctx = None
        trace_ids = sorted({
            m["trace"]["trace_id"] for m in members if "trace" in m
        })
        if trace_ids:
            round_ctx = {"trace_ids": trace_ids}
        with _tracing.use_context(round_ctx), _tracing.trace_span(
            "igg.serving.round", round=self.rounds, members=members,
            queued=len(self.queue),
        ):
            dt = 0.0
            if self._state is not None and mask.any():
                t0 = time.perf_counter()
                new = self._step(*self._state)
                if (
                    self.integrity_every
                    and (self.rounds + 1) % self.integrity_every == 0
                ):
                    # Before select_members: the mask select donates both
                    # the stepped output and the pre-step state, so the
                    # audit's re-execution must run while both survive.
                    self._audit_member(new, mask)
                # Masking AFTER the step bit-freezes non-running members;
                # the step itself ran every slot (that is what batching
                # means — the flops of idle slots are the price of the
                # shared program).
                self._state = _batched.select_members(mask, new, self._state)
                import jax

                jax.block_until_ready(self._state)
                dt = time.perf_counter() - t0
                # The serving-round latency surface: its rolling window is
                # the slo.serving.round_seconds.* gauge family (the SLO the
                # network-facing plane keys admission on — ROADMAP item 3).
                _telemetry.histogram("serving.round_seconds").record(dt)
                for k, slot in enumerate(self.slots):
                    if slot.active:
                        slot.steps += self.steps_per_round
                        # Cardinality-capped per-tenant attribution: tenant
                        # strings come from requests, so the series count
                        # must be bounded (IGG_TELEMETRY_MAX_TENANTS).
                        _telemetry.tenant_counter(slot.tenant).inc(
                            self.steps_per_round
                        )
                if dt > 0:
                    from ..utils.telemetry import teff_bytes

                    member_bytes = teff_bytes(
                        self._blank[self.info["stream"]]
                    ) * self.steps_per_round
                    gbs = member_bytes / dt / 1e9
                    for k, slot in enumerate(self.slots):
                        if slot.active:
                            _telemetry.histogram(
                                "serving.member_t_eff_gbs"
                            ).record(gbs)
                self._guard(mask)
                self._convergence()
            # Step-budget retirement (after guard: never hand back unguarded
            # state) and back-fill from the queue.
            for k, slot in enumerate(self.slots):
                if slot.active and slot.steps >= slot.max_steps:
                    self._retire(k, "completed")
            self.rounds += 1
            _telemetry.counter("serving.rounds").inc()
            if _telemetry.enabled():
                _telemetry.note_progress("serving.round", self.rounds)
                hb = _config.heartbeat_every_env() or 0
                # The gate must be rank-uniform (the probe is a collective):
                # rounds and mask derive from the deterministic admit/retire
                # sequence every rank drives identically — never from a
                # locally measured time.
                if hb and self.rounds % hb == 0:
                    if mask.any():
                        _tracing.skew_probe(dt / self.steps_per_round)
                    # The live-plane tick is strictly LOCAL (slo gauges +
                    # anomaly rules — no collectives), so it needs no
                    # rank-uniformity gate.
                    rss = _telemetry.proc_rss_bytes()
                    if rss is not None:
                        _telemetry.gauge("proc.rss_bytes").set(rss)
                    _liveplane.heartbeat_tick(model="serving")
                # Alert stream: a CRITICAL in-flight anomaly escalates into
                # the guard/evict machinery instead of scrolling past.
                self._alert_seq, fresh = _liveplane.alerts_since(
                    self._alert_seq
                )
                for alert in fresh:
                    if alert.get("severity") == "critical":
                        self._escalate(alert)
            if (
                self.checkpoint_every
                and self.rounds % self.checkpoint_every == 0
                and self._state is not None
            ):
                self._save_checkpoint()
            self._admit_from_queue()
            self._prune_results()

    def _audit_member(self, new, mask: np.ndarray) -> None:
        """Shadow-step audit of ONE sampled member (integrity plane).

        Re-executes the round's multi-step from the retained pre-step pool
        state (``donate=False`` keeps it alive) through the same compiled
        program and bit-compares the sampled member's fields
        (`integrity.audit_fields`).  The sample is round-robin over the
        ACTIVE slots keyed on the deterministic round counter — rank-
        uniform by construction, so the audit's replicated bit-compare
        collective fires on every rank together.  A mismatch is silent
        data corruption caught in compute: dump the ``reason=sdc`` flight
        bundle naming the implicated rank and raise — the pool dies loud,
        the fleet controller quarantines its device subset
        (`fleet.policy.decide_pool` kind ``sdc``).
        """
        active = [
            k for k, s in enumerate(self.slots) if s.active and mask[k]
        ]
        if not active:
            return
        k = active[self.rounds % len(active)]
        from ..integrity import IntegrityError, audit_fields

        redone = self._step(*self._state)
        report = audit_fields(
            _batched.member_state(tuple(new), k),
            _batched.member_state(tuple(redone), k),
            names=self.info["names"],
        )
        _telemetry.counter("integrity.audits").inc()
        if report.ok:
            return
        slot = self.slots[k]
        _telemetry.counter("integrity.audit_mismatches").inc()
        _telemetry.event(
            "integrity.audit_mismatch", detector="shadow_audit",
            round=self.rounds, member=slot.member, slot=k,
            tenant=slot.tenant, fields=list(report.bad_blocks),
            implicated_ranks=list(report.implicated_ranks),
        )
        implicated = (
            report.implicated_ranks[0] if report.implicated_ranks else None
        )
        _tracing.dump_flight_recorder(
            "sdc", detector="shadow_audit", round=self.rounds,
            member=slot.member, slot=k, implicated_rank=implicated,
            implicated_ranks=list(report.implicated_ranks),
            report=report.summary(),
        )
        raise IntegrityError(
            f"silent data corruption: serving round {self.rounds} member "
            f"{slot.member} (slot {k}) does not bit-reproduce on "
            f"re-execution — {report.summary()}",
            detector="shadow_audit", implicated_rank=implicated,
            step=self.rounds,
        )

    def _guard(self, mask: np.ndarray) -> None:
        if self.guard_policy == "off":
            return
        bad = _batched.check_members_finite(self._state)
        for k in np.flatnonzero(bad & mask):
            slot = self.slots[int(k)]
            if (
                self.guard_policy == "rollback"
                and slot.snapshot is not None
                and slot.rollbacks < self.max_rollbacks
            ):
                slot.rollbacks += 1
                self._state = _batched.set_member_state(
                    self._state, slot.snapshot, int(k)
                )
                slot.steps = slot.snapshot_steps
                _telemetry.counter("serving.rollbacks_total").inc()
                _telemetry.event(
                    "serving.rollback", member=slot.member, slot=int(k),
                    tenant=slot.tenant, to_steps=slot.snapshot_steps,
                    rollbacks=slot.rollbacks,
                )
            else:
                self._retire(int(k), "evicted")
        if self.guard_policy == "rollback":
            # Refresh per-slot snapshots from guard-passed state only.
            still = ~_batched.check_members_finite(self._state) if bad.any() \
                else ~bad
            for k, slot in enumerate(self.slots):
                if slot.active and still[k]:
                    slot.snapshot = _batched.member_state(self._state, k)
                    slot.snapshot_steps = slot.steps

    def _escalate(self, alert: dict) -> None:
        """React to one CRITICAL live-plane alert (module docstring): event
        always; on single-process pools additionally force an immediate
        member-finite sweep through the evict machinery (rank-local alerts
        must never mutate the SPMD pool state on multi-process grids)."""
        _telemetry.counter("serving.alert_escalations").inc()
        _telemetry.event(
            "serving.alert_escalation",
            rule=alert.get("rule"),
            severity=alert.get("severity"),
            evidence=alert.get("evidence"),
        )
        if self._state is None or _process_count() > 1:
            return
        mask = self._mask()
        if not mask.any():
            return
        if self.guard_policy == "off":
            # the per-round sweep is off: run one forced evict-mode sweep
            bad = _batched.check_members_finite(self._state)
            for k in np.flatnonzero(bad & mask):
                self._retire(int(k), "evicted")
        else:
            self._guard(mask)

    def _convergence(self) -> None:
        if self._residual_fn is None:
            return
        if not any(s.active and s.tol is not None for s in self.slots):
            # Nothing watched: zero the population gauge so the
            # convergence-stall rule stands down instead of chewing on the
            # last retired member's frozen residual forever.
            _telemetry.gauge("serving.pt_residual_watched").set(0)
            return
        res = np.asarray(self._residual_fn(*self._state))
        watched = [
            float(res[k])
            for k, slot in enumerate(self.slots)
            if slot.active and slot.tol is not None
        ]
        if watched:
            # The convergence-stall anomaly rule's input
            # (utils.liveplane.ConvergenceStallRule): the best residual
            # still being driven toward a tolerance this round, plus how
            # many members it speaks for (0 disarms the rule).
            _telemetry.gauge("serving.pt_residual_min").set(min(watched))
            _telemetry.gauge("serving.pt_residual_watched").set(len(watched))
        for k, slot in enumerate(self.slots):
            if (
                slot.active
                and slot.tol is not None
                and float(res[k]) < slot.tol
            ):
                self._retire(k, "converged", residual=float(res[k]))

    def run(self, max_rounds: int | None = None) -> dict[int, MemberResult]:
        """Drive rounds until the queue and the pool are empty (or
        ``max_rounds`` is hit).  Returns the results map."""
        n = 0
        while (self.queue or self.active_members) and (
            max_rounds is None or n < max_rounds
        ):
            self.run_round()
            n += 1
        if _telemetry.enabled() and not (self.queue or self.active_members):
            # A drained pool is not a stalled one: mark the progress record
            # done so the live plane's step-stall rule goes quiet while the
            # loop idles between request bursts.
            _telemetry.note_progress("serving.round", self.rounds, done=True)
        return self.results

    # -- batched checkpointing ------------------------------------------------

    def _serving_meta(self) -> dict:
        return {
            "serving": {
                "model": self.model_name,
                "rounds": self.rounds,
                "next_member": self._next_member,
                "slots": [
                    {
                        "member": s.member, "tenant": s.tenant,
                        "max_steps": s.max_steps, "tol": s.tol,
                        "steps": s.steps, "active": s.active,
                        "trace": s.trace,
                    }
                    for s in self.slots
                ],
            }
        }

    def _save_checkpoint(self) -> str:
        from ..utils import checkpoint as _ckpt

        return _ckpt.save_checkpoint(
            self.checkpoint_dir, self._state, self.rounds,
            extra=self._serving_meta(),
        )

    def prime(self, like_state: tuple) -> None:
        """Build the (empty) slot pool from one member state's field
        signature WITHOUT admitting anything — the public priming step
        `resume()` needs (restore requires a ``like=`` pool of the right
        shapes; a submitted request must never be the donor, its state
        would be clobbered by the restored pool)."""
        self._ensure_pool(tuple(like_state))

    def resume(self) -> bool:
        """Restore pool + slot metadata from ``checkpoint_dir`` (strict
        same-topology restore — a serving pool lives on one deployment).
        Returns True when a checkpoint was found.  Queue contents are not
        persisted (requests not yet admitted belong to the caller).
        Requires a `prime`-d, still-EMPTY pool: resuming over live members
        would silently destroy them, so that is refused."""
        from ..utils import checkpoint as _ckpt

        latest = _ckpt.latest_checkpoint(self.checkpoint_dir)
        if latest is None:
            return False
        if self._state is None:
            raise RuntimeError(
                "resume() needs the pool built first: call "
                "loop.prime(member_state) with one state of the right "
                "signature before resuming."
            )
        if self.active_members or self.queue:
            raise RuntimeError(
                "resume() would overwrite live members: restore into a "
                "fresh loop (prime + resume) before submitting requests."
            )
        state, rounds, extra = _ckpt.restore_checkpoint(
            latest, like=self._state, strict=True, verify=False
        )
        meta = extra.get("serving", {})
        if meta.get("model") != self.model_name:
            raise ValueError(
                f"checkpoint is a {meta.get('model')!r} pool, this loop "
                f"serves {self.model_name!r}"
            )
        self._state = state
        self.rounds = int(rounds)
        self._next_member = int(meta.get("next_member", self._next_member))
        for k, rec in enumerate(meta.get("slots", [])[: self.capacity]):
            self.slots[k] = _Slot(
                member=int(rec["member"]), tenant=rec["tenant"],
                max_steps=int(rec["max_steps"]), tol=rec["tol"],
                steps=int(rec["steps"]), active=bool(rec["active"]),
                trace=rec.get("trace"),
            )
            if self.guard_policy == "rollback" and self.slots[k].active:
                self.slots[k].snapshot = _batched.member_state(self._state, k)
                self.slots[k].snapshot_steps = self.slots[k].steps
        self._publish_gauges()
        return True
