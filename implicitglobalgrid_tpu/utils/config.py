"""Environment-variable configuration tier.

The reference reads ``IGG_*`` env vars once at `init_global_grid`
(`/root/reference/src/init_global_grid.jl:51-68`) as the deploy-time
configuration tier below the kwargs tier.  Its specific knobs
(``IGG_CUDAAWARE_MPI[_DIMX/Y/Z]``, ``IGG_ROCMAWARE_MPI*``,
``IGG_LOOPVECTORIZATION*``) toggle GPU-direct MPI transport and CPU
vectorization per dimension — both N/A on TPU, where `collective_permute`
always moves HBM→HBM over ICI and packing is compiled (SURVEY.md §2.3).

The *mechanism* carries over with the TPU-meaningful knobs:

========================  ====================================================
``IGG_DEVICE_TYPE``       default ``device_type`` (``auto|tpu|cpu|gpu``)
``IGG_QUIET``             nonzero suppresses the rank-0 banner
``IGG_REORDER``           default mesh reorder flag (ICI-torus alignment)
``IGG_OVERLAP``           default overlap in every dimension (reference
                          kwarg ``overlapx/y/z`` default 2)
``IGG_DONATE``            default for `update_halo`'s global-array buffer
                          donation (0 = off; see `ops.halo._default_donate`
                          — read per call, not at init)
``IGG_COALESCE``          multi-field halo-exchange message combining
                          (``ops.halo``): unset = auto — whenever >= 2
                          fields share a dimension's exchange, their send
                          slabs pack into one buffer per dtype byte width
                          and ride ONE collective-permute pair per
                          (dimension, width group); ``0`` restores per-field
                          collectives (debug/attribution); bit-identical
                          either way.  Read per call/trace, like
                          ``IGG_DONATE`` (`ops.halo._default_coalesce`)
``IGG_VMEM_MB``           per-core VMEM capacity the fused kernels plan
                          against (`ops._fused_envelope.vmem_budget` — read
                          per kernel build, not at init)
``IGG_INIT_RETRIES``      retry attempts for `init_distributed`'s runtime
                          bring-up (int >= 0, default 3; coordinator races
                          are the #1 multi-host bring-up failure) — read
                          per call by `parallel.distributed.init_distributed`
``IGG_INIT_TIMEOUT_S``    overall deadline in seconds across all bring-up
                          attempts (number > 0, default 600)
``IGG_INIT_BACKOFF_S``    base of the exponential retry backoff in seconds
                          (number > 0, default 1; doubles per attempt with
                          seeded jitter — `utils.resilience.backoff_schedule`)
``IGG_WATCHDOG_S``        collective-hang watchdog: dump all-thread stacks
                          after this many seconds during grid/runtime
                          bring-up.  Unset = off around the init barrier but
                          `init_distributed` defaults to its bring-up
                          deadline; 0 = off everywhere

``IGG_GUARD_EVERY``       default ``guard_every`` for the models' time loops
                          (int >= 0; 0 = guards off) — run the NaN/Inf
                          field probe every N steps (`igg.check_fields`)
``IGG_GUARD_POLICY``      what a tripped guard does: ``raise`` (default) |
                          ``warn`` | ``rollback`` (restore last good state)
``IGG_CHECKPOINT_EVERY``  default checkpoint cadence for the models' time
                          loops (int >= 0; 0 = off)
``IGG_CHECKPOINT_DIR``    default checkpoint directory (`utils.checkpoint`)
``IGG_CHECKPOINT_KEEP``   checkpoint retention for the models' time loops
                          (int >= 0; 0 = keep every generation): after each
                          save, prune to the newest N generations — pruning
                          never deletes the only integrity-verified one
``IGG_FAULT_INJECT``      fault-injection knob for the test/soak harness:
                          ``init_flake:N`` | ``halo_corrupt:stepN[:blockB]``
                          | ``worker_crash:stepN[:procP]``
                          | ``stall:stepN[:procP]``
                          | ``ckpt_corrupt:stepN[:shardS]``
                          | ``ckpt_truncate:stepN[:shardS]``
                          | ``bit_flip:stepN[:field][:procP]`` — flip ONE
                          mantissa bit (finite, NaN/Inf-guard-invisible;
                          the silent-data-corruption twin of
                          ``halo_corrupt``).  The optional third component
                          is a FIELD NAME or a reserved placement:
                          ``transport`` (flip a packed send-slab word in
                          flight) or ``ckpt`` (flip serialized shard bytes
                          after the lineage digest, before the write);
                          several faults compose comma-separated
                          (docs/robustness.md)
``IGG_INTEGRITY``         silent-data-corruption integrity plane master
                          switch (`implicitglobalgrid_tpu.integrity`,
                          docs/robustness.md): ``1`` arms transport
                          checksums on the host-entry coalesced halo
                          exchange; ``0`` force-disables EVERY detector
                          (checksums, shadow audit, env cadences) to a
                          pinned zero-overhead path like
                          ``IGG_TELEMETRY=0``; unset = checksums off but
                          ``IGG_INTEGRITY_EVERY`` still honored.  Resolved
                          host-side at the exchange entry / loop start
                          (the knob-binding contract) — never read from
                          traced code
``IGG_INTEGRITY_EVERY``   shadow-step audit cadence in steps for the
                          guarded time loops (int >= 0; 0/unset = off):
                          every N committed steps `guarded_time_loop`
                          re-executes the step from the retained pre-step
                          state and bit-compares against the committed
                          result; any difference raises
                          `integrity.IntegrityError` naming the implicated
                          rank.  Ignored when ``IGG_INTEGRITY=0``

``IGG_GATHER_BATCH``      blocks fetched per compiled dispatch in the
                          multi-host gather (int, clamped to >= 1, default
                          8; `ops.gather._gather_batch_size`)
``IGG_BATCH``             default slot-pool capacity B of the batched
                          serving loop (`serving.ServingLoop`; int >= 1,
                          default 4) — B ensemble members share one vmapped
                          SPMD step at ONE collective pair per exchanged
                          dimension (read per loop construction)
``IGG_BATCH_ROUND_STEPS`` default steps advanced per serving round (int >=
                          1, default 1; `serving.ServingLoop`) — the
                          admit/retire/guard granularity of the slot pool
``IGG_TELEMETRY``         telemetry master switch (``0`` disables the
                          metrics registry, the event log and every
                          instrumented hot path to their zero-allocation
                          no-op branch; unset/nonzero = on) — read per
                          call by `utils.telemetry` (docs/observability.md)
``IGG_TELEMETRY_DIR``     directory for the per-process JSONL event log
                          (``events.jsonl`` / ``events.pN.jsonl``); unset =
                          metrics-registry-only, no files written
``IGG_HEARTBEAT_EVERY``   rank-0 heartbeat cadence in steps for the models'
                          instrumented run loops (int >= 0; 0/unset = off):
                          every N steps print step time, steps/s and T_eff —
                          and, on multi-process grids, run the all-ranks
                          skew probe (`utils.tracing.skew_probe`)
``IGG_PROFILE``           windowed device-timeline capture (`utils.profiling`,
                          docs/observability.md): ``steps:A-B`` arms a
                          `jax.profiler` capture around time-loop steps A..B
                          (1-based, inclusive) of the next instrumented run;
                          ``steps:N`` = steps 1..N.  Per-rank output under
                          ``IGG_PROFILE_DIR`` / ``IGG_TELEMETRY_DIR``;
                          unset = no capture (the default).  Consumed ONCE
                          per process: the first instrumented run arms it
                          (`utils.profiling.maybe_arm`; several loops in
                          one process must not pay a profiler session
                          each or overwrite the first capture)
``IGG_PROFILE_DIR``       base directory for the per-rank profiler capture
                          dirs (``profile.p<rank>/``); unset = under
                          ``IGG_TELEMETRY_DIR`` (no directory at all
                          degrades to a structured ``profile.capture_failed``
                          event, never a crash)
``IGG_TRACE_RING``        capacity of the per-process host-span ring buffer
                          (`utils.tracing`; int >= 0, default 4096; 0
                          disables span recording entirely) — read per
                          span, like ``IGG_TELEMETRY``
``IGG_TRACE_SAMPLE``      head-based request-trace sampling rate at the
                          serving/fleet front doors (number in [0, 1],
                          default 1.0 = every request gets a trace
                          context minted); 0 disables minting entirely —
                          no context allocation, no header emission
                          beyond echoing an inbound ``traceparent``
``IGG_SKEW_WARN``         straggler threshold for the all-ranks skew probe
                          (number >= 0, default 2.0): a ``skew.straggler``
                          event fires when max/min per-rank step wall time
                          exceeds it; 0 disables the event (gauges still
                          publish)
``IGG_TELEMETRY_MAX_TENANTS``  cap on distinct ``serving.tenant.<t>.steps``
                          counter series (int >= 1, default 64); overflow
                          tenants fold into ``serving.tenant.__other__.steps``
``IGG_METRICS_PORT``      live-plane scrape port (`utils.liveplane`): unset =
                          no HTTP server (the default); ``0`` = bind an
                          ephemeral port (published via the
                          ``liveplane.port`` gauge, the rank-0 heartbeat
                          event and a ``liveplane.p<rank>.json`` endpoint
                          file under ``IGG_TELEMETRY_DIR``); N > 0 = bind
                          exactly N.  Never consulted when ``IGG_TELEMETRY=0``
                          (the server does not start)
``IGG_METRICS_HOST``      bind address of the live-plane server (default
                          ``127.0.0.1`` — loopback only; the endpoints are
                          unauthenticated read-only snapshots, widen the
                          bind deliberately)
``IGG_SLO_WINDOW_S``      length in seconds of one rolling SLO sub-window of
                          every `utils.telemetry.Histogram` (number > 0,
                          default 30; `telemetry.SLO_WINDOW_S_DEFAULT`) —
                          the ``window`` section of histogram summaries and
                          the ``slo.*`` gauges aggregate the last
                          `telemetry.SLO_WINDOWS` windows (read per window
                          rollover, like the other telemetry knobs)
``IGG_SERVE_PORT``        serving front-door port (`serving.frontdoor`,
                          docs/serving.md): 0 (the default when a
                          `FrontDoor` is constructed without an explicit
                          ``port``) binds an ephemeral port, published via
                          the ``frontdoor.port`` gauge and a
                          ``frontdoor.p<rank>.json`` endpoint file under
                          ``IGG_TELEMETRY_DIR``; N > 0 binds exactly N.
                          Rank 0 only — the front door is the cluster's
                          single network entry
``IGG_SERVE_HOST``        bind address of the front-door server (default
                          ``127.0.0.1`` — loopback only; the submit
                          endpoint is unauthenticated, widen deliberately)
``IGG_TENANT_QUOTA``      per-tenant token-bucket arrival limit for the
                          front door: ``RATE`` or ``RATE:BURST`` (requests
                          per second sustained, bucket depth BURST >= 1,
                          default burst = max(1, RATE)); unset = unlimited.
                          Exhaustion rejects with 429 reason ``quota``
``IGG_FRONTDOOR_QUEUE_MAX``  backpressure threshold (int >= 1): reject new
                          requests with 429 reason ``backpressure`` while
                          the ``serving.queue_depth`` gauge is at/above it
                          (unset = 4x the pool capacity)
``IGG_FRONTDOOR_SLO_P99_S``  SLO backpressure threshold (number > 0): reject
                          with 429 reason ``slo`` while the live
                          ``slo.serving.round_seconds.p99`` window exceeds
                          it (unset = only active CRITICAL anomaly alerts
                          flip the ``slo`` backpressure)
``IGG_AUTOSCALE_QUEUE_HIGH``  sustained-queue scale-up threshold for the
                          `serving.autoscale.Autoscaler` (int >= 1; unset =
                          the pool capacity): queue depth at/above it votes
                          ``up``
``IGG_AUTOSCALE_SUSTAIN`` consecutive autoscaler observations (int >= 1,
                          default 2) a non-``hold`` verdict must sustain
                          before a resize commits
``IGG_SERVE_MAX_BODY``    front-door request-body bound in bytes (int >= 1,
                          default 1 MiB = 1048576): a ``POST`` whose body
                          (declared or actual) exceeds it is refused with
                          a structured 413 before the handler buffers it —
                          the slow-loris/oversize hardening of
                          `serving.frontdoor` (docs/serving.md)
``IGG_RESULT_KEEP``       retired-result retention depth of the serving
                          loop (int >= 0; 0/unset = keep every result,
                          the pre-fleet behavior): after each round, prune
                          CONSUMED results (harvested by a front door)
                          beyond the newest N — `serving.ServingLoop`
                          (docs/serving.md, "bounded result retention")
``IGG_RESULT_TTL_S``      age bound in seconds on retired results (number
                          > 0; unset = no TTL): a consumed result older
                          than this is pruned at round end regardless of
                          ``IGG_RESULT_KEEP``.  A pruned result's fetch
                          returns a structured 410 (``results_expired``)
``IGG_FLEET_PORT``        fleet router public port (`fleet.router`,
                          docs/serving.md): 0/unset = bind an ephemeral
                          port (published via a ``fleet.json`` endpoint
                          file under ``IGG_TELEMETRY_DIR``); N > 0 binds
                          exactly N
``IGG_FLEET_POLL_S``      fleet controller liveness/health polling cadence
                          in seconds (number > 0, default 0.5;
                          `fleet.controller.FleetController`)
``IGG_FLEET_RESPAWN_LIMIT``  in-place pool respawns per continuous failure
                          streak before the fleet policy quarantines the
                          pool's device subset (int >= 0, default 2;
                          `fleet.policy.FleetPolicy`)
``IGG_FLEET_SCRAPE_RETRIES``  per-endpoint retry budget of the fleet/router
                          health scrapes and of ``scripts/igg_top.py``
                          (int >= 0, default 2): a scrape is retried with
                          exponential backoff before the endpoint is
                          marked ``UNREACHABLE``
``IGG_FLEET_SPILL_QUEUE`` hot-pool spill threshold (int >= 1; unset =
                          spill off): a pool whose scraped queue depth
                          sits at/above it makes the policy spawn a fresh
                          spill pool instead of resizing the live one
``IGG_FLEET_IDLE_RETIRE`` consecutive idle observations (queue 0, no
                          active members) before a spilled pool retires
                          (int >= 1; unset = pools never retire)
``IGG_FLEET_CANARY_STREAK``  consecutive healthy canary observations before
                          the candidate config auto-promotes fleet-wide
                          (int >= 1, default 3; `fleet.canary`)
``IGG_FLEET_CANARY_P99_S``  canary SLO breach threshold on the canary
                          pool's rolling ``slo.serving.round_seconds.p99``
                          window in seconds (number > 0; unset = only
                          active CRITICAL alerts breach the canary)
``IGG_GENERATION``        this incarnation's generation token (int >= 0;
                          unset = unfenced).  Set by the run supervisor
                          identically on every rank of one incarnation;
                          threaded through checkpoint meta, telemetry
                          event tags and front-door control broadcasts,
                          and checked against the authoritative fence file
                          at every durable publish
                          (`supervisor.generation`, docs/robustness.md)
``IGG_FENCE_DIR``         directory of the supervisor-published
                          authoritative ``generation.json`` fence file
                          (unset = no fence checks) — read per publish,
                          like the other resilience knobs
``IGG_SUPERVISE_MAX_RESTARTS``  in-place restarts per continuous failure
                          streak before the supervisor's policy engine
                          drops a topology rung (int >= 0, default 2;
                          `supervisor.policy.RecoveryPolicy`)
``IGG_SUPERVISE_BACKOFF_S``  base of the supervisor's exponential relaunch
                          backoff in seconds (number > 0, default 0.5;
                          `utils.resilience.backoff_schedule` semantics)
``IGG_SUPERVISE_POLL_S``  supervisor liveness/health polling cadence in
                          seconds (number > 0, default 0.5;
                          `supervisor.manager.RunSupervisor`)
``IGG_AUTOTUNE``          default for the models' ``make_multi_step``
                          ``autotune=`` kwarg (``implicitglobalgrid_tpu.
                          tuning``; nonzero = on, unset/0 = off): on first
                          use of a (backend, topology, model, size, dtype,
                          batch) point, search the schedule-kwarg space
                          (cost-model-pruned, short measured runs) and
                          apply the cached winner on every later call —
                          a pure substitution of existing kwargs, resolved
                          host-side before tracing (docs/performance.md)
``IGG_TUNE_CACHE``        primary directory of the autotuner's on-disk
                          winner table (unset = ``~/.cache/
                          implicitglobalgrid_tpu/tune``); the committed
                          seed layer ``tuning/entries`` is always the
                          read-only fallback — read per resolve
``IGG_TUNE_TOPK``         total candidates measured per search (int >= 1,
                          default 4; `tuning.space.prune` — the default
                          config always counts among them, so ``1`` can
                          only ever confirm the default)
``IGG_TUNE_STEPS``        timed chunk calls per measured candidate (int >=
                          1, default 3; `tuning.search.measure_candidate`
                          — short by design, the bench harness owns
                          publication-grade timing)
========================  ====================================================

Explicit kwargs always win over env values; env values win over built-in
defaults — the reference's precedence.  The resilience knobs are read per
call (like ``IGG_DONATE``), not snapshotted at init.
"""

from __future__ import annotations

import os

#: Valid values for ``IGG_GUARD_POLICY`` / the models' ``guard_policy``.
GUARD_POLICIES = ("raise", "warn", "rollback")


def _int_env(name: str, *, minimum: int | None = None, maximum: int | None = None) -> int | None:
    """Read an integer env var; ``None`` when unset/empty.

    Error messages follow the reference's contract (name the variable and
    the obtained value) and state the accepted range/format.
    """
    val = os.environ.get(name)
    if val is None or val == "":
        return None
    try:
        parsed = int(val)
    except ValueError:
        raise ValueError(
            f"Environment variable {name} must be an integer"
            f"{_range_desc(minimum, maximum)} (format: a base-10 integer), "
            f"got {val!r}."
        )
    _check_range(name, parsed, minimum, maximum, val)
    return parsed


def _float_env(
    name: str,
    *,
    minimum: float | None = None,
    exclusive_minimum: float | None = None,
) -> float | None:
    """Read a float env var; ``None`` when unset/empty.  Same error contract
    as `_int_env` (variable name, accepted range, obtained value)."""
    val = os.environ.get(name)
    if val is None or val == "":
        return None
    try:
        parsed = float(val)
    except ValueError:
        raise ValueError(
            f"Environment variable {name} must be a number"
            f"{_range_desc(minimum, None, exclusive_minimum)} "
            f"(format: a decimal number of seconds, e.g. '2' or '0.5'), "
            f"got {val!r}."
        )
    _check_range(name, parsed, minimum, None, val, exclusive_minimum)
    return parsed


def _choice_env(name: str, choices: tuple[str, ...]) -> str | None:
    """Read an enumerated env var; ``None`` when unset/empty."""
    val = os.environ.get(name)
    if val is None or val == "":
        return None
    if val not in choices:
        raise ValueError(
            f"Environment variable {name} must be one of "
            f"{', '.join(repr(c) for c in choices)}, got {val!r}."
        )
    return val


def _range_desc(minimum, maximum, exclusive_minimum=None) -> str:
    if exclusive_minimum is not None:
        return f" > {exclusive_minimum}"
    if minimum is not None and maximum is not None:
        return f" in [{minimum}, {maximum}]"
    if minimum == 0:
        return " >= 0 (non-negative)"
    if minimum is not None:
        return f" >= {minimum}"
    if maximum is not None:
        return f" <= {maximum}"
    return ""


def _check_range(name, parsed, minimum, maximum, val, exclusive_minimum=None):
    bad = (
        (minimum is not None and parsed < minimum)
        or (maximum is not None and parsed > maximum)
        or (exclusive_minimum is not None and parsed <= exclusive_minimum)
    )
    if bad:
        kind = "an integer" if isinstance(parsed, int) else "a number"
        raise ValueError(
            f"Environment variable {name} must be {kind}"
            f"{_range_desc(minimum, maximum, exclusive_minimum)}, got {val!r}."
        )


def env_config() -> dict:
    """Read the ``IGG_*`` environment tier (once per init, like the reference)."""
    cfg: dict = {}
    device_type = os.environ.get("IGG_DEVICE_TYPE")
    if device_type:
        cfg["device_type"] = device_type
    quiet = _int_env("IGG_QUIET")
    if quiet is not None:
        cfg["quiet"] = quiet > 0
    reorder = _int_env("IGG_REORDER")
    if reorder is not None:
        cfg["reorder"] = reorder
    overlap = _int_env("IGG_OVERLAP")
    if overlap is not None:
        cfg["overlap"] = overlap
    return cfg


# -- Resilience knobs (read per call, like IGG_DONATE) ------------------------
#
# Each accessor validates the reference's error contract: negative retries,
# zero/negative timeouts and unknown policies are rejected with a message
# naming the variable, the accepted range and the obtained value.


def init_retries_env() -> int | None:
    """``IGG_INIT_RETRIES``: retry attempts after the first bring-up failure."""
    return _int_env("IGG_INIT_RETRIES", minimum=0)


def init_timeout_env() -> float | None:
    """``IGG_INIT_TIMEOUT_S``: overall bring-up deadline in seconds (> 0)."""
    return _float_env("IGG_INIT_TIMEOUT_S", exclusive_minimum=0)


def init_backoff_env() -> float | None:
    """``IGG_INIT_BACKOFF_S``: base retry backoff in seconds (> 0)."""
    return _float_env("IGG_INIT_BACKOFF_S", exclusive_minimum=0)


def watchdog_env() -> float | None:
    """``IGG_WATCHDOG_S``: collective-hang watchdog in seconds (>= 0).

    ``None`` = unset (caller picks its default), ``0.0`` = explicitly off —
    the distinction lets an explicit 0 disable a watchdog a caller would
    otherwise arm with its own fallback timeout.
    """
    return _float_env("IGG_WATCHDOG_S", minimum=0)


def guard_every_env() -> int | None:
    """``IGG_GUARD_EVERY``: NaN/Inf guard cadence in steps (>= 0; 0 = off)."""
    return _int_env("IGG_GUARD_EVERY", minimum=0)


def guard_policy_env() -> str | None:
    """``IGG_GUARD_POLICY``: ``raise`` | ``warn`` | ``rollback``."""
    return _choice_env("IGG_GUARD_POLICY", GUARD_POLICIES)


def checkpoint_every_env() -> int | None:
    """``IGG_CHECKPOINT_EVERY``: checkpoint cadence in steps (>= 0; 0 = off)."""
    return _int_env("IGG_CHECKPOINT_EVERY", minimum=0)


def checkpoint_dir_env() -> str | None:
    """``IGG_CHECKPOINT_DIR``: default checkpoint directory."""
    val = os.environ.get("IGG_CHECKPOINT_DIR")
    return val or None


def checkpoint_keep_env() -> int | None:
    """``IGG_CHECKPOINT_KEEP``: retention depth in generations (>= 0;
    0 = keep every generation)."""
    return _int_env("IGG_CHECKPOINT_KEEP", minimum=0)


def fault_inject_env() -> str | None:
    """``IGG_FAULT_INJECT``: raw fault spec (parsed by `utils.resilience`)."""
    val = os.environ.get("IGG_FAULT_INJECT")
    return val or None


def integrity_enabled_env() -> bool | None:
    """``IGG_INTEGRITY``: integrity-plane master switch (tri-state).

    ``None`` = unset (transport checksums off; ``IGG_INTEGRITY_EVERY``
    still honored), ``False`` = ``0`` (every detector force-disabled —
    the pinned zero-overhead path), ``True`` = armed.  Read host-side at
    the exchange entry / loop construction, never from traced code.
    """
    val = _int_env("IGG_INTEGRITY")
    return None if val is None else val > 0


def integrity_every_env() -> int | None:
    """``IGG_INTEGRITY_EVERY``: shadow-step audit cadence in steps
    (>= 0; 0 = off).  Ignored when ``IGG_INTEGRITY=0``."""
    return _int_env("IGG_INTEGRITY_EVERY", minimum=0)


def coalesce_env() -> bool | None:
    """``IGG_COALESCE``: multi-field halo-exchange message combining.

    ``None`` = unset (auto: coalesce whenever >= 2 fields share a
    dimension's exchange), ``False`` = per-field collectives, ``True`` =
    the auto behavior pinned explicitly.  Bit-identical either way — the
    knob exists for debugging/per-field attribution and A/B measurement.
    """
    val = _int_env("IGG_COALESCE")
    return None if val is None else val > 0


def gather_batch_env() -> int | None:
    """``IGG_GATHER_BATCH``: blocks per compiled gather dispatch.

    Clamped (not rejected) to >= 1 by the consumer, matching the original
    `ops.gather` behavior for 0/negative values.
    """
    return _int_env("IGG_GATHER_BATCH")


# -- Batched serving knobs (read per loop construction; docs/usage.md) --------


def batch_env() -> int | None:
    """``IGG_BATCH``: default serving slot-pool capacity B (>= 1)."""
    return _int_env("IGG_BATCH", minimum=1)


def batch_round_steps_env() -> int | None:
    """``IGG_BATCH_ROUND_STEPS``: default steps per serving round (>= 1)."""
    return _int_env("IGG_BATCH_ROUND_STEPS", minimum=1)


# -- Telemetry knobs (read per call; docs/observability.md) -------------------


def telemetry_enabled_env() -> bool:
    """``IGG_TELEMETRY``: master switch for `utils.telemetry` (default ON;
    ``0`` routes every instrumented hot path to its no-op branch)."""
    val = _int_env("IGG_TELEMETRY")
    return True if val is None else val > 0


def telemetry_dir_env() -> str | None:
    """``IGG_TELEMETRY_DIR``: event-log directory (unset = no files)."""
    val = os.environ.get("IGG_TELEMETRY_DIR")
    return val or None


def heartbeat_every_env() -> int | None:
    """``IGG_HEARTBEAT_EVERY``: rank-0 heartbeat cadence in steps (>= 0;
    0 = off)."""
    return _int_env("IGG_HEARTBEAT_EVERY", minimum=0)


def profile_env() -> str | None:
    """``IGG_PROFILE``: device-timeline capture window spec (``steps:A-B``
    or ``steps:N``); unset/empty = no capture.  Parsed and validated by
    `utils.profiling.parse_profile_window` (the error contract names the
    variable and the accepted grammar)."""
    val = os.environ.get("IGG_PROFILE")
    return val or None


def profile_dir_env() -> str | None:
    """``IGG_PROFILE_DIR``: base directory for per-rank profiler capture
    dirs (unset = derive from ``IGG_TELEMETRY_DIR``)."""
    val = os.environ.get("IGG_PROFILE_DIR")
    return val or None


def trace_ring_env() -> int | None:
    """``IGG_TRACE_RING``: per-process span ring-buffer capacity (>= 0;
    0 disables span recording; unset = the `utils.tracing` default)."""
    return _int_env("IGG_TRACE_RING", minimum=0)


def trace_sample_env() -> float | None:
    """``IGG_TRACE_SAMPLE``: head-based request-trace sampling rate at the
    front doors (in [0, 1]; 0 mints no contexts, unset/1 traces every
    request; inbound contexts are never re-sampled)."""
    return _float_env("IGG_TRACE_SAMPLE", minimum=0)


def skew_warn_env() -> float | None:
    """``IGG_SKEW_WARN``: straggler event threshold on max/min per-rank
    step wall time (>= 0; 0 disables the event, gauges still publish)."""
    return _float_env("IGG_SKEW_WARN", minimum=0)


def telemetry_max_tenants_env() -> int | None:
    """``IGG_TELEMETRY_MAX_TENANTS``: cap on distinct per-tenant counter
    series (>= 1); overflow folds into ``serving.tenant.__other__.steps``."""
    return _int_env("IGG_TELEMETRY_MAX_TENANTS", minimum=1)


# -- Live-plane knobs (read per call; docs/observability.md) ------------------


def metrics_port_env() -> int | None:
    """``IGG_METRICS_PORT``: live-plane scrape port (>= 0; 0 = ephemeral).
    ``None`` = unset — the per-rank HTTP server never starts."""
    return _int_env("IGG_METRICS_PORT", minimum=0)


def metrics_host_env() -> str | None:
    """``IGG_METRICS_HOST``: live-plane bind address (default loopback —
    the consumer falls back to ``127.0.0.1`` when unset)."""
    val = os.environ.get("IGG_METRICS_HOST")
    return val or None


def slo_window_env() -> float | None:
    """``IGG_SLO_WINDOW_S``: rolling SLO sub-window length in seconds
    (> 0; unset = the `utils.telemetry.SLO_WINDOW_S_DEFAULT` default)."""
    return _float_env("IGG_SLO_WINDOW_S", exclusive_minimum=0)


# -- Serving front-door knobs (read per construction; docs/serving.md) --------


def serve_port_env() -> int | None:
    """``IGG_SERVE_PORT``: front-door port (>= 0; 0 = ephemeral).  ``None``
    = unset — `serving.frontdoor.FrontDoor` falls back to 0 (ephemeral)."""
    return _int_env("IGG_SERVE_PORT", minimum=0)


def serve_host_env() -> str | None:
    """``IGG_SERVE_HOST``: front-door bind address (default loopback —
    the consumer falls back to ``127.0.0.1`` when unset)."""
    val = os.environ.get("IGG_SERVE_HOST")
    return val or None


def tenant_quota_env() -> tuple[float, float] | None:
    """``IGG_TENANT_QUOTA``: per-tenant token-bucket arrival limit as
    ``(rate_per_s, burst)``, or ``None`` when unset (= unlimited).

    Format ``RATE`` or ``RATE:BURST`` — sustained RATE requests/second per
    tenant with up to BURST (>= 1; default ``max(1, RATE)``) accumulated.
    """
    val = os.environ.get("IGG_TENANT_QUOTA")
    if val is None or val == "":
        return None
    parts = val.split(":")
    try:
        if len(parts) not in (1, 2):
            raise ValueError
        rate = float(parts[0])
        burst = float(parts[1]) if len(parts) == 2 else max(1.0, rate)
    except ValueError:
        raise ValueError(
            f"Environment variable IGG_TENANT_QUOTA must be 'RATE' or "
            f"'RATE:BURST' (decimal requests/second, e.g. '5' or '5:10'), "
            f"got {val!r}."
        )
    if rate <= 0 or burst < 1:
        raise ValueError(
            f"Environment variable IGG_TENANT_QUOTA needs RATE > 0 and "
            f"BURST >= 1, got {val!r}."
        )
    return rate, burst


def frontdoor_queue_max_env() -> int | None:
    """``IGG_FRONTDOOR_QUEUE_MAX``: queue-depth backpressure threshold
    (>= 1; unset = the front door's 4x-capacity default)."""
    return _int_env("IGG_FRONTDOOR_QUEUE_MAX", minimum=1)


def frontdoor_slo_p99_env() -> float | None:
    """``IGG_FRONTDOOR_SLO_P99_S``: round-latency p99 backpressure
    threshold in seconds (> 0; unset = alerts-only SLO backpressure)."""
    return _float_env("IGG_FRONTDOOR_SLO_P99_S", exclusive_minimum=0)


def autoscale_queue_high_env() -> int | None:
    """``IGG_AUTOSCALE_QUEUE_HIGH``: queue depth that votes for a scale-up
    (>= 1; unset = the pool capacity)."""
    return _int_env("IGG_AUTOSCALE_QUEUE_HIGH", minimum=1)


def autoscale_sustain_env() -> int | None:
    """``IGG_AUTOSCALE_SUSTAIN``: consecutive non-hold autoscaler verdicts
    before a resize commits (>= 1, default 2)."""
    return _int_env("IGG_AUTOSCALE_SUSTAIN", minimum=1)


def serve_max_body_env() -> int | None:
    """``IGG_SERVE_MAX_BODY``: front-door request-body bound in bytes
    (>= 1; unset = the 1 MiB default, `serving.frontdoor.MAX_BODY_DEFAULT`)."""
    return _int_env("IGG_SERVE_MAX_BODY", minimum=1)


def result_keep_env() -> int | None:
    """``IGG_RESULT_KEEP``: retired-result retention depth (>= 0;
    0/unset = keep every result — the pre-fleet behavior)."""
    return _int_env("IGG_RESULT_KEEP", minimum=0)


def result_ttl_env() -> float | None:
    """``IGG_RESULT_TTL_S``: age bound in seconds on consumed results
    (> 0; unset = no TTL)."""
    return _float_env("IGG_RESULT_TTL_S", exclusive_minimum=0)


# -- Fleet knobs (read per construction, host-side; docs/serving.md) ----------


def fleet_port_env() -> int | None:
    """``IGG_FLEET_PORT``: fleet router public port (>= 0; 0 = ephemeral).
    ``None`` = unset — `fleet.router.FleetRouter` falls back to 0."""
    return _int_env("IGG_FLEET_PORT", minimum=0)


def fleet_poll_env() -> float | None:
    """``IGG_FLEET_POLL_S``: fleet controller polling cadence in seconds
    (> 0, default 0.5)."""
    return _float_env("IGG_FLEET_POLL_S", exclusive_minimum=0)


def fleet_respawn_limit_env() -> int | None:
    """``IGG_FLEET_RESPAWN_LIMIT``: pool respawns per failure streak before
    the policy quarantines the pool's device subset (>= 0, default 2)."""
    return _int_env("IGG_FLEET_RESPAWN_LIMIT", minimum=0)


def fleet_scrape_retries_env() -> int | None:
    """``IGG_FLEET_SCRAPE_RETRIES``: per-endpoint health-scrape retry budget
    (>= 0, default 2) before the endpoint is marked ``UNREACHABLE``."""
    return _int_env("IGG_FLEET_SCRAPE_RETRIES", minimum=0)


def fleet_spill_queue_env() -> int | None:
    """``IGG_FLEET_SPILL_QUEUE``: hot-pool queue depth that makes the policy
    spawn a spill pool (>= 1; unset = spill off)."""
    return _int_env("IGG_FLEET_SPILL_QUEUE", minimum=1)


def fleet_idle_retire_env() -> int | None:
    """``IGG_FLEET_IDLE_RETIRE``: consecutive idle observations before a
    spilled pool retires (>= 1; unset = pools never retire)."""
    return _int_env("IGG_FLEET_IDLE_RETIRE", minimum=1)


def fleet_canary_streak_env() -> int | None:
    """``IGG_FLEET_CANARY_STREAK``: consecutive healthy canary observations
    before auto-promote (>= 1, default 3)."""
    return _int_env("IGG_FLEET_CANARY_STREAK", minimum=1)


def fleet_canary_p99_env() -> float | None:
    """``IGG_FLEET_CANARY_P99_S``: canary round-p99 breach threshold in
    seconds (> 0; unset = alerts-only breach detection)."""
    return _float_env("IGG_FLEET_CANARY_P99_S", exclusive_minimum=0)


# -- Supervisor / generation-fencing knobs (docs/robustness.md) ---------------


def generation_env() -> int | None:
    """``IGG_GENERATION``: this incarnation's generation token (>= 0;
    None = unfenced — the default outside a supervised run)."""
    return _int_env("IGG_GENERATION", minimum=0)


def fence_dir_env() -> str | None:
    """``IGG_FENCE_DIR``: directory of the authoritative ``generation.json``
    fence file (unset = fence checks off)."""
    val = os.environ.get("IGG_FENCE_DIR")
    return val or None


def supervise_max_restarts_env() -> int | None:
    """``IGG_SUPERVISE_MAX_RESTARTS``: in-place restarts per failure streak
    before the supervisor shrinks a rung (>= 0, default 2)."""
    return _int_env("IGG_SUPERVISE_MAX_RESTARTS", minimum=0)


def supervise_backoff_env() -> float | None:
    """``IGG_SUPERVISE_BACKOFF_S``: base relaunch backoff in seconds
    (> 0, default 0.5)."""
    return _float_env("IGG_SUPERVISE_BACKOFF_S", exclusive_minimum=0)


def supervise_poll_env() -> float | None:
    """``IGG_SUPERVISE_POLL_S``: supervisor liveness/health polling cadence
    in seconds (> 0, default 0.5)."""
    return _float_env("IGG_SUPERVISE_POLL_S", exclusive_minimum=0)


# -- Autotuning knobs (read per resolve, host-side; docs/performance.md) ------


def autotune_env() -> bool | None:
    """``IGG_AUTOTUNE``: default for ``make_multi_step(autotune=)``.

    ``None`` = unset (off unless the kwarg says otherwise); resolved
    host-side before any tracing, so the knob can never bind into a cached
    executable (the knob-binding contract).
    """
    val = _int_env("IGG_AUTOTUNE")
    return None if val is None else val > 0


def tune_cache_env() -> str | None:
    """``IGG_TUNE_CACHE``: primary winner-table directory (unset = the
    per-user default, `tuning.cache.default_cache_dir`)."""
    val = os.environ.get("IGG_TUNE_CACHE")
    return val or None


def tune_topk_env() -> int | None:
    """``IGG_TUNE_TOPK``: total candidates measured per search, the default
    config included (>= 1, default 4)."""
    return _int_env("IGG_TUNE_TOPK", minimum=1)


def tune_steps_env() -> int | None:
    """``IGG_TUNE_STEPS``: timed chunk calls per measured candidate (>= 1,
    default 3)."""
    return _int_env("IGG_TUNE_STEPS", minimum=1)
