"""Environment-variable configuration tier.

The reference reads ``IGG_*`` env vars once at `init_global_grid`
(`/root/reference/src/init_global_grid.jl:51-68`) as the deploy-time
configuration tier below the kwargs tier.  Its specific knobs
(``IGG_CUDAAWARE_MPI[_DIMX/Y/Z]``, ``IGG_ROCMAWARE_MPI*``,
``IGG_LOOPVECTORIZATION*``) toggle GPU-direct MPI transport and CPU
vectorization per dimension — both N/A on TPU, where `collective_permute`
always moves HBM→HBM over ICI and packing is compiled (SURVEY.md §2.3).

The *mechanism* carries over with the TPU-meaningful knobs:

========================  ====================================================
``IGG_DEVICE_TYPE``       default ``device_type`` (``auto|tpu|cpu|gpu``)
``IGG_QUIET``             nonzero suppresses the rank-0 banner
``IGG_REORDER``           default mesh reorder flag (ICI-torus alignment)
``IGG_OVERLAP``           default overlap in every dimension (reference
                          kwarg ``overlapx/y/z`` default 2)
``IGG_DONATE``            default for `update_halo`'s global-array buffer
                          donation (0 = off; see `ops.halo._default_donate`
                          — read per call, not at init)
``IGG_VMEM_MB``           per-core VMEM capacity the fused kernels plan
                          against (`ops._fused_envelope.vmem_budget` — read
                          per kernel build, not at init)
========================  ====================================================

Explicit `init_global_grid` kwargs always win over env values; env values win
over built-in defaults — the reference's precedence.
"""

from __future__ import annotations

import os


def _int_env(name: str) -> int | None:
    val = os.environ.get(name)
    if val is None or val == "":
        return None
    try:
        return int(val)
    except ValueError:
        raise ValueError(f"Environment variable {name} must be an integer, got {val!r}.")


def env_config() -> dict:
    """Read the ``IGG_*`` environment tier (once per init, like the reference)."""
    cfg: dict = {}
    device_type = os.environ.get("IGG_DEVICE_TYPE")
    if device_type:
        cfg["device_type"] = device_type
    quiet = _int_env("IGG_QUIET")
    if quiet is not None:
        cfg["quiet"] = quiet > 0
    reorder = _int_env("IGG_REORDER")
    if reorder is not None:
        cfg["reorder"] = reorder
    overlap = _int_env("IGG_OVERLAP")
    if overlap is not None:
        cfg["overlap"] = overlap
    return cfg
