"""Global-grid index math (reference: `/root/reference/src/tools.jl`).

The "implicit" in implicit global grid: global sizes and physical coordinates
are *computed* from (local size, dims, coords, overlap, period) — the global
array never exists.  The formulas are ported bit-exact from the reference
(`src/tools.jl:24-59` for sizes, `:98-107/:146-155/:194-203` for coordinates),
with one deliberate API change: element indices are **0-based** (Python)
where the reference is 1-based, i.e. ``x_g(i, dx, A)`` here equals the
reference's ``x_g(i+1, dx, A)``.

Coordinate helpers work in two contexts:

* On the host (e.g. in tests or per-process logic): coordinates default to the
  grid singleton's ``coords``.
* Inside `igg.stencil`/`shard_map` (tracing): the block coordinates come from
  `lax.axis_index`, so one formula serves every block of the mesh.
"""

from __future__ import annotations

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES


def _local_size(A, dim: int, gg) -> int:
    """Local (per-block) size of ``A`` in ``dim``.

    Index math accepts both field representations: global-block `jax.Array`s
    (shape ``dims*local``) and plain host arrays in the reference's local view
    (shape as-is) — the latter distinguished by not dividing evenly.
    """
    from ..ops.halo import local_shape

    try:
        shp = local_shape(A, gg)
    except ValueError:
        shp = tuple(np.shape(A))
    return shp[dim] if dim < len(shp) else 1


def nx_g(A=None):
    """Global grid size in x; with ``A``, the global size of array ``A``
    (staggering-aware: ``nx_g + (size(A,0) - nx)``, reference src/tools.jl:45)."""
    gg = _grid.global_grid()
    if A is None:
        return gg.nxyz_g[0]
    return gg.nxyz_g[0] + (_local_size(A, 0, gg) - gg.nxyz[0])


def ny_g(A=None):
    gg = _grid.global_grid()
    if A is None:
        return gg.nxyz_g[1]
    return gg.nxyz_g[1] + (_local_size(A, 1, gg) - gg.nxyz[1])


def nz_g(A=None):
    gg = _grid.global_grid()
    if A is None:
        return gg.nxyz_g[2]
    return gg.nxyz_g[2] + (_local_size(A, 2, gg) - gg.nxyz[2])


ny_g.__doc__ = nx_g.__doc__.replace(" x;", " y;") if nx_g.__doc__ else None
nz_g.__doc__ = nx_g.__doc__.replace(" x;", " z;") if nx_g.__doc__ else None


def _coord(dim: int, gg, coords):
    """Block coordinate in ``dim``: explicit > traced axis_index > grid.coords."""
    if coords is not None:
        return coords[dim]
    if gg.dims[dim] > 1:
        # Inside an igg.stencil/shard_map trace the block coordinate comes
        # from the mesh; on the host (no axis environment) fall back to this
        # process's coords, matching the reference's per-rank view.
        from jax import lax

        try:
            return lax.axis_index(AXIS_NAMES[dim])
        except Exception:
            pass
    return gg.coords[dim]


def _coord_g(i, d, A, dim: int, coords):
    """Shared implementation of x_g/y_g/z_g (reference formula, src/tools.jl:98-107)."""
    import jax

    gg = _grid.global_grid()
    n = gg.nxyz[dim]
    o = gg.overlaps[dim]
    n_g = gg.nxyz_g[dim]
    size_d = _local_size(A, dim, gg) if A is not None else n
    c = _coord(dim, gg, coords)

    traced = isinstance(c, jax.core.Tracer) or isinstance(i, jax.core.Tracer)
    if traced:
        import jax.numpy as jnp

        xp = jnp
    else:
        xp = np
    i = xp.asarray(i)
    x0 = 0.5 * (n - size_d) * d
    x = (c * (n - o) + i) * d + x0
    if gg.periods[dim]:
        # The first cell of the periodic global problem is a ghost cell: shift
        # by one spacing and wrap (reference: src/tools.jl:101-105).  The
        # wrap CONDITIONS are evaluated in exact integer index space — the
        # reference's float comparisons are seam-fragile in two opposite
        # ways (observed in f64 with d = 10/123): the upper test can
        # false-fire on the last in-range plane (fl(124*d - d) > fl(123*d)),
        # and when it fires legitimately its subtraction can cancel to a
        # tiny negative residue (125*d - d - 124*d ~ -2e-15) that a
        # sequential lower wrap re-wraps — either way one seam plane lands a
        # full period out of the domain, making the periodic IC inconsistent
        # and breaking the plane-pair invariant the halo exchange is built
        # on.  j2 is the doubled half-spacing index: x/d == j2/2 exactly
        # (the 0.5*(n-size_d) staggering offset is a half-integer), so the
        # integer comparisons decide the wrap exactly; the wrapped VALUES
        # keep the reference's float formula.
        x = x - d
        j2 = 2 * (c * (n - o) + i) + (n - size_d) - 2
        x = xp.where(
            j2 > 2 * (n_g - 1),
            x - n_g * d,
            xp.where(j2 < 0, x + n_g * d, x),
        )
    if not traced and x.ndim == 0:
        return float(x)
    return x


def x_g(ix, dx, A=None, *, coords=None):
    """Global x-coordinate of local element ``ix`` (0-based) of array ``A``.

    ``dx`` is the grid spacing.  ``ix`` may be a scalar or an index array.
    Staggered arrays (e.g. size ``nx+1``) are offset by ``0.5*(nx-size)*dx``
    exactly like the reference (`/root/reference/src/tools.jl:98-107`).
    ``coords`` overrides the block coordinates (useful for computing another
    block's coordinates on the host); inside `igg.stencil` the block
    coordinate is taken from the mesh automatically.
    """
    return _coord_g(ix, dx, A, 0, coords)


def y_g(iy, dy, A=None, *, coords=None):
    """Global y-coordinate of local element ``iy`` (0-based) of array ``A``."""
    return _coord_g(iy, dy, A, 1, coords)


def z_g(iz, dz, A=None, *, coords=None):
    """Global z-coordinate of local element ``iz`` (0-based) of array ``A``."""
    return _coord_g(iz, dz, A, 2, coords)
