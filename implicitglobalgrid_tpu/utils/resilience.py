"""Resilience layer: guarded bring-up, numerical guards, fault injection.

The reference's whole value proposition is that the 3-function API survives
scaling to thousands of processes (`/root/reference/README.md:12`); at that
scale the failures that dominate are not stencil bugs but *runtime* faults:
coordinator races during multi-host bring-up, a NaN born in one block
silently flooding the global grid through `update_halo`, and preempted
workers losing whole simulations.  This module is the robustness layer a
production stack ships first:

* **Guarded bring-up** — `retry_call` / `backoff_schedule` give
  `parallel.distributed.init_distributed` configurable retry with
  exponential backoff + seeded jitter and an overall deadline
  (``IGG_INIT_RETRIES`` / ``IGG_INIT_TIMEOUT_S`` / ``IGG_INIT_BACKOFF_S``);
  `watchdog` dumps all-thread stacks when a collective hangs (generalizing
  what ``tests/_distributed_worker.py`` hand-rolled).
* **Numerical guards** — `check_fields` runs ONE cheap jitted all-reduce
  isnan/isinf probe per guard point and reports the offending *block
  coordinates*; `RunGuard` applies the ``raise`` | ``warn`` | ``rollback``
  policy inside the models' time loops (``guard_every=N``).
* **Fault injection** — `FaultInjector` parses ``IGG_FAULT_INJECT``
  (``init_flake:N``, ``halo_corrupt:stepN[:blockB]``,
  ``worker_crash:stepN[:procP]``, ``stall:stepN[:procP]``,
  ``net_delay:stepN[:procP]``, ``ckpt_corrupt:stepN[:shardS]``,
  ``ckpt_truncate:stepN[:shardS]``,
  ``bit_flip:stepN[:field|transport|ckpt][:procP]``; several compose
  comma-separated via
  `FaultSet`, and ``chaos:seed=N:rate=R[:steps=M][:kinds=a+b]`` expands
  into a deterministic randomized storm over those kinds —
  `chaos_schedule`) so the 2-process `test_distributed.py` path and
  `scripts/soak.py` can prove crash→restart-from-checkpoint,
  corruption→guard-trip, damaged-generation fallback and the supervised
  multi-fault ``chaos`` drill end to end.

Checkpoint/restart itself lives in `utils.checkpoint`; `RunGuard` drives it.
"""

from __future__ import annotations

import contextlib
import dataclasses
import faulthandler
import os
import random
import sys
import time
import warnings
from typing import Any, Callable, Sequence

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES, NDIMS
from . import config as _config
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = [
    "GuardError",
    "FieldReport",
    "RunGuard",
    "FaultInjector",
    "FaultSet",
    "backoff_schedule",
    "retry_call",
    "watchdog",
    "check_fields",
    "get_fault_injector",
    "reset_fault_injector",
    "snapshot_state",
    "chaos_schedule",
    "expand_fault_spec",
    "fault_event_matches_spec",
]


# -- Guarded bring-up ---------------------------------------------------------

#: Built-in defaults of the init retry tier (kwarg > ``IGG_*`` env > these).
DEFAULT_INIT_RETRIES = 3
DEFAULT_INIT_TIMEOUT_S = 600.0
DEFAULT_INIT_BACKOFF_S = 1.0
_BACKOFF_CAP_S = 30.0


def backoff_schedule(
    retries: int,
    *,
    base_s: float = DEFAULT_INIT_BACKOFF_S,
    cap_s: float = _BACKOFF_CAP_S,
    jitter: float = 0.5,
    seed: int | None = None,
) -> list[float]:
    """Exponential backoff delays for ``retries`` re-attempts.

    Delay ``i`` is ``min(base * 2**i, cap)`` stretched by a uniform jitter in
    ``[1, 1 + jitter]`` — jitter de-synchronizes thousands of workers
    hammering a coordinator after a correlated failure (the thundering-herd
    fix), and seeding it makes schedules reproducible in tests.  ``seed``
    defaults to this process's index when the runtime is up; during bring-up
    `init_distributed` passes its ``process_id`` through instead (an
    auto-detected pod without one falls back to a shared seed — spread-out
    retries need the explicit id).
    """
    if retries < 0:
        raise ValueError(f"retries must be >= 0 (got {retries})")
    if base_s <= 0 or cap_s <= 0:
        raise ValueError(f"base_s and cap_s must be > 0 (got {base_s}, {cap_s})")
    if jitter < 0:
        raise ValueError(f"jitter must be >= 0 (got {jitter})")
    if seed is None:
        seed = _safe_process_index()
    rng = random.Random(seed)
    return [
        min(base_s * (2.0**i), cap_s) * (1.0 + rng.uniform(0.0, jitter))
        for i in range(retries)
    ]


def _safe_process_index() -> int:
    """Process index without touching the (possibly absent) runtime."""
    try:
        import jax

        from ..parallel import distributed as _dist

        if _dist.is_distributed_initialized():
            return jax.process_index()
    except Exception:
        pass
    return 0


def retry_call(
    fn: Callable[[], Any],
    *,
    retries: int = DEFAULT_INIT_RETRIES,
    timeout_s: float | None = DEFAULT_INIT_TIMEOUT_S,
    base_backoff_s: float = DEFAULT_INIT_BACKOFF_S,
    jitter: float = 0.5,
    seed: int | None = None,
    describe: str = "operation",
    on_retry: Callable[[int, BaseException, float], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Call ``fn`` with up to ``retries`` re-attempts under a deadline.

    ``timeout_s`` is an *overall* deadline across attempts: a retry whose
    backoff would cross it is not taken (a hang inside one attempt cannot be
    interrupted from Python — arm `watchdog` for that).  ``on_retry(attempt,
    error, delay)`` observes each failure; the default logs to stderr.
    Raises the last error, annotated with the attempt count and deadline.
    """
    if timeout_s is not None and timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0 (got {timeout_s})")
    delays = backoff_schedule(
        retries, base_s=base_backoff_s, jitter=jitter, seed=seed
    )
    t0 = clock()
    last: BaseException | None = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except (KeyboardInterrupt, SystemExit, GeneratorExit):
            # Deliberate shutdown is not a flaky bring-up: never retry it.
            raise
        except BaseException as e:
            last = e
            if attempt >= retries:
                break
            delay = delays[attempt]
            elapsed = clock() - t0
            if timeout_s is not None and elapsed + delay > timeout_s:
                raise RuntimeError(
                    f"{describe} failed after {attempt + 1} attempt(s) in "
                    f"{elapsed:.1f}s; the overall deadline "
                    f"(timeout_s={timeout_s}, IGG_INIT_TIMEOUT_S) leaves no "
                    f"room for another retry. Last error: {e!r}"
                ) from e
            # Machine-readable retry record (docs/observability.md): the
            # soak/ops timeline needs every bring-up retry, not just stderr.
            _telemetry.event(
                "retry",
                what=describe,
                attempt=attempt + 1,
                of=retries + 1,
                delay_s=delay,
                error=repr(e),
            )
            _telemetry.counter("resilience.retries").inc()
            if on_retry is not None:
                on_retry(attempt, e, delay)
            else:
                print(
                    f"[igg.resilience] {describe} attempt {attempt + 1}/"
                    f"{retries + 1} failed ({e!r}); retrying in {delay:.2f}s",
                    file=sys.stderr,
                    flush=True,
                )
            sleep(delay)
    raise RuntimeError(
        f"{describe} failed after {retries + 1} attempt(s) "
        f"(retries={retries}, IGG_INIT_RETRIES). Last error: {last!r}"
    ) from last


# faulthandler keeps ONE process-wide timer; this stack makes nested
# watchdogs well-behaved AND strictest-wins: arming/exiting re-arms with the
# SMALLEST timeout on the stack and the OR of the exit flags, so an inner
# watchdog with a laxer deadline (e.g. init_distributed's 600 s default
# inside a test worker's 270 s exit=True watchdog) can never weaken the
# enclosing one.  Each re-arm restarts the timer, so an outer deadline can
# extend by at most one inner scope's duration — bounded, and strictly
# tighter than the pre-stack behavior (inner exit silently DISARMED the
# outer watchdog entirely).
_watchdog_stack: list[tuple[float, bool, Any]] = []


def arm_watchdog(timeout_s: float, *, exit: bool = False, file=None) -> None:
    """Arm the stack-dump watchdog for the remaining process lifetime.

    For linear scripts (test workers) where a ``with`` block is awkward;
    pair with `disarm_watchdog` or let it ride until process exit.
    """
    _watchdog_stack.append((float(timeout_s), exit, file))
    _rearm()


def disarm_watchdog() -> None:
    _watchdog_stack.pop() if _watchdog_stack else None
    _rearm()


def _rearm() -> None:
    if not _watchdog_stack:
        faulthandler.cancel_dump_traceback_later()
        return
    # The entry whose deadline will actually fire supplies the dump stream.
    timeout_s, _, file = min(_watchdog_stack, key=lambda e: e[0])
    kwargs = {"exit": any(e for _, e, _ in _watchdog_stack)}
    if file is not None:
        kwargs["file"] = file
    faulthandler.dump_traceback_later(timeout_s, **kwargs)


@contextlib.contextmanager
def watchdog(timeout_s: float | None, *, exit: bool = False, file=None):
    """Dump all-thread stack traces if the enclosed block runs past ``timeout_s``.

    The collective-hang debugging tool: a deadlocked `psum`/`ppermute` (one
    process missing from a collective) blocks in C++ where Python sees
    nothing — `faulthandler.dump_traceback_later` fires from a watchdog
    thread and shows every thread's stack, and ``exit=True`` also kills the
    process so an orchestrator can restart it (generalizes the hand-rolled
    watchdog in ``tests/_distributed_worker.py``).  ``timeout_s=None``/0
    disarms (the ``IGG_WATCHDOG_S``-unset path).  faulthandler keeps one
    process-wide timer, so nesting is strictest-wins: the smallest timeout
    on the watchdog stack is armed and ``exit`` flags OR together — an
    inner watchdog can tighten but never weaken an enclosing one.
    """
    if not timeout_s:
        yield
        return
    arm_watchdog(timeout_s, exit=exit, file=file)
    t0 = time.monotonic()
    try:
        yield
    finally:
        disarm_watchdog()
        elapsed = time.monotonic() - t0
        if elapsed > timeout_s:
            # The block outlived this watchdog's deadline — the closest
            # observable proxy for "the dump fired" (faulthandler cannot
            # call back into Python).  NOT a guarantee: nested scopes
            # re-arm the one process-wide timer (`_rearm`), so the timer
            # may never have run `timeout_s` continuously; the stderr dump
            # is the ground truth, this event is the timeline marker.
            _telemetry.event(
                "watchdog.deadline_exceeded",
                timeout_s=timeout_s,
                elapsed_s=elapsed,
            )
            _telemetry.counter("resilience.watchdog_deadline_exceeded").inc()
            # Flight-recorder bundle (docs/observability.md): a blown
            # watchdog deadline is exactly the moment an operator needs
            # the span ring + metrics + config of this rank on disk.
            _tracing.dump_flight_recorder(
                "watchdog.deadline_exceeded",
                timeout_s=timeout_s,
                elapsed_s=elapsed,
            )


# -- Numerical guards ---------------------------------------------------------


class GuardError(RuntimeError):
    """A NaN/Inf guard tripped.  Carries the step and the offending blocks."""

    def __init__(self, message: str, *, step: int | None = None, report=None):
        super().__init__(message)
        self.step = step
        self.report = report


@dataclasses.dataclass(frozen=True)
class FieldReport:
    """Result of one `check_fields` probe.

    ``bad_blocks`` maps field name -> tuple of block ``coords`` (Cartesian
    mesh coordinates, the reference's ``coords``) holding at least one
    non-finite value.  Replicated across processes: every rank sees the
    same report and can take the same policy action.
    """

    names: tuple[str, ...]
    bad_blocks: dict[str, tuple[tuple[int, ...], ...]]

    @property
    def ok(self) -> bool:
        return not self.bad_blocks

    def summary(self) -> str:
        if self.ok:
            return f"all finite ({', '.join(self.names)})"
        parts = [
            f"{name}: block(s) {', '.join(str(c) for c in coords)}"
            for name, coords in self.bad_blocks.items()
        ]
        return "non-finite values in " + "; ".join(parts)


_probe_cache: dict = {}


def _clear_caches() -> None:
    _probe_cache.clear()


def _probe_fn(gg, shapes_dtypes):
    """Build (and cache) the jitted per-block finite probe.

    One program per (epoch, signature): each block reduces its fields to a
    per-field bad flag, scatters it into a ``dims``-shaped one-hot and
    `psum`s over all mesh axes — the result is a tiny REPLICATED
    ``(nfields, *dims)`` flag array every process can read without extra
    communication (the all-reduce rides the same compiled collectives as a
    step).  Cost: one elementwise isfinite pass + an all-reduce of
    ``nfields * prod(dims)`` int32s.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map

    key = (gg.epoch, shapes_dtypes)
    fn = _probe_cache.get(key)
    if fn is not None:
        return fn

    def block_flags(*fields):
        flags = []
        for A in fields:
            if jnp.issubdtype(A.dtype, jnp.inexact):
                bad = jnp.any(~jnp.isfinite(A)).astype(jnp.int32)
            else:
                bad = jnp.int32(0)  # integer fields cannot hold NaN/Inf
            flags.append(bad)
        return jnp.stack(flags)

    if gg.nprocs == 1 and not gg.force_spmd:
        fn = jax.jit(lambda *f: block_flags(*f).reshape((len(shapes_dtypes), 1, 1, 1)))
        _probe_cache[key] = fn
        return fn

    def per_block(*fields):
        flags = block_flags(*fields)  # (nfields,)
        onehot = jnp.zeros((len(shapes_dtypes), *gg.dims), jnp.int32)
        for i, (shp, _) in enumerate(shapes_dtypes):
            # Coordinates only over the FIELD's own dimensions: a lower-rank
            # field is replicated along the remaining mesh axes, and using
            # the device's full 3-D coords would report one phantom bad
            # block per replica (the replicas scatter at distinct cz).
            # Clamping those axes to 0 makes every replica-holding device
            # scatter at the same logical coords (the count psums up > 1,
            # which argwhere treats the same as 1).
            coords = tuple(
                lax.axis_index(AXIS_NAMES[d])
                if d < len(shp) and gg.dims[d] > 1
                else jnp.int32(0)
                for d in range(NDIMS)
            )
            onehot = lax.dynamic_update_slice(
                onehot,
                flags[i].reshape((1, 1, 1, 1)),
                (jnp.int32(i), *coords),
            )
        # psum over every mesh axis -> replicated on all devices/processes.
        return lax.psum(onehot, AXIS_NAMES)

    specs = tuple(P(*AXIS_NAMES[: len(s)]) for s, _ in shapes_dtypes)
    mapped = shard_map(
        per_block, mesh=gg.mesh, in_specs=specs, out_specs=P(), check_vma=False
    )
    fn = jax.jit(mapped)
    _probe_cache[key] = fn
    return fn


def check_fields(*fields, names: Sequence[str] | None = None) -> FieldReport:
    """Probe global-block field(s) for NaN/Inf; report offending blocks.

    The numerical-guard API (`igg.check_fields`): one cheap jitted
    all-reduce isnan/isinf pass over the given fields.  Returns a
    `FieldReport` whose ``bad_blocks`` names the Cartesian ``coords`` of
    every block holding a non-finite value — the information an operator
    needs to localize the fault on a pod (which host, which block), which a
    plain ``jnp.isnan(A).any()`` on the global array cannot give.

    Works on concrete global-block arrays (the models' time loops, any
    host-side loop).  Multi-host safe: the probe result is replicated, so
    every process sees the same report and the ``rollback`` policy cannot
    diverge across ranks.
    """
    from ..ops.halo import local_shape

    _grid.check_initialized()
    gg = _grid.global_grid()
    if not fields:
        raise ValueError("check_fields requires at least one field.")
    if names is None:
        names = tuple(f"field{i}" for i in range(len(fields)))
    else:
        names = tuple(names)
        if len(names) != len(fields):
            raise ValueError(
                f"names has {len(names)} entries for {len(fields)} fields."
            )
    sig = tuple((local_shape(A, gg), str(A.dtype)) for A in fields)
    flags = np.asarray(_probe_fn(gg, sig)(*fields))
    bad: dict[str, tuple[tuple[int, ...], ...]] = {}
    for i, name in enumerate(names):
        coords = tuple(tuple(int(c) for c in idx) for idx in np.argwhere(flags[i]))
        if coords:
            bad[name] = coords
    return FieldReport(names=names, bad_blocks=bad)


# -- Fault injection ----------------------------------------------------------

FAULT_KINDS = (
    "init_flake",
    "halo_corrupt",
    "worker_crash",
    "stall",
    "net_delay",
    "ckpt_corrupt",
    "ckpt_truncate",
    "bit_flip",
)

#: third spec component's prefix per fault kind (e.g. ``halo_corrupt:step3:block5``)
_TARGET_PREFIX = {
    "halo_corrupt": "block",
    "worker_crash": "proc",
    "stall": "proc",
    "net_delay": "proc",
    "ckpt_corrupt": "shard",
    "ckpt_truncate": "shard",
    "bit_flip": "proc",
}

#: ``bit_flip``'s reserved (non-field-name) placement components
BIT_FLIP_PLACEMENTS = ("transport", "ckpt")

#: kinds the seeded chaos schedule samples from by default (init_flake
#: excluded: it fires during bring-up, outside the per-step storm the
#: schedule models; bit_flip excluded from the DEFAULT draw because it is
#: guard-invisible — a storm that lands one in a run without the integrity
#: plane armed silently falsifies the result instead of exercising recovery.
#: ``kinds=…+bit_flip`` opts a storm in explicitly when ``IGG_INTEGRITY``
#: detectors are armed.)
CHAOS_KINDS = (
    "worker_crash",
    "stall",
    "net_delay",
    "ckpt_corrupt",
    "ckpt_truncate",
    "halo_corrupt",
)

#: chaos-mode defaults (spec grammar: ``chaos:seed=N:rate=R[:steps=M][:kinds=a+b]``)
CHAOS_STEPS_DEFAULT = 16


def chaos_schedule(
    seed: int,
    rate: float,
    *,
    steps: int = CHAOS_STEPS_DEFAULT,
    kinds: Sequence[str] = CHAOS_KINDS,
) -> list[str]:
    """The deterministic randomized fault storm of one chaos spec.

    Samples at most ONE fault per time-loop step (unambiguous
    ``(kind, step)`` identity — what lets a supervisor match ``fault.*``
    events back to the armed schedule and prune fired faults across
    relaunches): for each step ``1..steps``, with probability ``rate`` a
    kind is drawn uniformly from ``kinds``.  Pure function of its
    arguments (`random.Random(seed)`), so the supervisor, the soak driver
    and a test all derive the identical storm from the spec alone.
    Targets stay at each kind's default (crash/stall/delay: the last
    process; ckpt damage: shard 0; corruption: block 0).
    """
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"chaos rate must be in [0, 1] (got {rate})")
    if steps < 1:
        raise ValueError(f"chaos steps must be >= 1 (got {steps})")
    bad = [k for k in kinds if k not in FAULT_KINDS or k == "init_flake"]
    if bad:
        raise ValueError(
            f"chaos kinds {bad} not samplable; choose from {CHAOS_KINDS}"
        )
    rng = random.Random(seed)
    out = []
    for step in range(1, steps + 1):
        if rng.random() < rate:
            out.append(f"{rng.choice(list(kinds))}:step{step}")
    return out


def _parse_chaos_spec(spec: str) -> list[str]:
    """``chaos:seed=N:rate=R[:steps=M][:kinds=a+b]`` -> concrete specs."""
    fields: dict[str, str] = {}
    for part in spec.split(":")[1:]:
        key, sep, val = part.partition("=")
        if not sep or key not in ("seed", "rate", "steps", "kinds"):
            raise ValueError(
                f"IGG_FAULT_INJECT: {spec!r} — chaos takes "
                f"'chaos:seed=N:rate=R[:steps=M][:kinds=a+b]' "
                f"(got component {part!r})."
            )
        fields[key] = val
    try:
        seed = int(fields["seed"])
        rate = float(fields["rate"])
        steps = int(fields.get("steps", CHAOS_STEPS_DEFAULT))
    except (KeyError, ValueError):
        raise ValueError(
            f"IGG_FAULT_INJECT: {spec!r} — chaos needs integer seed=, "
            f"decimal rate= (and optional integer steps=)."
        )
    kinds = (
        tuple(fields["kinds"].split("+")) if "kinds" in fields else CHAOS_KINDS
    )
    return chaos_schedule(seed, rate, steps=steps, kinds=kinds)


def expand_fault_spec(spec: str | None) -> list[str]:
    """A comma-separated ``IGG_FAULT_INJECT`` value as CONCRETE per-fault
    specs, ``chaos:`` parts expanded through `chaos_schedule` — the form a
    supervisor arms, prunes (`fault_event_matches_spec`) and re-arms."""
    if not spec:
        return []
    out: list[str] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith("chaos:") or part == "chaos":
            out.extend(_parse_chaos_spec(part))
        else:
            FaultInjector.from_spec(part)  # validate eagerly
            out.append(part)
    return out


def fault_event_matches_spec(events: Sequence[dict], spec: str) -> bool:
    """Did one of these ``fault.*`` event records fire THIS concrete spec?

    The supervisor's cross-incarnation fire-once hygiene: a fault whose
    event is on the timeline is pruned from the next incarnation's
    environment (a crash at step N must not re-crash the restart that
    resumes from the step-N checkpoint).  Identity is ``(kind, step)``
    (`chaos_schedule` guarantees uniqueness); ``init_flake`` matches on
    any firing.
    """
    inj = FaultInjector.from_spec(spec)
    etype = f"fault.{inj.kind}"
    for e in events:
        if e.get("type") != etype:
            continue
        if inj.kind == "init_flake":
            return True
        if e.get("step") == inj.step:
            return True
    return False


@dataclasses.dataclass
class FaultInjector:
    """Deterministic fault injection, armed via ``IGG_FAULT_INJECT``.

    Spec grammar (see docs/robustness.md):

    * ``init_flake:N`` — the first ``N`` `init_distributed` attempts raise
      (simulated coordinator race); attempt ``N+1`` proceeds.  Proves the
      retry/backoff path end to end.
    * ``halo_corrupt:stepN[:blockB]`` — after time-loop step ``N``, a NaN is
      written into an interior cell of block ``B`` (Cartesian rank, default
      0).  Every process executes the same scatter (the target index is
      derived from the block's coords, which all ranks can compute), so the
      injection stays SPMD-consistent on multi-host runs.  Proves
      corruption→guard-trip.
    * ``worker_crash:stepN[:procP]`` — after time-loop step ``N`` (and after
      that step's checkpoint), process ``P`` (default: the last process)
      exits hard with status 17.  Proves crash→restart-from-checkpoint.
    * ``stall:stepN[:procP]`` — after time-loop step ``N``, process ``P``
      (default: the last process) sleeps `STALL_S` seconds before
      continuing — a transient hang, NOT a crash.  On a communicating grid
      every rank's loop wedges with it (the neighbors block in the next
      collective), which is exactly the condition the live plane's
      scrape-time step-stall rule (`utils.liveplane.StepStallRule`) exists
      to see from outside the loop; the soak ``live_plane`` scenario
      drives this end to end.
    * ``net_delay:stepN[:procP]`` — after time-loop step ``N``, process
      ``P`` (default: the last process) arms `NET_DELAY_S` seconds of
      latency on its NEXT host control collective (the skew-probe /
      ``broadcast_control`` transport, `utils.tracing.
      arm_collective_delay`): the rank enters the collective late and its
      peers block with it — a transient network fault that recovers on
      its own (the chaos storm's benign kind).
    * ``ckpt_corrupt:stepN[:shardS]`` — right after the step-``N`` checkpoint
      publishes, a byte of shard file ``S`` (default 0) is flipped WITHOUT
      updating the manifest (process 0 applies it).  Proves the CRC
      verification + generation fallback of `utils.checkpoint`.
    * ``ckpt_truncate:stepN[:shardS]`` — same, but the shard file is
      truncated to half its size (a torn write).
    * ``bit_flip:stepN[:field|transport|ckpt][:procP]`` — silent data
      corruption: ONE mantissa LSB flips, producing a perfectly FINITE
      wrong value that `check_fields` can never see (``halo_corrupt`` is
      its guard-VISIBLE twin — same injection point, NaN payload).  The
      optional placement component picks the detector under test: a FIELD
      NAME (or omitted: field 0) flips an interior cell of the committed
      post-step state — caught only by the shadow-step audit
      (``IGG_INTEGRITY_EVERY``); ``transport`` arms a payload-word flip on
      rank ``P``'s next checksummed halo hop (`ops.halo.
      arm_transport_flip`) — caught by the RECEIVER's transport checksum,
      implicating the sender; ``ckpt`` flips one payload byte after the
      lineage digests are taken but before the shard writer runs — CRC
      verifies clean (the bytes on disk are intact), only the lineage
      chain convicts the generation as poisoned-at-save.

    Each fault fires once per injector (a rolled-back or restarted run does
    not re-trip), mirroring how real transient faults behave.  Several
    faults compose as a comma-separated spec (parsed by `FaultSet`), e.g.
    ``worker_crash:step4:proc1,ckpt_corrupt:step4`` — crash AND damaged
    newest generation in one run, the elastic-failover drill.
    """

    kind: str | None = None
    step: int | None = None
    target: int | None = None  # halo_corrupt: block rank; worker_crash: process
    count: int = 0  # init_flake: remaining flaky attempts
    fired: bool = False
    #: bit_flip placement: a field NAME, "transport", "ckpt", or None (field 0)
    field: str | None = None

    #: exit status of an injected worker crash (distinct from real crashes)
    CRASH_STATUS = 17

    #: injected-stall duration in seconds (class attr: tests shrink it)
    STALL_S = 6.0

    #: injected host-collective latency in seconds (class attr: tests shrink)
    NET_DELAY_S = 1.5

    def spec(self) -> str:
        """The canonical spec string this injector parses back from (the
        supervisor's arm/prune round-trip)."""
        if self.kind is None:
            return ""
        if self.kind == "init_flake":
            return f"init_flake:{self.count}"
        out = f"{self.kind}:step{self.step}"
        if self.kind == "bit_flip" and self.field is not None:
            out += f":{self.field}"
        if self.target is not None:
            out += f":{_TARGET_PREFIX[self.kind]}{self.target}"
        return out

    @classmethod
    def from_spec(cls, spec: str | None) -> "FaultInjector":
        if not spec:
            return cls()
        parts = spec.split(":")
        kind = parts[0]
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"IGG_FAULT_INJECT: unknown fault kind {kind!r} in {spec!r}; "
                f"accepted kinds: {', '.join(FAULT_KINDS)} (format: "
                f"'init_flake:N' or 'halo_corrupt:stepN[:blockB]' or "
                f"'worker_crash:stepN[:procP]')."
            )
        if kind == "init_flake":
            if len(parts) != 2 or not parts[1].isdigit():
                raise ValueError(
                    f"IGG_FAULT_INJECT: {spec!r} — init_flake takes "
                    f"'init_flake:N' with N a non-negative integer count of "
                    f"attempts to fail."
                )
            return cls(kind=kind, count=int(parts[1]))
        tgt_prefix = _TARGET_PREFIX[kind]
        if kind == "bit_flip":
            if len(parts) not in (2, 3, 4) or not parts[1].startswith("step"):
                raise ValueError(
                    f"IGG_FAULT_INJECT: {spec!r} — bit_flip takes "
                    f"'bit_flip:stepN[:field|transport|ckpt][:procP]' with N "
                    f"the 1-based time-loop step."
                )
            try:
                step = int(parts[1][len("step"):])
            except ValueError:
                raise ValueError(
                    f"IGG_FAULT_INJECT: {spec!r} — step must be an integer, "
                    f"got {parts[1][len('step'):]!r}."
                )
            field = None
            target = None
            for comp in parts[2:]:
                if comp.startswith("proc") and comp[len("proc"):].isdigit():
                    if target is not None:
                        raise ValueError(
                            f"IGG_FAULT_INJECT: {spec!r} — bit_flip takes at "
                            f"most one 'procP' component."
                        )
                    target = int(comp[len("proc"):])
                elif comp.isdigit():
                    # A bare integer is ambiguous (field index? rank?) and
                    # would silently mis-read as a target on the other
                    # kinds' grammar — demand the explicit form.
                    raise ValueError(
                        f"IGG_FAULT_INJECT: {spec!r} — bare integer "
                        f"{comp!r} is not a bit_flip placement; name the "
                        f"FIELD (e.g. ':T'), a reserved placement "
                        f"(':transport' or ':ckpt'), or the target rank as "
                        f"':proc{comp}'."
                    )
                elif field is None:
                    field = comp
                else:
                    raise ValueError(
                        f"IGG_FAULT_INJECT: {spec!r} — bit_flip takes at "
                        f"most one placement component (field name, "
                        f"'transport' or 'ckpt'); got both {field!r} and "
                        f"{comp!r}."
                    )
            return cls(kind=kind, step=step, target=target, field=field)
        if len(parts) not in (2, 3) or not parts[1].startswith("step"):
            raise ValueError(
                f"IGG_FAULT_INJECT: {spec!r} — {kind} takes "
                f"'{kind}:stepN[:{tgt_prefix}P]' with N the 1-based "
                f"time-loop step."
            )
        try:
            step = int(parts[1][len("step"):])
        except ValueError:
            raise ValueError(
                f"IGG_FAULT_INJECT: {spec!r} — step must be an integer, "
                f"got {parts[1][len('step'):]!r}."
            )
        target = None
        if len(parts) == 3:
            if not parts[2].startswith(tgt_prefix):
                what = {
                    "block": "a block rank.",
                    "proc": "a process index.",
                    "shard": "a shard (writer process) index.",
                }[tgt_prefix]
                raise ValueError(
                    f"IGG_FAULT_INJECT: {spec!r} — the third component must "
                    f"be '{tgt_prefix}P' with P " + what
                )
            try:
                target = int(parts[2][len(tgt_prefix):])
            except ValueError:
                raise ValueError(
                    f"IGG_FAULT_INJECT: {spec!r} — {tgt_prefix} must be an "
                    f"integer, got {parts[2][len(tgt_prefix):]!r}."
                )
        return cls(kind=kind, step=step, target=target)

    @property
    def active(self) -> bool:
        return self.kind is not None

    # - init_flake -

    def maybe_flake_init(self) -> None:
        """Raise a simulated coordinator race while flaky attempts remain."""
        if self.kind == "init_flake" and self.count > 0:
            self.count -= 1
            _telemetry.event("fault.init_flake", remaining=self.count)
            raise RuntimeError(
                "IGG_FAULT_INJECT(init_flake): simulated coordinator race "
                f"({self.count} flaky attempt(s) remaining)"
            )

    # - halo_corrupt -

    def maybe_corrupt(self, state: tuple, step: int) -> tuple:
        """After step ``step``: NaN-poison one interior cell of the target block.

        Runs identically on EVERY process (same scatter, same global index),
        so multi-host programs stay SPMD-consistent; only the target block's
        owner actually holds the poisoned cell.
        """
        if self.kind != "halo_corrupt" or self.fired or step != self.step:
            return state
        self.fired = True
        A = self._poison_block(state[0], announce_step=step)
        return (A, *state[1:])

    def _poison_block(self, A, announce_step=None):
        import jax.numpy as jnp

        idx = _block_interior_index(A, self.target or 0)
        _telemetry.event(
            "fault.halo_corrupt",
            index=list(int(i) for i in idx),
            block=self.target or 0,
            step=announce_step if announce_step is not None else self.step,
        )
        if _safe_process_index() == 0:
            at = "" if announce_step is None else f" after step {announce_step}"
            print(
                f"[igg.resilience] IGG_FAULT_INJECT(halo_corrupt): writing "
                f"NaN into global index {tuple(idx)} (block "
                f"{self.target or 0}){at}",
                file=sys.stderr,
                flush=True,
            )
        return A.at[idx].set(jnp.nan)

    def corrupt_halo_hook(self, fields: tuple) -> tuple:
        """`ops.halo` post-exchange hook: poison direct `update_halo` output.

        Step-agnostic (direct halo calls carry no step): fires on the first
        exchange after arming.  Installed by the pytest ``fault_injection``
        fixture / `install_halo_fault_hook`.
        """
        if self.kind != "halo_corrupt" or self.fired:
            return fields
        self.fired = True
        return (self._poison_block(fields[0]), *fields[1:])

    # - worker_crash -

    def maybe_crash(self, step: int) -> None:
        """After step ``step``'s guard+checkpoint: hard-exit this process."""
        if self.kind != "worker_crash" or self.fired or step != self.step:
            return  # cheap short-circuit: this runs every step of every loop
        want = self.target if self.target is not None else _last_process_index()
        if _safe_process_index() != want:
            return
        self.fired = True
        # The event line is a single O_APPEND os.write — it survives the
        # os._exit below, which is exactly what the failover drill's
        # machine-readable timeline needs (the crash marker).
        _telemetry.event(
            "fault.worker_crash", step=step, status=self.CRASH_STATUS
        )
        # Same discipline for the flight bundle: one complete line on disk
        # BEFORE the hard exit (the soak drill verifies it exists).
        _tracing.dump_flight_recorder(
            "fault.worker_crash", step=step, status=self.CRASH_STATUS
        )
        print(
            f"[igg.resilience] IGG_FAULT_INJECT(worker_crash): exiting hard "
            f"after step {step} (status {self.CRASH_STATUS})",
            file=sys.stderr,
            flush=True,
        )
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(self.CRASH_STATUS)

    # - stall -

    def maybe_stall(self, step: int) -> None:
        """After step ``step``: the target process sleeps `STALL_S` seconds.

        The event line lands BEFORE the sleep (the timeline marker an
        operator correlates the live-plane ``alert.step_stall`` against).
        """
        if self.kind != "stall" or self.fired or step != self.step:
            return
        want = self.target if self.target is not None else _last_process_index()
        if _safe_process_index() != want:
            return
        self.fired = True
        _telemetry.event("fault.stall", step=step, sleep_s=self.STALL_S)
        print(
            f"[igg.resilience] IGG_FAULT_INJECT(stall): sleeping "
            f"{self.STALL_S}s after step {step}",
            file=sys.stderr,
            flush=True,
        )
        time.sleep(self.STALL_S)

    # - net_delay -

    def maybe_net_delay(self, step: int) -> None:
        """After step ``step``: arm `NET_DELAY_S` of latency on the target
        process's NEXT host control collective (`utils.tracing.
        arm_collective_delay` — the skew-probe / `broadcast_control`
        transport).  A transient network fault, not a hang: the delayed
        rank enters the collective late, its peers block with it, and the
        skew probe sees the straggle — nothing needs supervisor
        intervention, which is exactly what a chaos storm needs between
        the faults that do."""
        if self.kind != "net_delay" or self.fired or step != self.step:
            return
        want = self.target if self.target is not None else _last_process_index()
        if _safe_process_index() != want:
            return
        self.fired = True
        _telemetry.event("fault.net_delay", step=step, delay_s=self.NET_DELAY_S)
        print(
            f"[igg.resilience] IGG_FAULT_INJECT(net_delay): delaying the "
            f"next host control collective by {self.NET_DELAY_S}s "
            f"(after step {step})",
            file=sys.stderr,
            flush=True,
        )
        _tracing.arm_collective_delay(self.NET_DELAY_S)

    # - ckpt_corrupt / ckpt_truncate -

    def maybe_damage_checkpoint(self, step_dir: str, step: int) -> None:
        """After the step-``step`` checkpoint published: damage one shard.

        Called by `utils.checkpoint.save_checkpoint` on process 0 right
        after the atomic rename — the manifest already vouches for the
        intact bytes, so the damage is exactly what `verify_checkpoint`
        exists to catch.  ``ckpt_corrupt`` flips one byte mid-file (CRC
        mismatch); ``ckpt_truncate`` halves the file (size mismatch).
        """
        if (
            self.kind not in ("ckpt_corrupt", "ckpt_truncate")
            or self.fired
            or step != self.step
        ):
            return
        self.fired = True
        shard = os.path.join(step_dir, f"shards_p{self.target or 0}.npz")
        size = os.path.getsize(shard)
        if self.kind == "ckpt_truncate":
            os.truncate(shard, size // 2)
            what = f"truncated to {size // 2} of {size} bytes"
        else:
            with open(shard, "r+b") as f:
                f.seek(size // 2)
                byte = f.read(1)
                f.seek(size // 2)
                f.write(bytes([byte[0] ^ 0xFF]))
            what = f"flipped byte at offset {size // 2}"
        _telemetry.event(
            f"fault.{self.kind}", step=step, shard=self.target or 0, what=what
        )
        print(
            f"[igg.resilience] IGG_FAULT_INJECT({self.kind}): {what} in "
            f"{shard} after step {step}",
            file=sys.stderr,
            flush=True,
        )

    # - bit_flip -

    def _bit_flip_armed(self, step: int, placement: str | None) -> bool:
        """Does this injector's bit_flip fire at ``step`` for ``placement``
        (None = the state placement: any non-reserved ``field``)?"""
        if self.kind != "bit_flip" or self.fired or step != self.step:
            return False
        if placement is None:
            return self.field not in BIT_FLIP_PLACEMENTS
        return self.field == placement

    def maybe_bit_flip(self, state: tuple, step: int,
                       names: Sequence[str] | None = None) -> tuple:
        """State placement: after step ``step``, flip ONE mantissa LSB of an
        interior cell of the committed state — a finite wrong value, by
        construction invisible to the NaN/Inf guard (``halo_corrupt`` is the
        guard-visible twin).  Only the shadow-step audit can convict it.
        Runs identically on every process (same scatter, same global index),
        like `maybe_corrupt`.
        """
        if not self._bit_flip_armed(step, None):
            return state
        fidx = 0
        if self.field is not None:
            if names is None or self.field not in tuple(names):
                have = ", ".join(map(repr, names)) if names else "(unnamed)"
                raise ValueError(
                    f"IGG_FAULT_INJECT(bit_flip): field {self.field!r} does "
                    f"not exist in this run — the guarded state carries "
                    f"{have}. The spec is 'bit_flip:stepN[:field|transport|"
                    f"ckpt][:procP]'; a field component must name one of the "
                    f"run's fields."
                )
            fidx = tuple(names).index(self.field)
        self.fired = True
        A = self._flip_state_cell(state[fidx], step, fidx)
        return (*state[:fidx], A, *state[fidx + 1:])

    def _flip_state_cell(self, A, step: int, fidx: int):
        import jax.numpy as jnp
        from jax import lax

        from ..ops.gather import _word_dtype

        idx = _block_interior_index(A, self.target or 0)
        _telemetry.event(
            "fault.bit_flip", step=step, placement="state",
            field=self.field or f"field{fidx}",
            index=list(int(i) for i in idx), proc=self.target or 0,
        )
        if _safe_process_index() == 0:
            print(
                f"[igg.resilience] IGG_FAULT_INJECT(bit_flip): flipping one "
                f"mantissa bit at global index {tuple(idx)} "
                f"(field {self.field or fidx}, block {self.target or 0}) "
                f"after step {step}",
                file=sys.stderr,
                flush=True,
            )
        val = A[idx]
        if jnp.issubdtype(A.dtype, jnp.floating):
            word = lax.bitcast_convert_type(val, _word_dtype(A.dtype))
            new = lax.bitcast_convert_type(
                word ^ np.array(1, word.dtype), A.dtype
            )
        else:
            new = val ^ np.array(1, A.dtype)
        return A.at[idx].set(new)

    def maybe_bit_flip_transport(self, step: int) -> None:
        """Transport placement: arm a payload-word flip on rank ``P``'s next
        checksummed halo hop (`ops.halo.arm_transport_flip`) — the
        arm-on-step / fire-on-next-collective idiom of ``net_delay``.  Every
        process arms (the flip is rank-conditional INSIDE the traced
        program), so the SPMD build stays identical on all ranks."""
        if not self._bit_flip_armed(step, "transport"):
            return
        self.fired = True
        from ..ops import halo as _halo

        want = self.target if self.target is not None else 0
        _telemetry.event(
            "fault.bit_flip", step=step, placement="transport", proc=want
        )
        if _safe_process_index() == 0:
            print(
                f"[igg.resilience] IGG_FAULT_INJECT(bit_flip): arming an "
                f"in-flight payload-word flip on rank {want}'s next "
                f"checksummed halo transport (after step {step})",
                file=sys.stderr,
                flush=True,
            )
        _halo.arm_transport_flip(want)

    def maybe_bit_flip_ckpt(self, payload: dict, step: int) -> None:
        """Checkpoint placement: flip one payload byte AFTER the lineage
        digests were taken and BEFORE the shard writer runs (`utils.
        checkpoint._save_checkpoint` calls this between the two).  The CRC
        manifest then vouches for the flipped bytes — the file on disk is
        intact — and only the lineage chain convicts the generation as
        poisoned-at-save.  Mutates the payload dict's arrays in place; the
        writer process ``P`` (default 0) applies it."""
        if not self._bit_flip_armed(step, "ckpt"):
            return
        want = self.target if self.target is not None else 0
        if _safe_process_index() != want:
            return
        self.fired = True
        keys = sorted(k for k in payload if not k.endswith("_shape"))
        if not keys:
            return
        # copy=True: the payload entries are zero-copy views of the live
        # device buffers and arrive read-only
        arr = np.array(payload[keys[0]], copy=True)
        arr.view(np.uint8).reshape(-1)[0] ^= 1
        payload[keys[0]] = arr
        _telemetry.event(
            "fault.bit_flip", step=step, placement="ckpt", key=keys[0],
            proc=want,
        )
        print(
            f"[igg.resilience] IGG_FAULT_INJECT(bit_flip): flipped one "
            f"payload byte of {keys[0]} between digest and write of the "
            f"step-{step} checkpoint",
            file=sys.stderr,
            flush=True,
        )


@dataclasses.dataclass
class FaultSet:
    """Several `FaultInjector`s armed at once (comma-separated spec).

    The process-wide injector `get_fault_injector` returns: every hook
    point (`maybe_flake_init`, `maybe_corrupt`, `maybe_crash`,
    `maybe_damage_checkpoint`, `corrupt_halo_hook`) fans out to each armed
    fault, so e.g. ``worker_crash:step4:proc1,ckpt_corrupt:step4`` crashes
    a worker AND damages the newest checkpoint generation in one run — the
    supervised-failover drill `scripts/soak.py` and the 2-process elastic
    restart test run.
    """

    injectors: tuple = ()

    @classmethod
    def from_spec(cls, spec: str | None) -> "FaultSet":
        """Parse a comma-separated spec; ``chaos:seed=N:rate=R[...]`` parts
        expand into their deterministic storm (`chaos_schedule`) first."""
        return cls(
            tuple(
                FaultInjector.from_spec(part)
                for part in expand_fault_spec(spec)
            )
        )

    @property
    def active(self) -> bool:
        return any(i.active for i in self.injectors)

    def maybe_flake_init(self) -> None:
        for i in self.injectors:
            i.maybe_flake_init()

    def maybe_corrupt(self, state: tuple, step: int) -> tuple:
        for i in self.injectors:
            state = i.maybe_corrupt(state, step)
        return state

    def corrupt_halo_hook(self, fields: tuple) -> tuple:
        for i in self.injectors:
            fields = i.corrupt_halo_hook(fields)
        return fields

    def maybe_crash(self, step: int) -> None:
        for i in self.injectors:
            i.maybe_crash(step)

    def maybe_stall(self, step: int) -> None:
        for i in self.injectors:
            i.maybe_stall(step)

    def maybe_net_delay(self, step: int) -> None:
        for i in self.injectors:
            i.maybe_net_delay(step)

    def maybe_bit_flip(self, state: tuple, step: int,
                       names: Sequence[str] | None = None) -> tuple:
        for i in self.injectors:
            state = i.maybe_bit_flip(state, step, names)
        return state

    def maybe_bit_flip_transport(self, step: int) -> None:
        for i in self.injectors:
            i.maybe_bit_flip_transport(step)

    def maybe_bit_flip_ckpt(self, payload: dict, step: int) -> None:
        for i in self.injectors:
            i.maybe_bit_flip_ckpt(payload, step)

    def specs(self) -> list[str]:
        """Canonical per-fault spec strings (the supervisor round-trip)."""
        return [i.spec() for i in self.injectors if i.active]

    def maybe_damage_checkpoint(self, step_dir: str, step: int) -> None:
        for i in self.injectors:
            i.maybe_damage_checkpoint(step_dir, step)


def _last_process_index() -> int:
    return _telemetry.process_count() - 1


def _block_interior_index(A, block_rank: int) -> tuple:
    """A global index inside block ``block_rank`` of global-block field ``A``,
    one cell off the block edge (so frozen boundary rings don't mask it and
    the models' interior updates propagate it).  Derived purely from the grid
    topology — every process computes the same index."""
    from ..ops.halo import local_shape
    from ..parallel import topology

    gg = _grid.global_grid()
    if not 0 <= block_rank < gg.nprocs:
        raise ValueError(
            f"IGG_FAULT_INJECT(halo_corrupt): block {block_rank} is out of "
            f"range for this grid ({gg.nprocs} blocks, dims {gg.dims})."
        )
    coords = topology.coords_of_rank(block_rank, gg.dims)
    lsh = local_shape(A, gg)
    return tuple(
        c * n + min(1, n - 1) for c, n in zip(coords[: len(lsh)], lsh)
    )


_injector: FaultSet | None = None
_injector_spec: str | None = None


def get_fault_injector() -> FaultSet:
    """The process-wide injector for the current ``IGG_FAULT_INJECT`` value
    (a `FaultSet`: the spec may arm several comma-separated faults).

    Cached per spec string so fired/remaining state persists across calls;
    changing the env var re-arms automatically, `reset_fault_injector`
    re-arms explicitly (the pytest fixture path).
    """
    global _injector, _injector_spec
    spec = _config.fault_inject_env()
    if _injector is None or spec != _injector_spec:
        _injector = FaultSet.from_spec(spec)
        _injector_spec = spec
    return _injector


def reset_fault_injector() -> None:
    global _injector, _injector_spec
    _injector = None
    _injector_spec = None


def install_halo_fault_hook() -> None:
    """Wire the active injector into `ops.halo`'s post-exchange hook point."""
    from ..ops import halo as _halo

    inj = get_fault_injector()
    _halo.set_post_exchange_hook(inj.corrupt_halo_hook if inj.active else None)


# -- Run guard (the models' time-loop hook) -----------------------------------


_copy_jit = None

#: shared reusable null context for the untraced step pipeline
#: (`contextlib.nullcontext` instances are stateless and re-enterable)
_NULL_CM = contextlib.nullcontext()


def snapshot_state(state: tuple) -> tuple:
    """Device-side bit-exact copy of a state tuple (fresh buffers).

    A plain reference is not enough for rollback: the models' step functions
    donate their inputs, so the snapshot must own separate buffers.  `jnp.copy`
    under jit produces a genuine device copy with the input's sharding.
    """
    global _copy_jit
    import jax
    import jax.numpy as jnp

    if _copy_jit is None:
        _copy_jit = jax.jit(jnp.copy)
    return tuple(_copy_jit(A) for A in state)


def guarded_time_loop(step_fn, state: tuple, nt: int, *, guard: "RunGuard",
                      sync_every_step: bool = False,
                      model: str | None = None,
                      bytes_per_step: int | None = None) -> tuple:
    """The models' host-side time loop with the guard pipeline attached.

    Resumes from the guard's checkpoint dir when one exists, then advances
    to step ``nt``, running `RunGuard.on_step` after every step (fault
    injection → shadow-step audit at the ``integrity_every`` cadence →
    NaN/Inf guard → checkpoint → crash injection; rollback may rewind the
    loop variable).  Shared by the three models' ``run()`` so the guard
    semantics cannot drift between them.

    ``model`` switches on the per-step telemetry (docs/observability.md):
    wall time, steps/s and — with ``bytes_per_step`` (the solver's
    must-stream bytes model, `telemetry.teff_bytes`) — the built-in
    ``T_eff`` histogram, plus the rank-0 ``IGG_HEARTBEAT_EVERY`` heartbeat.
    Per-step wall time is the LOOP iteration's host time (dispatch + sync +
    guard pipeline), exact when each step synchronizes and amortized-only
    otherwise.  With ``IGG_TELEMETRY=0`` (or ``model=None``) the loop takes
    the zero-allocation branch: one ``is not None`` check per step.
    """
    state, it = guard.start(state)
    enabled = guard.enabled  # skip the per-step pipeline entirely when idle
    tele = (
        _telemetry.step_loop(
            model, bytes_per_step=bytes_per_step, start_step=it,
            total_steps=nt,
        )
        if model is not None
        else None
    )
    if it > nt:
        # A checkpoint past the requested horizon is almost always a stale
        # directory (e.g. a previous longer run) — returning it silently
        # would mislabel old physics as this run's result.
        warnings.warn(
            f"resumed checkpoint is at step {it}, past the requested "
            f"nt={nt}; returning the checkpointed state unchanged (stale "
            f"checkpoint_dir?)",
            RuntimeWarning,
            stacklevel=2,
        )
    # Live-plane escalation wiring (docs/observability.md): while this loop
    # runs, a CRITICAL anomaly alert (from the heartbeat tick or a scrape)
    # forces an out-of-cadence guard probe instead of scrolling past as a
    # log line.  Subscribed only for the loop's lifetime.
    _liveplane = None
    if tele is not None and enabled:
        from . import liveplane as _liveplane_mod

        _liveplane = _liveplane_mod
        _liveplane.subscribe(guard.on_alert)
    try:
        return _guarded_loop_body(
            step_fn, state, nt, it, guard, enabled, sync_every_step,
            model, tele,
        )
    finally:
        if _liveplane is not None:
            _liveplane.unsubscribe(guard.on_alert)
        if tele is not None:
            # Crash-safe capture stop (docs/observability.md device
            # timeline): a profiler window still open when the loop exits
            # through a guard trip / injected fault stops HERE, so the
            # bytes already captured land next to the flight bundle
            # instead of dying with the process state.  Never raises.
            from . import profiling as _profiling

            _profiling.close_open_capture("scope_exit")


def _guarded_loop_body(step_fn, state, nt, it, guard, enabled,
                       sync_every_step, model, tele) -> tuple:
    import jax

    from .compat import trace_annotation

    while it < nt:
        # The ``igg.step`` host span (docs/observability.md): one span per
        # loop iteration — dispatch + sync + guard pipeline, the same wall
        # time the step_seconds histogram records — tagged so a merged
        # cross-rank trace aligns steps BY NUMBER; the profiler annotation
        # rides along for on-device captures.  Untraced loops reuse the
        # shared null managers (the zero-allocation contract).
        if tele is None:
            span = ann = _NULL_CM
        else:
            span = _tracing.trace_span("igg.step", model=model, step=it + 1)
            ann = trace_annotation(f"igg_step[{model}]")
        with span:
            # Shadow-audit retention (docs/robustness.md): off-cadence steps
            # pay one `is not None`-style check; on-cadence steps snapshot
            # the pre-step state the audit re-executes from.
            pre = guard.audit_snapshot(state, it) if enabled else None
            with ann:
                state = step_fn(*state)
            if sync_every_step:
                jax.block_until_ready(state)
            it += 1
            if enabled:
                state, it = guard.on_step(state, it, replay=(step_fn, pre))
        if tele is not None:
            tele.on_step(it)
    if tele is not None:
        tele.finish(it)
    return state


class RunGuard:
    """Guard + checkpoint + fault-injection driver for a host-side time loop.

    Used by the three models' ``run()`` loops::

        guard = RunGuard(guard_every=10, policy="rollback",
                         checkpoint_every=100, checkpoint_dir="/ckpt",
                         names=("T", "Cp"))
        state, it = guard.start(state)
        while it < nt:
            state = step(*state)
            it += 1
            state, it = guard.on_step(state, it)

    Per step, in order: (1) fault injection (``halo_corrupt``, ``bit_flip``),
    (2) the shadow-step audit every ``integrity_every`` steps
    (``IGG_INTEGRITY_EVERY``): the loop retained a pre-step snapshot
    (`audit_snapshot`), the just-committed step re-executes from it and the
    two results bit-compare (`integrity.audit_fields`) — a mismatch raises
    `integrity.IntegrityError` BEFORE any checkpoint can persist the corrupt
    state, with a ``reason=sdc`` flight bundle naming the implicated
    rank(s), (3) the NaN/Inf guard every ``guard_every`` steps with the
    ``raise`` | ``warn`` | ``rollback`` policy, (4) checkpoint every
    ``checkpoint_every`` steps (only ever of guard-passed state) followed by
    retention pruning when ``checkpoint_keep`` (``IGG_CHECKPOINT_KEEP``) is
    set — pruning never deletes the only integrity-verified generation,
    (5) fault injection (``worker_crash`` — after the checkpoint, so restart
    resumes exactly at the crash point — ``stall``, ``net_delay``, and the
    ``bit_flip`` transport arming).  Rollback restores the last good
    snapshot (in-memory; the disk checkpoint serves cross-process restart)
    and rewinds ``it``.  A pending CRITICAL live-plane alert (`on_alert`,
    subscribed by `guarded_time_loop`) forces the step-(3) probe out of
    cadence at the next step.

    All knobs resolve kwarg > ``IGG_*`` env > default (the reference's
    configuration tiers); ``IGG_INTEGRITY=0`` force-disables the audit
    cadence regardless of either tier (the pinned zero-overhead switch,
    like ``IGG_TELEMETRY=0``).
    """

    def __init__(
        self,
        *,
        guard_every: int | None = None,
        policy: str | None = None,
        checkpoint_every: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_keep: int | None = None,
        names: Sequence[str] | None = None,
        max_rollbacks: int = 3,
        injector: "FaultInjector | FaultSet | None" = None,
        integrity_every: int | None = None,
    ):
        env_ge = _config.guard_every_env()
        env_pol = _config.guard_policy_env()
        env_ce = _config.checkpoint_every_env()
        env_dir = _config.checkpoint_dir_env()
        env_keep = _config.checkpoint_keep_env()
        env_ie = _config.integrity_every_env()
        self.guard_every = int(
            guard_every if guard_every is not None else (env_ge or 0)
        )
        self.policy = policy if policy is not None else (env_pol or "raise")
        self.checkpoint_every = int(
            checkpoint_every if checkpoint_every is not None else (env_ce or 0)
        )
        self.checkpoint_dir = (
            checkpoint_dir if checkpoint_dir is not None else env_dir
        )
        # Retention: keep this many newest generations, pruning the rest
        # after every save (0 = unbounded).  Pruning never deletes the only
        # integrity-verified generation (`checkpoint.prune_checkpoints`).
        self.checkpoint_keep = int(
            checkpoint_keep if checkpoint_keep is not None else (env_keep or 0)
        )
        # Shadow-step audit cadence (docs/robustness.md).  ``IGG_INTEGRITY=0``
        # overrides BOTH tiers to 0: the master switch pins the whole
        # integrity plane to zero overhead, whatever a cadence knob says.
        self.integrity_every = int(
            integrity_every if integrity_every is not None else (env_ie or 0)
        )
        if _config.integrity_enabled_env() is False:
            self.integrity_every = 0
        if self.integrity_every < 0:
            raise ValueError(
                f"integrity_every must be >= 0 (got {self.integrity_every})"
            )
        if self.guard_every < 0:
            raise ValueError(f"guard_every must be >= 0 (got {self.guard_every})")
        if self.checkpoint_every < 0:
            raise ValueError(
                f"checkpoint_every must be >= 0 (got {self.checkpoint_every})"
            )
        if self.checkpoint_keep < 0:
            raise ValueError(
                f"checkpoint_keep must be >= 0 (got {self.checkpoint_keep}; "
                f"0 keeps every generation)"
            )
        if self.policy not in _config.GUARD_POLICIES:
            raise ValueError(
                f"guard policy must be one of "
                f"{', '.join(map(repr, _config.GUARD_POLICIES))}, got {self.policy!r}."
            )
        if self.checkpoint_every and not self.checkpoint_dir:
            raise ValueError(
                "checkpoint_every > 0 requires a checkpoint_dir (kwarg or "
                "IGG_CHECKPOINT_DIR)."
            )
        self.names = tuple(names) if names is not None else None
        self.max_rollbacks = max_rollbacks
        self.rollbacks = 0
        self._last_good: tuple | None = None
        self._last_good_step = 0
        self._injector = injector if injector is not None else get_fault_injector()
        # Live-plane escalation (utils.liveplane): a pending critical alert
        # staged by `on_alert`; the next `on_step` consumes it as a forced
        # out-of-cadence field probe.
        self._alert: dict | None = None

    @property
    def enabled(self) -> bool:
        return bool(
            self.guard_every
            or self.checkpoint_every
            or self.integrity_every
            or self._injector.active
        )

    def start(self, state: tuple) -> tuple:
        """Resume from the latest checkpoint if one exists, else step 0.

        Returns ``(state, start_step)``.  The step-0 state is snapshotted as
        the initial rollback target when the policy needs one.
        """
        it = 0
        if self.checkpoint_dir:
            from . import checkpoint as _ckpt

            latest = _ckpt.latest_checkpoint(self.checkpoint_dir)
            if latest is not None:
                # latest_checkpoint already CRC-verified this generation:
                # don't pay a second full read+CRC pass over every shard.
                state, it, _ = _ckpt.restore_checkpoint(
                    latest, like=state, verify=False
                )
                _telemetry.event("run.resumed", step=it, path=latest)
                _telemetry.counter("resilience.resumes").inc()
                print(
                    f"[igg.resilience] resumed from checkpoint {latest} "
                    f"(step {it})",
                    file=sys.stderr,
                    flush=True,
                )
        if self.policy == "rollback" and self.guard_every:
            self._last_good = snapshot_state(state)
            self._last_good_step = it
        return state, it

    def on_alert(self, alert: dict) -> None:
        """Live-plane subscriber (`utils.liveplane.subscribe`): a CRITICAL
        alert escalates into the guard machinery — the next `on_step` runs
        the NaN/Inf field probe immediately, out of cadence, under the
        configured policy.  Warn-severity alerts stay observability-only.
        Thread-safe by construction: one reference assignment (the engine
        may call from the scrape thread)."""
        if alert.get("severity") == "critical":
            self._alert = alert

    def audit_snapshot(self, state: tuple, it: int) -> tuple | None:
        """Pre-step state retained for the shadow audit of step ``it + 1``,
        or None when that step is off-cadence.  Called by the loop BEFORE the
        step executes; the snapshot owns fresh buffers (`snapshot_state`), so
        donating step functions can consume it in the re-execution."""
        if not self.integrity_every or (it + 1) % self.integrity_every != 0:
            return None
        return snapshot_state(state)

    def on_step(self, state: tuple, it: int, replay=None) -> tuple:
        """Run the per-step guard pipeline; returns ``(state, it)``.

        ``replay``: ``(step_fn, pre_state_or_None)`` from the loop — when the
        retained `audit_snapshot` is present, step ``it`` re-executes from it
        and bit-compares against the committed ``state`` (the shadow-step
        audit).  Injection runs FIRST, so an armed state-placement
        ``bit_flip`` lands in the committed copy and the clean re-execution
        convicts it — the detection matrix's compute-placement leg."""
        state = self._injector.maybe_corrupt(state, it)
        state = self._injector.maybe_bit_flip(state, it, self.names)
        if replay is not None and replay[1] is not None:
            state = self._audit(state, it, replay)
        escalated, self._alert = self._alert, None
        if escalated is not None and _last_process_index() > 0:
            # Multi-process grid: `check_fields` is a COLLECTIVE, and an
            # alert is rank-LOCAL — a probe keyed on it would be exactly
            # the SPMD-divergence (deadlock) class the static analyzer
            # pins.  The alert event + health view carry the signal;
            # cross-rank escalation is an operator decision.
            escalated = None
        if escalated is not None:
            _telemetry.event(
                "guard.alert_probe", step=it, rule=escalated.get("rule"),
                severity=escalated.get("severity"),
            )
            _telemetry.counter("resilience.alert_probes").inc()
        do_guard = (
            (self.guard_every and it % self.guard_every == 0)
            or escalated is not None
        )
        do_ckpt = self.checkpoint_every and it % self.checkpoint_every == 0
        # Checkpoints must only ever hold guard-passed state: when guarding
        # is on, a checkpoint step that falls between probe points is probed
        # too (guard_every=3, checkpoint_every=2 must not persist a NaN born
        # at step 2 and first probed at step 3).
        if do_guard or (do_ckpt and self.guard_every):
            report = check_fields(*state, names=self.names)
            if not report.ok:
                state, it = self._trip(state, it, report)
                return state, it  # fresh state: skip checkpoint/crash this round
            if self.policy == "rollback":
                self._last_good = snapshot_state(state)
                self._last_good_step = it
        if do_ckpt:
            from . import checkpoint as _ckpt

            _ckpt.save_checkpoint(self.checkpoint_dir, state, it)
            if self.checkpoint_keep:
                _ckpt.prune_checkpoints(
                    self.checkpoint_dir, keep=self.checkpoint_keep
                )
        self._injector.maybe_crash(it)
        self._injector.maybe_stall(it)
        self._injector.maybe_net_delay(it)
        self._injector.maybe_bit_flip_transport(it)
        return state, it

    def _audit(self, state: tuple, it: int, replay) -> tuple:
        """The shadow-step audit of step ``it`` (docs/robustness.md).

        Re-executes the step from the retained pre-step snapshot and
        bit-compares against the committed result.  Healthy hardware is
        run-to-run deterministic under XLA, so ANY difference is silent data
        corruption; the verdict is replicated (`integrity.audit_fields`), so
        every rank raises together — no rank-local collective divergence.
        Raises BEFORE the checkpoint stage so corrupt state never persists.
        """
        from ..integrity import audit_fields
        from ..integrity.errors import IntegrityError

        step_fn, pre = replay
        redone = step_fn(*pre)
        report = audit_fields(tuple(state), tuple(redone), names=self.names)
        _telemetry.counter("integrity.audits").inc()
        if report.ok:
            return state
        _telemetry.counter("integrity.audit_mismatches").inc()
        _telemetry.event(
            "integrity.audit_mismatch", detector="shadow_audit", step=it,
            report=report.summary(),
            implicated_ranks=list(report.implicated_ranks),
        )
        implicated = (
            report.implicated_ranks[0] if report.implicated_ranks else -1
        )
        _tracing.dump_flight_recorder(
            "sdc", detector="shadow_audit", step=it,
            implicated_rank=implicated,
            implicated_ranks=list(report.implicated_ranks),
            report=report.summary(),
        )
        raise IntegrityError(
            f"shadow-step audit mismatch at step {it}: {report.summary()}. "
            f"The committed step and its re-execution from identical inputs "
            f"differ bitwise — silent data corruption on the implicated "
            f"rank(s); quarantine them (restart-in-place re-runs the lying "
            f"core).",
            detector="shadow_audit", implicated_rank=implicated, step=it,
        )

    def _trip(self, state: tuple, it: int, report: FieldReport) -> tuple:
        msg = f"NaN/Inf guard tripped at step {it}: {report.summary()}"
        _telemetry.event(
            "guard.trip", step=it, policy=self.policy, report=report.summary()
        )
        _telemetry.counter("resilience.guard_trips").inc()
        _tracing.dump_flight_recorder(
            "guard.trip", step=it, policy=self.policy,
            report=report.summary(),
        )
        if self.policy == "raise":
            raise GuardError(msg, step=it, report=report)
        if self.policy == "warn":
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
            return state, it
        # rollback
        if self._last_good is None:
            raise GuardError(
                msg + " — policy='rollback' but no good state was ever "
                "recorded (is guard_every set?)",
                step=it,
                report=report,
            )
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise GuardError(
                msg + f" — giving up after {self.max_rollbacks} rollback(s): "
                "the fault re-occurs deterministically",
                step=it,
                report=report,
            )
        warnings.warn(
            msg + f" — rolling back to step {self._last_good_step}",
            RuntimeWarning,
            stacklevel=3,
        )
        _telemetry.event(
            "guard.rollback", step=it, to_step=self._last_good_step
        )
        _telemetry.counter("resilience.rollbacks").inc()
        return snapshot_state(self._last_good), self._last_good_step
