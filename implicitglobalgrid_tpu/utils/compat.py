"""JAX version compatibility shims.

The framework targets the current public API (`jax.shard_map` with
``check_vma``); older installs ship the same machinery as
`jax.experimental.shard_map.shard_map` with the flag named ``check_rep``.
Routing every call through this one adapter keeps a JAX up/downgrade a
one-line concern instead of a scattered AttributeError hunt — the same
degrade-to-a-clear-error contract `parallel.distributed.is_distributed_initialized`
follows.
"""

from __future__ import annotations

import contextlib

#: Module flag behind `pallas_force_interpret` on JAX versions without the
#: TPU interpreter (`pltpu.force_tpu_interpret_mode`): the repo's kernels
#: read it (via `pallas_interpret_active`) and pass ``interpret=True`` to
#: `pallas_call`, routing through the generic Pallas interpreter instead.
_pallas_interpret = False


def pallas_compiler_params(**kwargs):
    """`pltpu.CompilerParams` across JAX versions (older: `TPUCompilerParams`).

    Both spell the same Mosaic knobs (``vmem_limit_bytes`` et al.); only the
    class name moved.
    """
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


@contextlib.contextmanager
def pallas_force_interpret():
    """Run Pallas TPU kernels in interpret mode, across JAX versions.

    Newer JAX: delegates to ``pltpu.force_tpu_interpret_mode()`` (the
    TPU-semantics interpreter).  Older JAX (no such API): flips a module
    flag that the repo's kernel builders consult to pass ``interpret=True``
    to `pallas_call` — the generic interpreter, which executes this repo's
    DMA/`run_scoped` kernel style correctly (validated against the XLA
    cadences by the kernel test suites).  Note the flag is part of each
    builder's cache key, so interpret and compiled executables never mix.
    """
    from jax.experimental.pallas import tpu as pltpu

    global _pallas_interpret
    force = getattr(pltpu, "force_tpu_interpret_mode", None)
    if force is not None:
        with force():
            yield
        return
    prev = _pallas_interpret
    _pallas_interpret = True
    try:
        yield
    finally:
        _pallas_interpret = prev


def pallas_interpret_active() -> bool:
    """Whether `pallas_force_interpret`'s flag-based fallback is active."""
    return _pallas_interpret


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across JAX versions.

    ``check_vma`` maps onto the older ``check_rep`` — both flags gate the
    same replication/varying-axes verification pass.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError as e:  # pragma: no cover - no known JAX hits this
        raise RuntimeError(
            "This JAX version exposes neither jax.shard_map nor "
            "jax.experimental.shard_map; implicitglobalgrid_tpu requires one "
            "of the two (jax >= 0.4.30 or newer)."
        ) from e
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )
