"""JAX version compatibility shims.

The framework targets the current public API (`jax.shard_map` with
``check_vma``); older installs ship the same machinery as
`jax.experimental.shard_map.shard_map` with the flag named ``check_rep``.
Routing every call through this one adapter keeps a JAX up/downgrade a
one-line concern instead of a scattered AttributeError hunt — the same
degrade-to-a-clear-error contract `parallel.distributed.is_distributed_initialized`
follows.
"""

from __future__ import annotations

import contextlib

#: Module flag behind `pallas_force_interpret` on JAX versions without the
#: TPU interpreter (`pltpu.force_tpu_interpret_mode`): the repo's kernels
#: read it (via `pallas_interpret_active`) and pass ``interpret=True`` to
#: `pallas_call`, routing through the generic Pallas interpreter instead.
_pallas_interpret = False


def pallas_compiler_params(**kwargs):
    """`pltpu.CompilerParams` across JAX versions (older: `TPUCompilerParams`).

    Both spell the same Mosaic knobs (``vmem_limit_bytes`` et al.); only the
    class name moved.
    """
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


@contextlib.contextmanager
def pallas_force_interpret():
    """Run Pallas TPU kernels in interpret mode, across JAX versions.

    Newer JAX: delegates to ``pltpu.force_tpu_interpret_mode()`` (the
    TPU-semantics interpreter).  Older JAX (no such API): flips a module
    flag that the repo's kernel builders consult to pass ``interpret=True``
    to `pallas_call` — the generic interpreter, which executes this repo's
    DMA/`run_scoped` kernel style correctly (validated against the XLA
    cadences by the kernel test suites).  Note the flag is part of each
    builder's cache key, so interpret and compiled executables never mix.
    """
    from jax.experimental.pallas import tpu as pltpu

    global _pallas_interpret
    force = getattr(pltpu, "force_tpu_interpret_mode", None)
    if force is not None:
        with force():
            yield
        return
    prev = _pallas_interpret
    _pallas_interpret = True
    try:
        yield
    finally:
        _pallas_interpret = prev


def pallas_interpret_active() -> bool:
    """Whether `pallas_force_interpret`'s flag-based fallback is active."""
    return _pallas_interpret


def named_scope(name: str):
    """`jax.named_scope` across JAX versions (no-op where absent).

    The device-side profiler annotation: names entered here land in the
    XLA op metadata (``metadata={op_name="...igg_ring_pass..."}``) of every
    op traced inside the scope, so a `profile_trace` capture shows the
    pipelined ring/interior/exchange phases BY NAME in Perfetto — and the
    compiled HLO text carries them too, which is what the toolchain-
    independent test asserts (`tests/test_telemetry.py`).
    """
    import jax

    ns = getattr(jax, "named_scope", None)
    if ns is None:  # pragma: no cover - every supported JAX ships it
        return contextlib.nullcontext()
    return ns(name)


def trace_annotation(name: str):
    """`jax.profiler.TraceAnnotation` across JAX versions (no-op fallback).

    The HOST-side profiler annotation: names the enclosing wall-clock span
    on the Python-thread track of a `profile_trace` capture (dispatch,
    guard probes, checkpoint I/O).  Complements `named_scope`, which names
    the *device* ops.
    """
    try:
        import jax

        cls = getattr(jax.profiler, "TraceAnnotation", None)
        if cls is not None:
            return cls(name)
    except Exception:  # pragma: no cover - profiler machinery absent
        pass
    return contextlib.nullcontext()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across JAX versions.

    ``check_vma`` maps onto the older ``check_rep`` — both flags gate the
    same replication/varying-axes verification pass.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError as e:  # pragma: no cover - no known JAX hits this
        raise RuntimeError(
            "This JAX version exposes neither jax.shard_map nor "
            "jax.experimental.shard_map; implicitglobalgrid_tpu requires one "
            "of the two (jax >= 0.4.30 or newer)."
        ) from e
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )
