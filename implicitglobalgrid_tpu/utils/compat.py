"""JAX version compatibility shims.

The framework targets the current public API (`jax.shard_map` with
``check_vma``); older installs ship the same machinery as
`jax.experimental.shard_map.shard_map` with the flag named ``check_rep``.
Routing every call through this one adapter keeps a JAX up/downgrade a
one-line concern instead of a scattered AttributeError hunt — the same
degrade-to-a-clear-error contract `parallel.distributed.is_distributed_initialized`
follows.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across JAX versions.

    ``check_vma`` maps onto the older ``check_rep`` — both flags gate the
    same replication/varying-axes verification pass.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError as e:  # pragma: no cover - no known JAX hits this
        raise RuntimeError(
            "This JAX version exposes neither jax.shard_map nor "
            "jax.experimental.shard_map; implicitglobalgrid_tpu requires one "
            "of the two (jax >= 0.4.30 or newer)."
        ) from e
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )
