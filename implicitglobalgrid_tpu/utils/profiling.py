"""Device-timeline profiling plane: windowed capture, per-scope attribution,
measured comm/compute overlap, cross-run drift diffing.

Three observability PRs built the HOST side of the story — telemetry
(PR 4), host spans + the merged cross-rank timeline (PR 9), the live plane
(PR 10) — but the repo's performance claims are about the DEVICE timeline:
ROADMAP item 1 wants the exchange provably concurrent with the interior
pass, and VERDICT r5 names a third of the diffusion headline lost between
the 976 GB/s kernel and the 659.5 GB/s cadence — "cadence glue" only
per-op device-time attribution can localize.  This module cashes in the
correlate-BY-NAME contract `utils.tracing` set up: host spans reuse the
compiled ``named_scope`` names (``igg_ring_pass``/``igg_interior_pass``/
``igg_halo_exchange``/``igg_slab_exchange_*``), so a parsed profiler
capture attributes device time to the same namespace the host timeline
already speaks (docs/observability.md "Device timeline").

* **Capture** — ``IGG_PROFILE=steps:A-B`` arms a `jax.profiler` capture
  around time-loop steps A..B of the next instrumented run
  (`ProfileCapture`, constructed by the step pipeline the way the live
  plane's server is: `maybe_arm` from `telemetry._StepLoop`).  Output is
  per-rank (``profile.p<rank>/`` under ``IGG_PROFILE_DIR`` /
  ``IGG_TELEMETRY_DIR``) with ``create_perfetto_trace=True`` so a
  parseable ``*.trace.json.gz`` lands next to the xplane protobuf.  The
  capture meta file ``profile.p<rank>.json`` (window, host perf anchors,
  trace path, attribution) is the discovery surface for the merge and the
  CLI.  Every failure mode — no profiler in the toolchain, no directory,
  a start/stop error, an unparseable trace — degrades to ONE structured
  ``profile.capture_failed`` event, never a crash; a window left open at
  scope exit (guard trip, injected crash) is stopped by
  `resilience.guarded_time_loop`'s exit path so the bytes already
  captured still land.
* **Attribution** — `attribute_trace` parses the Chrome/Perfetto JSON,
  keeps the DEVICE ops (events carrying XLA's ``args.hlo_op``), and
  attributes their time to the ``named_scope`` namespace where the op
  name carries one, else to the blessed
  `utils.hlo_analysis.classify_op_name` buckets: ``collectives`` (fabric
  traffic), ``kernels`` (fusions / custom-calls — the Pallas launches),
  ``glue`` (copies, slices, control flow — the unattributed cadence
  overhead).  The **measured overlap fraction** is wall-clock
  union-intersection per device track: |union(collective intervals) ∩
  union(kernel intervals)| / |union(collective intervals)| — the number
  ROADMAP item 1's acceptance needs, honest bounds in
  docs/observability.md.
* **Join** — `attach_device_tracks` adds per-rank device tracks to the
  PR-9 merged host timeline (``scripts/igg_trace.py merge --device``):
  device events ride the owning rank's pid on dedicated device tids,
  anchored at the host ``start_trace`` instant (the capture meta's perf
  sample), and the output still passes `tracing.validate_chrome_trace`.
* **Feed out** — `publish_attribution` lands
  ``profile.scope_seconds.<name>`` / ``profile.overlap_fraction`` gauges;
  ``bench.py`` records ``extras.profile_attribution`` with the overlap
  fraction as a REPORTED perf-gate key (`analysis.perf`);
  ``scripts/igg_prof.py diff A B`` names the scope a cross-run regression
  ate its time in.

Layering: module scope imports only stdlib + `config`/`telemetry`/
`hlo_analysis`; jax is reached lazily inside start/stop so the parser and
diff tooling work in a jax-less (or broken-accelerator) environment.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time
from typing import Any, Sequence

from . import config as _config
from . import hlo_analysis as _hlo
from . import telemetry as _telemetry

__all__ = [
    "SCOPE_NAMES",
    "parse_profile_window",
    "ProfileCapture",
    "maybe_arm",
    "active_capture",
    "close_open_capture",
    "profile_trace",
    "profile_meta_filename",
    "load_trace",
    "find_trace_files",
    "device_ops",
    "attribute_trace",
    "attribute_capture",
    "attribution_delta",
    "render_attribution_table",
    "render_delta_table",
    "publish_attribution",
    "attach_device_tracks",
    "find_capture_metas",
    "resolve_trace_path",
    "reset",
]

#: capture meta / attribution record schema version
PROFILE_SCHEMA = 1

#: the compiled named_scope namespace (docs/observability.md): device ops
#: whose qualified name carries one of these attribute to it directly —
#: the same names the host spans use, which is what lets the merged
#: timeline line both sides up.  Ordered begin/finish before the bare
#: exchange so the most specific name wins a substring match.
SCOPE_NAMES = (
    "igg_ring_pass",
    "igg_interior_pass",
    "igg_slab_exchange_begin",
    "igg_slab_exchange_finish",
    "igg_halo_exchange",
)

#: fallback buckets for device ops outside any named scope (the
#: `hlo_analysis.classify_op_name` vocabulary; "glue" is the unattributed
#: cadence overhead the attribution exists to localize)
FALLBACK_BUCKETS = ("collectives", "kernels", "glue")


def profile_meta_filename(rank: int) -> str:
    return f"profile.p{rank}.json"


# -- window spec --------------------------------------------------------------


def parse_profile_window(spec: str) -> tuple[int, int]:
    """``IGG_PROFILE`` grammar -> ``(start_step, stop_step)``, 1-based
    inclusive.

    ``steps:A-B`` captures time-loop steps A..B; ``steps:N`` is shorthand
    for ``steps:1-N``.  Error messages follow the config contract (name
    the variable, the accepted format and the obtained value).
    """
    err = ValueError(
        f"Environment variable IGG_PROFILE must be 'steps:A-B' or "
        f"'steps:N' (1-based inclusive time-loop steps, e.g. "
        f"'steps:20-40'), got {spec!r}."
    )
    head, sep, rng = spec.partition(":")
    if head != "steps" or not sep or not rng:
        raise err
    lo, dash, hi = rng.partition("-")
    try:
        a = int(lo)
        b = int(hi) if dash else a
        if not dash:
            a = 1
    except ValueError:
        raise err from None
    if a < 1 or b < a:
        raise err
    return a, b


# -- capture ------------------------------------------------------------------


class ProfileCapture:
    """One armed windowed device capture for this process's current run.

    Driven by the step pipeline (`telemetry._StepLoop`): `on_step(it)` is
    called after every completed step and starts/stops the profiler at the
    window edges.  All device interaction is guarded — any failure emits a
    structured ``profile.capture_failed`` event and disarms the capture;
    the run never pays more than the event.
    """

    def __init__(self, window: tuple[int, int], *, logdir: str | None = None,
                 rank: int | None = None):
        self.window = (int(window[0]), int(window[1]))
        self.rank = _telemetry._proc_index() if rank is None else rank
        if logdir is None:
            base = _config.profile_dir_env() or _config.telemetry_dir_env()
            logdir = (
                os.path.join(base, f"profile.p{self.rank}") if base else None
            )
        self.logdir = logdir
        self.started = False
        self.done = False
        self.started_at_step: int | None = None
        self.last_step: int | None = None
        self.t_start_perf: float | None = None
        self.wall_start: float | None = None
        self.meta_path: str | None = None

    # - lifecycle -

    def _fail(self, stage: str, error: Exception | str) -> None:
        self.done = True
        if self.started:
            # best-effort teardown so a later capture can start
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self.started = False
        _telemetry.event(
            "profile.capture_failed",
            stage=stage,
            window=list(self.window),
            logdir=self.logdir,
            error=str(error),
        )
        _telemetry.counter("profile.capture_failures").inc()

    def _start(self, step: int) -> None:
        if self.logdir is None:
            self._fail(
                "start",
                "no capture directory (set IGG_PROFILE_DIR or "
                "IGG_TELEMETRY_DIR)",
            )
            return
        try:
            import jax

            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(
                self.logdir, create_perfetto_trace=True
            )
        except Exception as e:
            self._fail("start", e)
            return
        # anchor AFTER start returns: the profiler is live from here, so
        # this perf sample is the instant the device track aligns to.
        self.t_start_perf = time.perf_counter()
        self.wall_start = time.time()
        self.started = True
        self.started_at_step = step
        _telemetry.event(
            "profile.start",
            step=step,
            window=list(self.window),
            logdir=self.logdir,
        )

    def _stop(self, step: int, reason: str) -> None:
        self.done = True
        if not self.started:
            return
        self.started = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            self._fail("stop", e)
            return
        t_stop_perf = time.perf_counter()
        meta: dict[str, Any] = {
            "schema": PROFILE_SCHEMA,
            "rank": self.rank,
            "pid": os.getpid(),
            "window": list(self.window),
            "started_at_step": self.started_at_step,
            "stopped_at_step": step,
            "reason": reason,
            "logdir": self.logdir,
            "t_start_perf": self.t_start_perf,
            "t_stop_perf": t_stop_perf,
            "wall_start": self.wall_start,
            "wall_stop": time.time(),
        }
        traces = find_trace_files(self.logdir)
        if not traces:
            meta["trace_path"] = None
            meta["attribution"] = {
                "error": "no *.trace.json.gz emitted (toolchain without "
                "the Chrome-trace exporter?)"
            }
            self._write_meta(meta)
            self._fail("locate", meta["attribution"]["error"])
            return
        meta["trace_path"] = traces[-1]
        try:
            attribution = attribute_trace(traces[-1])
        except (OSError, ValueError) as e:
            attribution = {"error": f"{type(e).__name__}: {e}"}
            _telemetry.event(
                "profile.capture_failed",
                stage="attribute",
                window=list(self.window),
                error=attribution["error"],
            )
            _telemetry.counter("profile.capture_failures").inc()
        meta["attribution"] = attribution
        if "error" not in attribution:
            publish_attribution(attribution)
        self._write_meta(meta)
        _telemetry.counter("profile.captures").inc()
        _telemetry.event(
            "profile.stop",
            step=step,
            window=list(self.window),
            reason=reason,
            trace=meta["trace_path"],
            meta=self.meta_path,
            overlap_fraction=(
                attribution.get("overlap", {}).get("fraction")
                if "error" not in attribution
                else None
            ),
        )

    def _write_meta(self, meta: dict) -> None:
        # The meta lands where `find_capture_metas` looks: the telemetry
        # dir, else the capture BASE dir (logdir's parent — logdir itself
        # is the per-rank profile.p<rank>/ subdir, where a non-recursive
        # glob would never see it).
        directory = _config.telemetry_dir_env() or (
            os.path.dirname(self.logdir) if self.logdir else None
        )
        if not directory:
            return
        try:
            self.meta_path = _telemetry.atomic_write_json(
                os.path.join(directory, profile_meta_filename(self.rank)),
                meta,
                indent=1,
            )
        except OSError:
            self.meta_path = None

    # - step pipeline hooks -

    def on_run_start(self, start_step: int) -> None:
        """Arm-time hook: a window already entered at resume (checkpointed
        runs) starts immediately — step ``start_step + 1`` is next."""
        a, b = self.window
        if not self.done and a <= start_step + 1 <= b:
            self._start(start_step + 1)

    def on_step(self, it: int) -> None:
        """Post-step hook from the instrumented loop (step ``it`` done)."""
        self.last_step = it
        if self.done:
            return
        a, b = self.window
        if self.started:
            if it >= b:
                self._stop(it, "window")
        elif it + 1 >= a and it + 1 <= b:
            self._start(it + 1)
        elif it + 1 > b:
            self.done = True  # window passed before the run reached it

    def close(self, reason: str) -> None:
        """Scope-exit stop (`resilience.guarded_time_loop`'s finally path
        and `_StepLoop.finish`): a window still open when the run ends —
        normally or through a guard trip — stops cleanly so the captured
        bytes land.  The recorded stop step is the LAST completed step the
        pipeline reported (falling back to the start step when the window
        opened and the run died before any step finished)."""
        if self.started and not self.done:
            step = (
                self.last_step
                if self.last_step is not None
                else (self.started_at_step or 0)
            )
            self._stop(step, reason)
        else:
            self.done = True

    def info(self) -> dict:
        """The in-flight description a flight-recorder bundle wants."""
        return {
            "window": list(self.window),
            "logdir": self.logdir,
            "started": self.started,
            "started_at_step": self.started_at_step,
            "done": self.done,
        }


_active: ProfileCapture | None = None


def maybe_arm(start_step: int = 0) -> ProfileCapture | None:
    """Arm a windowed capture for this run when ``IGG_PROFILE`` says so.

    Called from the step pipeline (`telemetry._StepLoop.__init__`, the
    live-plane `ensure_server` slot).  Returns None when the knob is unset
    or telemetry is off (the zero-overhead contract: the loop then pays
    one ``is not None`` check per step).  An invalid spec raises — the
    config-tier error contract, same as every other malformed ``IGG_*``.
    """
    global _active
    spec = _config.profile_env()
    if not spec or not _telemetry.enabled():
        return None
    if _active is not None:
        # Fire-once per process (the documented "next instrumented run"
        # contract): a process running several instrumented loops —
        # bench.py runs three models back to back — must not pay a
        # profiler session per run and overwrite the first capture's
        # artifacts with whichever run happened last.  `reset()` re-arms.
        return None
    window = parse_profile_window(spec)
    cap = ProfileCapture(window)
    _active = cap
    cap.on_run_start(start_step)
    return cap


def active_capture() -> dict | None:
    """The open capture window's description, or None — what
    `tracing.dump_flight_recorder` bundles so a crash mid-capture is
    explained (docs/observability.md)."""
    if _active is not None and _active.started and not _active.done:
        return _active.info()
    return None


def close_open_capture(reason: str = "scope_exit") -> None:
    """Stop any open window (the resilience scope-exit path).  Idempotent
    and never raises — it runs inside ``finally`` blocks."""
    global _active
    try:
        if _active is not None:
            _active.close(reason)
    except Exception:
        pass


def reset() -> None:
    """Drop the armed capture (test hook)."""
    global _active
    _active = None


@contextlib.contextmanager
def profile_trace(logdir, **kwargs):
    """Record a `jax.profiler` trace of the enclosed block (the ONE manual
    capture implementation; ``igg.profile_trace`` is a thin alias).

    ``create_perfetto_trace`` defaults to True so the capture always emits
    the parseable ``*.trace.json.gz`` the attribution pipeline reads::

        with igg.profile_trace("/tmp/igg-trace"):
            for _ in range(20):
                state = step(*state)
        rec = profiling.attribute_capture("/tmp/igg-trace")

    Prefer the windowed env-armed capture (``IGG_PROFILE=steps:A-B``) for
    instrumented runs — it needs no code changes and lands the per-rank
    meta file the merge/CLI tooling discovers.
    """
    import jax

    kwargs.setdefault("create_perfetto_trace", True)
    with jax.profiler.trace(str(logdir), **kwargs):
        yield


# -- trace parsing ------------------------------------------------------------


def load_trace(path: str | os.PathLike) -> dict:
    """One Chrome-trace JSON document from ``path`` (gzip by suffix).

    Raises ValueError on malformed/truncated input — callers turn that
    into a structured finding (`attribute_trace` callers, the CLI), never
    a traceback shown to an operator.
    """
    path = os.fspath(path)
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, EOFError) as e:
        # gzip truncation surfaces as EOFError/OSError mid-read
        raise ValueError(f"{path}: unreadable trace ({e})") from e
    except ValueError as e:
        raise ValueError(f"{path}: malformed trace JSON ({e})") from e
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        raise ValueError(
            f"{path}: not a Chrome trace (no traceEvents list)."
        )
    return doc


def find_trace_files(logdir: str | os.PathLike) -> list[str]:
    """The ``*.trace.json.gz`` files under a profiler log dir, oldest
    first (the exporter nests them under ``plugins/profile/<run>/``; the
    ``perfetto_trace.json.gz`` sibling is protobuf-oriented and skipped by
    the suffix match)."""
    logdir = os.fspath(logdir)
    hits = glob.glob(
        os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True
    )
    hits += glob.glob(os.path.join(logdir, "*.trace.json"))
    return sorted(set(hits), key=lambda p: (os.path.getmtime(p), p))


def device_ops(doc: dict) -> list[dict]:
    """The device-op events of a capture: complete (``X``) events carrying
    XLA's ``args.hlo_op`` — runtime/python/annotation events don't, which
    is exactly the filter (host time is the span ring's job).  Returns
    ``{name, hlo_op, hlo_module, pid, tid, ts, dur}`` dicts (µs)."""
    out = []
    for e in doc.get("traceEvents", []):
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        hlo_op = args.get("hlo_op")
        if not hlo_op:
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if not isinstance(ts, (int, float)) or not isinstance(
            dur, (int, float)
        ):
            continue
        out.append(
            {
                "name": e.get("name", hlo_op),
                "hlo_op": hlo_op,
                "hlo_module": args.get("hlo_module"),
                "pid": e.get("pid", 0),
                "tid": e.get("tid", 0),
                "ts": float(ts),
                "dur": float(dur),
            }
        )
    return out


def scope_of(op: dict) -> str:
    """Attribution bucket of one device op: a `SCOPE_NAMES` member when the
    qualified op name carries one (TPU captures put the ``named_scope``
    path in the op name), else the `hlo_analysis.classify_op_name` bucket
    (``collectives`` / ``kernels`` / ``glue``)."""
    name = op["name"]
    for scope in SCOPE_NAMES:
        if scope in name:
            return scope
    kind = _hlo.classify_op_name(op["hlo_op"] or name)
    return {"collective": "collectives", "kernel": "kernels"}.get(
        kind, "glue"
    )


def op_kind(op: dict) -> str:
    """``collective`` | ``kernel`` | ``glue`` of one device op (by the
    blessed name vocabulary — scope membership does not change what the op
    IS; a collective inside ``igg_slab_exchange_begin`` still counts as
    comm time in the overlap measure)."""
    return _hlo.classify_op_name(op["hlo_op"] or op["name"])


# -- interval arithmetic (overlap measure) ------------------------------------


def _union(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for a, b in intervals[1:]:
        if a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _intersection_seconds(u1, u2) -> float:
    total, i, j = 0.0, 0, 0
    while i < len(u1) and j < len(u2):
        a = max(u1[i][0], u2[j][0])
        b = min(u1[i][1], u2[j][1])
        if b > a:
            total += b - a
        if u1[i][1] < u2[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_measure(ops: Sequence[dict]) -> dict:
    """The measured comm/compute overlap of one capture's device ops.

    Per device track (pid): union the collective-op intervals and the
    kernel-op intervals, intersect the two unions, sum across tracks.
    ``fraction = overlapped / comm`` — the share of fabric time hidden
    under compute, the number ROADMAP item 1's acceptance gates on.  None
    when the capture holds no collectives (single-device runs) — absence
    is meaningful, never 0.0 (docs/observability.md honesty bounds).
    """
    by_pid: dict[Any, dict[str, list]] = {}
    for op in ops:
        kind = op_kind(op)
        if kind == "glue":
            continue
        iv = (op["ts"], op["ts"] + op["dur"])
        by_pid.setdefault(op["pid"], {"collective": [], "kernel": []})[
            kind
        ].append(iv)
    comm = compute = overlapped = 0.0
    for tracks in by_pid.values():
        u_comm = _union(tracks["collective"])
        u_kern = _union(tracks["kernel"])
        comm += sum(b - a for a, b in u_comm)
        compute += sum(b - a for a, b in u_kern)
        overlapped += _intersection_seconds(u_comm, u_kern)
    return {
        "comm_seconds": comm * 1e-6,
        "compute_seconds": compute * 1e-6,
        "overlapped_seconds": overlapped * 1e-6,
        "fraction": round(overlapped / comm, 6) if comm > 0 else None,
    }


# -- attribution --------------------------------------------------------------


def attribute_ops(ops: Sequence[dict]) -> dict:
    """Per-scope device-time attribution over parsed device ops."""
    scope_s: dict[str, float] = {}
    for op in ops:
        scope = scope_of(op)
        scope_s[scope] = scope_s.get(scope, 0.0) + op["dur"]
    scope_seconds = {
        name: round(us * 1e-6, 9) for name, us in sorted(scope_s.items())
    }
    total = round(sum(op["dur"] for op in ops) * 1e-6, 9)
    return {
        "schema": PROFILE_SCHEMA,
        "n_device_ops": len(ops),
        "device_seconds": total,
        "scope_seconds": scope_seconds,
        "unattributed_seconds": scope_seconds.get("glue", 0.0),
        "overlap": overlap_measure(ops),
    }


def attribute_trace(trace: str | os.PathLike | dict) -> dict:
    """Full attribution record of one capture (path or loaded doc).

    Raises ValueError on malformed input (callers degrade to a structured
    finding); a VALID trace with zero device ops returns a record saying
    so (``n_device_ops: 0``) rather than failing — a host-only capture is
    an answer, not an error.
    """
    doc = trace if isinstance(trace, dict) else load_trace(trace)
    rec = attribute_ops(device_ops(doc))
    if not isinstance(trace, dict):
        rec["trace"] = os.fspath(trace)
    return rec


def attribute_capture(logdir: str | os.PathLike) -> dict:
    """Attribute the newest trace under a profiler log dir."""
    traces = find_trace_files(logdir)
    if not traces:
        raise ValueError(
            f"{os.fspath(logdir)}: no *.trace.json.gz capture found "
            f"(run with IGG_PROFILE / profile_trace first)."
        )
    return attribute_trace(traces[-1])


def publish_attribution(rec: dict) -> None:
    """Land an attribution record on the metrics registry:
    ``profile.scope_seconds.<scope>`` gauges plus
    ``profile.overlap_fraction`` (set only when measured — a gauge of
    None would fake a number)."""
    for scope, seconds in rec.get("scope_seconds", {}).items():
        _telemetry.gauge(f"profile.scope_seconds.{scope}").set(seconds)
    frac = rec.get("overlap", {}).get("fraction")
    if frac is not None:
        _telemetry.gauge("profile.overlap_fraction").set(frac)


# -- cross-run diffing --------------------------------------------------------


def attribution_delta(a: dict, b: dict) -> dict:
    """Attribute the drift between two attribution records (run A -> B).

    Per scope: seconds in each run and the delta (positive = B spends
    MORE); ``worst`` names the scope that grew the most — where a
    regression went.  The overlap fractions ride along so "the exchange
    stopped hiding" is visible next to "interior got slower".
    """
    scopes = sorted(
        set(a.get("scope_seconds", {})) | set(b.get("scope_seconds", {}))
    )
    table = {}
    worst, worst_delta = None, 0.0
    for s in scopes:
        sa = float(a.get("scope_seconds", {}).get(s, 0.0))
        sb = float(b.get("scope_seconds", {}).get(s, 0.0))
        delta = round(sb - sa, 9)
        table[s] = {"a_s": sa, "b_s": sb, "delta_s": delta}
        if delta > worst_delta:
            worst, worst_delta = s, delta
    return {
        "schema": PROFILE_SCHEMA,
        "scopes": table,
        "device_seconds": {
            "a": a.get("device_seconds"),
            "b": b.get("device_seconds"),
        },
        "overlap_fraction": {
            "a": a.get("overlap", {}).get("fraction"),
            "b": b.get("overlap", {}).get("fraction"),
        },
        "worst": worst,
        "worst_delta_s": round(worst_delta, 9),
    }


def render_attribution_table(rec: dict) -> str:
    """Fixed-width per-scope table (golden-pinned by
    tests/test_profiling.py: change the format deliberately and update the
    golden)."""
    head = f"{'scope':<28} {'device_ms':>12} {'share':>7}"
    lines = [head, "-" * len(head)]
    total = rec.get("device_seconds") or 0.0
    for name, sec in rec.get("scope_seconds", {}).items():
        share = (sec / total) if total else 0.0
        lines.append(f"{name:<28} {sec * 1e3:>12.3f} {share:>6.1%}")
    lines.append("-" * len(head))
    lines.append(
        f"{'total':<28} {total * 1e3:>12.3f} {'':>7} "
        f"({rec.get('n_device_ops', 0)} device op(s))"
    )
    ov = rec.get("overlap", {})
    frac = ov.get("fraction")
    lines.append(
        "overlap: comm "
        f"{(ov.get('comm_seconds') or 0.0) * 1e3:.3f} ms, compute "
        f"{(ov.get('compute_seconds') or 0.0) * 1e3:.3f} ms, overlapped "
        f"{(ov.get('overlapped_seconds') or 0.0) * 1e3:.3f} ms -> fraction "
        + (f"{frac:.4f}" if frac is not None else "n/a (no collectives)")
    )
    return "\n".join(lines)


def render_delta_table(delta: dict) -> str:
    """Fixed-width cross-run drift table (``igg_prof.py diff``)."""
    head = f"{'scope':<28} {'A_ms':>10} {'B_ms':>10} {'delta_ms':>10}"
    lines = [head, "-" * len(head)]
    for name, row in delta.get("scopes", {}).items():
        lines.append(
            f"{name:<28} {row['a_s'] * 1e3:>10.3f} "
            f"{row['b_s'] * 1e3:>10.3f} {row['delta_s'] * 1e3:>+10.3f}"
        )
    ov = delta.get("overlap_fraction", {})

    def _f(v):
        return f"{v:.4f}" if isinstance(v, (int, float)) else "n/a"

    lines.append(
        f"overlap fraction: A {_f(ov.get('a'))} -> B {_f(ov.get('b'))}"
    )
    if delta.get("worst"):
        lines.append(
            f"worst regression: {delta['worst']} "
            f"(+{delta['worst_delta_s'] * 1e3:.3f} ms)"
        )
    return "\n".join(lines)


# -- merged-timeline join (igg_trace.py merge --device) -----------------------


def find_capture_metas(directory: str | os.PathLike) -> list[str]:
    """The per-rank capture meta files (``profile.p<rank>.json``) in a
    telemetry/run directory."""
    return sorted(
        glob.glob(os.path.join(os.fspath(directory), "profile.p*.json"))
    )


def resolve_trace_path(meta: dict, meta_dir: str | None = None) -> str | None:
    """The capture's trace file, surviving archived/copied run dirs.

    The meta records ``trace_path``/``logdir`` as ABSOLUTE paths from
    capture time; a run directory copied off the original machine (the
    diff tool's cross-round use) still holds the trace under its own
    ``profile.p<rank>/`` — so resolution falls back from the recorded
    absolute path to the meta's own directory before giving up (None).
    """
    path = meta.get("trace_path")
    if path and os.path.isfile(path):
        return path
    if meta_dir is not None and meta.get("rank") is not None:
        traces = find_trace_files(
            os.path.join(os.fspath(meta_dir), f"profile.p{meta['rank']}")
        )
        if traces:
            return traces[-1]
    if meta.get("logdir"):
        traces = find_trace_files(meta["logdir"])
        if traces:
            return traces[-1]
    return None


#: tid base for attached device tracks (host spans sit on tid 0; a large
#: offset keeps original device-thread identity visible as tid - base)
DEVICE_TID_BASE = 10_000

#: max seconds a capture meta's wall_start may PREDATE the merged
#: timeline's per-rank clock-sync anchor before `attach_device_tracks`
#: refuses it as stale (same spirit as `tracing.BARRIER_WALL_TOL_S`: a
#: capture happens during the run, after the sync barrier — anything
#: earlier is a previous run's leftover in a reused telemetry dir).
STALE_META_TOL_S = 2.0


def attach_device_tracks(
    doc: dict, metas: Sequence[str | os.PathLike | dict]
) -> dict:
    """Add per-rank device tracks to a merged host timeline (in place).

    ``doc`` is `tracing.merge_trace_files` output; ``metas`` are capture
    meta files (or loaded dicts) from the same run's ranks.  Each rank's
    device ops land on ITS host track's pid (new ``DEVICE_TID_BASE + k``
    tids, one per original device thread), aligned by anchoring the
    capture's first device-op timestamp at the host ``start_trace``
    instant (the meta's ``t_start_perf`` sample) and riding the host
    track's barrier offset.  The honesty bound: that anchor is accurate to
    the profiler's start latency (ms-scale) — recorded per rank in
    ``otherData.device_alignment``, never silently claimed tighter.  The
    result still passes `tracing.validate_chrome_trace`.
    """
    alignment = doc.get("otherData", {}).get("clock_alignment")
    if alignment is None:
        raise ValueError(
            "attach_device_tracks needs merge_trace_files output "
            "(otherData.clock_alignment missing)."
        )
    base_us = float(alignment.get("ts_zero_offset_s", 0.0)) * 1e6
    dev_align: dict[str, Any] = {
        "note": (
            "device tracks are aligned by anchoring each rank's first "
            "captured device op at its host start_trace instant "
            "(profile.p<rank>.json t_start_perf); the anchor error is the "
            "profiler start latency — ms-scale — ON TOP of the host "
            "clock_alignment uncertainty, so cross-track ordering finer "
            "than that is not trustworthy."
        ),
        "per_rank": {},
    }
    events = doc["traceEvents"]
    # Phase 1 — validate EVERY meta before touching the doc, so a raising
    # check (schema drift, the stale-file refusal) can never leave the
    # caller holding a partially mutated timeline.
    plans: list[tuple[dict, dict, str | None, list]] = []
    for meta_in in metas:
        if isinstance(meta_in, dict):
            meta, meta_dir = meta_in, None
        else:
            with open(os.fspath(meta_in), encoding="utf-8") as f:
                meta = json.load(f)
            meta_dir = os.path.dirname(os.path.abspath(os.fspath(meta_in)))
        if meta.get("schema") != PROFILE_SCHEMA:
            raise ValueError(
                f"capture meta {meta_in}: unsupported schema "
                f"{meta.get('schema')!r} (expected {PROFILE_SCHEMA})."
            )
        rank = meta["rank"]
        per = alignment.get("per_rank", {}).get(str(rank))
        if per is None:
            # A crashed rank can leave a capture meta with no host dump
            # (the meta publishes at window close, the trace.p<rank>.json
            # at dump_trace) — exactly the post-mortem this plane serves,
            # so degrade per rank instead of refusing the whole merge.
            dev_align["per_rank"][str(rank)] = {
                "trace": None,
                "n_ops": 0,
                "note": (
                    "no host track for this rank in the merged trace "
                    "(crashed before dump_trace?) — device ops omitted"
                ),
            }
            continue
        # Staleness guard — the device twin of merge_trace_files' same-
        # barrier refusal: a capture happens DURING the run, so its wall
        # clock cannot predate this rank's clock-sync anchor.  A
        # profile.p<rank>.json left in a reused telemetry dir by a
        # PREVIOUS run is exactly that shape, and joining it would anchor
        # dead-process perf samples onto the live timeline (then the
        # re-base below would silently shift every host span too).
        sync_wall = per.get("wall_at_sync_unix_s")
        wall_start = meta.get("wall_start")
        if (
            sync_wall is not None
            and wall_start is not None
            and wall_start < sync_wall - STALE_META_TOL_S
        ):
            raise ValueError(
                f"capture meta for rank {rank} predates the merged "
                f"timeline's clock sync by "
                f"{sync_wall - wall_start:.1f}s — a stale "
                f"profile.p{rank}.json from a previous run in a reused "
                f"telemetry dir looks exactly like this: delete it, or "
                f"re-run the capture alongside the current trace dumps."
            )
        trace_path = resolve_trace_path(meta, meta_dir)
        ops = (
            device_ops(load_trace(trace_path))
            if trace_path and meta.get("t_start_perf") is not None
            else []  # load_trace raising here is still pre-mutation
        )
        plans.append((meta, per, trace_path, ops))
    # Phase 2 — attach the validated ranks' device tracks.
    for meta, per, trace_path, ops in plans:
        rank = meta["rank"]
        entry: dict[str, Any] = {"trace": trace_path, "n_ops": 0}
        dev_align["per_rank"][str(rank)] = entry
        if not trace_path or meta.get("t_start_perf") is None:
            entry["note"] = "no device trace captured"
            continue
        if not ops:
            entry["note"] = "capture holds no device ops"
            continue
        t0 = min(op["ts"] for op in ops)
        # host merged-timeline µs of device ts: the capture-start perf
        # instant, through this rank's host offset, minus the merge's
        # zero re-base.
        anchor_us = (
            (meta["t_start_perf"] + per["offset_s"]) * 1e6 - base_us
        )
        tids = sorted({op["tid"] for op in ops})
        tid_map = {t: DEVICE_TID_BASE + i for i, t in enumerate(tids)}
        for t in tids:
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": rank,
                    "tid": tid_map[t],
                    "args": {"name": f"device ops (capture tid {t})"},
                }
            )
        for op in ops:
            events.append(
                {
                    "ph": "X",
                    "name": op["name"],
                    "pid": rank,
                    "tid": tid_map[op["tid"]],
                    "ts": anchor_us + (op["ts"] - t0),
                    "dur": op["dur"],
                    "args": {
                        "hlo_op": op["hlo_op"],
                        "hlo_module": op["hlo_module"],
                        "igg_scope": scope_of(op),
                    },
                }
            )
        entry["n_ops"] = len(ops)
        entry["t_start_perf"] = meta["t_start_perf"]
        entry["window"] = meta.get("window")
    # Re-base: the validator refuses negative timestamps, and a device op
    # may align before the earliest host span.
    xs = [e["ts"] for e in events if e.get("ph") == "X"]
    if xs:
        shift = -min(min(xs), 0.0)
        if shift > 0:
            for e in events:
                if e.get("ph") == "X":
                    e["ts"] += shift
            alignment["ts_zero_offset_s"] = (
                float(alignment.get("ts_zero_offset_s", 0.0)) - shift / 1e6
            )
    events.sort(key=lambda e: (e["pid"], e.get("ts", -1.0)))
    doc["otherData"]["device_alignment"] = dev_align
    return doc
