"""Telemetry: process-local metrics registry + per-process JSONL event log.

The reference's entire instrumentation story is ``tic``/``toc``
(`/root/reference/src/tools.jl:230-236`), yet its headline claims are
*measurements* — weak-scaling efficiency and the effective memory throughput
``T_eff`` the ImplicitGlobalGrid/ParallelStencil papers report solver
performance in.  This module is the first-class observability layer behind
those numbers (docs/observability.md):

* **Metrics registry** — process-local counters, gauges and histograms
  (bounded reservoirs), keyed by dotted names (``halo.exchanges``,
  ``diffusion3d.t_eff_gbs``).  `snapshot()` returns the whole registry as
  plain data; `dump_metrics` writes it as JSON *and* Prometheus text
  exposition so any scrape/collect pipeline can ingest it.
* **Event log** — append-only JSONL, one file per process under
  ``IGG_TELEMETRY_DIR`` (``events.jsonl`` for process 0, ``events.pN.jsonl``
  for the rest).  Every line carries an absolute timestamp, the process
  rank, pid and (when a grid is up) the block coordinates — so a soak
  failover drill yields a machine-readable cross-process timeline of
  crashes, checkpoint fallbacks, elastic reshards and recoveries.  Lines
  are written with a single ``os.write`` on an ``O_APPEND`` descriptor:
  crash-safe (a hard ``os._exit`` right after an `event` call loses
  nothing) and interleaving-safe across processes.
* **Step-loop instrumentation** — `step_loop` hands the models'
  `guarded_time_loop` a per-step recorder: wall time, steps/s and the
  built-in ``T_eff`` (GB/s) from the solver's bytes-moved-per-step model
  (the reference perf convention: only arrays that *must* stream per step
  count, so ``T_eff = bytes_model / t_step`` is a lower bound on achieved
  HBM traffic), plus an optional rank-0 heartbeat line every
  ``IGG_HEARTBEAT_EVERY`` steps.

Zero overhead when disabled: with ``IGG_TELEMETRY=0`` every accessor
returns a shared no-op singleton (`counter`/`gauge`/`histogram`) or ``None``
(`step_loop`), `event` returns before touching the filesystem, and the
instrumented hot paths guard on `enabled()` — no allocation, no locks, no
timestamps on the disabled branch (pinned by ``tests/test_telemetry.py``).

The registry is process-lifetime state (NOT reset by `finalize_global_grid`
— a run's metrics outlive its grid); `reset()` exists for tests.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable, Sequence

from . import config as _config

__all__ = [
    "enabled",
    "counter",
    "gauge",
    "histogram",
    "tenant_counter",
    "tenant_histogram",
    "frontdoor_tenant_counter",
    "event",
    "atomic_write_json",
    "snapshot",
    "telemetry_snapshot",
    "dump_metrics",
    "prometheus_text",
    "step_loop",
    "teff_bytes",
    "proc_rss_bytes",
    "process_count",
    "note_progress",
    "last_progress",
    "reset",
]


def enabled() -> bool:
    """The ``IGG_TELEMETRY`` master switch (read per call, like IGG_DONATE)."""
    return _config.telemetry_enabled_env()


# -- Metric types -------------------------------------------------------------

#: reservoir size of every histogram — enough for stable p50/p90/p99 while
#: bounding a million-step run's memory to a few KiB per metric
RESERVOIR_SIZE = 512

#: rolling-SLO geometry (docs/observability.md live-plane section): every
#: histogram additionally keeps a ring of per-window sub-reservoirs so the
#: live plane can answer "p99 over the last few windows" instead of "p99
#: since process start".  `SLO_WINDOWS` windows of ``IGG_SLO_WINDOW_S``
#: seconds each (default `SLO_WINDOW_S_DEFAULT`), `WINDOW_RESERVOIR`
#: samples per window — bounded however long the run.
SLO_WINDOWS = 5
SLO_WINDOW_S_DEFAULT = 30.0
WINDOW_RESERVOIR = 256


def _slo_window_s() -> float:
    val = _config.slo_window_env()
    return SLO_WINDOW_S_DEFAULT if val is None else val


class Counter:
    """Monotonic counter.  ``inc`` is the only mutator (never decremented)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int | float = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class _Window:
    """One rolling-SLO sub-window: a bounded sample list over a time slice."""

    __slots__ = ("t0", "count", "total", "samples")

    def __init__(self, t0: float):
        self.t0 = t0
        self.count = 0
        self.total = 0.0
        self.samples: list[float] = []

    def add(self, v: float, rng) -> None:
        self.count += 1
        self.total += v
        if len(self.samples) < WINDOW_RESERVOIR:
            self.samples.append(v)
        else:
            j = rng.randrange(self.count)
            if j < WINDOW_RESERVOIR:
                self.samples[j] = v


def _quantile_of(samples: list[float], q: float) -> float | None:
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, round(q * (len(s) - 1))))
    return s[idx]


class Histogram:
    """Streaming distribution: count/sum/min/max + a bounded reservoir.

    The reservoir is classic Vitter-R sampling with a per-histogram seeded
    PRNG — deterministic for a given record sequence (tests), uniform over
    the stream, and bounded at `RESERVOIR_SIZE` samples however many values
    are recorded.  Quantiles in `summary()` come from the reservoir.

    On top of the run-lifetime reservoir, every histogram keeps a ring of
    rolling sub-windows (`SLO_WINDOWS` windows of ``IGG_SLO_WINDOW_S``
    seconds, `WINDOW_RESERVOIR` samples each — allocated lazily on first
    record, so the disabled-mode zero-allocation contract is untouched):
    `window_summary()` yields live p50/p90/p99 over the last few windows —
    the ``slo.*`` gauge family and the ``/healthz`` live plane read it
    (docs/observability.md).  All mutators and readers hold the instance
    lock, so a scrape thread rendering `prometheus_text` mid-`record` sees
    a consistent snapshot (the concurrent-scrape pin in
    ``tests/test_telemetry.py``).
    """

    __slots__ = ("name", "count", "total", "min", "max", "_samples", "_rng",
                 "_hlock", "_win_cur", "_win_ring", "_win_len")

    def __init__(self, name: str):
        import random

        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples: list[float] = []
        self._rng = random.Random(0x1661)  # seeded: deterministic reservoirs
        self._hlock = threading.Lock()
        self._win_cur: _Window | None = None  # lazy: first record allocates
        self._win_ring: collections.deque | None = None
        self._win_len = 0.0

    def record(self, v: float, now: float | None = None) -> None:
        v = float(v)
        with self._hlock:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._samples) < RESERVOIR_SIZE:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < RESERVOIR_SIZE:
                    self._samples[j] = v
            # rolling-SLO window ring (lazy; ``now`` injectable for tests)
            if now is None:
                now = time.monotonic()
            w = self._win_cur
            if w is None:
                self._win_len = _slo_window_s()
                self._win_ring = collections.deque(maxlen=SLO_WINDOWS - 1)
                w = self._win_cur = _Window(now)
            elif now - w.t0 >= self._win_len:
                self._win_ring.append(w)
                self._win_len = _slo_window_s()  # re-read per rollover
                w = self._win_cur = _Window(now)
            w.add(v, self._rng)

    def quantile(self, q: float) -> float | None:
        with self._hlock:
            return _quantile_of(self._samples, q)

    def window_summary(self, now: float | None = None) -> dict | None:
        """Live ``{window_s, windows, count, p50, p90, p99}`` over the last
        `SLO_WINDOWS` windows, or None before the first record.  Windows
        older than the rolling horizon (``SLO_WINDOWS * window_s`` behind
        ``now``) are excluded, so a long-idle histogram goes quiet instead
        of replaying stale quantiles forever."""
        with self._hlock:
            return self._window_summary_locked(now)

    def _window_summary_locked(self, now: float | None = None) -> dict | None:
        if self._win_cur is None:
            return None
        if now is None:
            now = time.monotonic()
        horizon = now - SLO_WINDOWS * self._win_len
        live = [
            w
            for w in (*self._win_ring, self._win_cur)
            if w.t0 >= horizon
        ]
        samples: list[float] = []
        count = 0
        total = 0.0
        for w in live:
            samples.extend(w.samples)
            count += w.count
            total += w.total
        if not count:
            return None
        return {
            "window_s": self._win_len,
            "windows": len(live),
            "count": count,
            "mean": total / count,
            "p50": _quantile_of(samples, 0.50),
            "p90": _quantile_of(samples, 0.90),
            "p99": _quantile_of(samples, 0.99),
        }

    def summary(self) -> dict:
        with self._hlock:
            out = {
                "count": self.count,
                "sum": self.total,
                "min": self.min,
                "max": self.max,
                "mean": (self.total / self.count) if self.count else None,
                "p50": _quantile_of(self._samples, 0.50),
                "p90": _quantile_of(self._samples, 0.90),
                "p99": _quantile_of(self._samples, 0.99),
            }
            win = self._window_summary_locked()
            if win is not None:
                out["window"] = win
            return out


class _Noop:
    """Shared do-nothing metric: the disabled-mode singleton every accessor
    returns — identity-stable so tests can pin the zero-allocation branch."""

    __slots__ = ()
    name = "<noop>"
    value = 0

    def inc(self, n: int | float = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def record(self, v: float, now: float | None = None) -> None:
        pass


NOOP = _Noop()


# -- Registry -----------------------------------------------------------------

_lock = threading.Lock()
_counters: dict[str, Counter] = {}
_gauges: dict[str, Gauge] = {}
_histograms: dict[str, Histogram] = {}
# (dir, filename) -> fd of the open event log
_event_fds: dict[tuple[str, str], int] = {}


def counter(name: str) -> Counter | _Noop:
    """The registry counter ``name`` (created on first use); `NOOP` when
    telemetry is disabled."""
    if not enabled():
        return NOOP
    with _lock:
        m = _counters.get(name)
        if m is None:
            m = _counters[name] = Counter(name)
        return m


def gauge(name: str) -> Gauge | _Noop:
    if not enabled():
        return NOOP
    with _lock:
        m = _gauges.get(name)
        if m is None:
            m = _gauges[name] = Gauge(name)
        return m


def gauge_value(name: str) -> float | None:
    """Current value of a gauge IF it exists (never creates one) — the
    read side the enriched heartbeat uses to attach skew/serving context
    only when something actually published it."""
    with _lock:
        g = _gauges.get(name)
        return g.value if g is not None else None


def histogram(name: str) -> Histogram | _Noop:
    if not enabled():
        return NOOP
    with _lock:
        m = _histograms.get(name)
        if m is None:
            m = _histograms[name] = Histogram(name)
        return m


#: default ``IGG_TELEMETRY_MAX_TENANTS`` (distinct per-tenant series)
MAX_TENANTS_DEFAULT = 64

#: the fold-over series once the tenant cap is hit
TENANT_OVERFLOW = "serving.tenant.__other__.steps"

_TENANT_PREFIX, _TENANT_SUFFIX = "serving.tenant.", ".steps"


def _capped_tenant_metric(registry: dict, factory, tenant: str,
                          prefix: str, suffix: str, overflow: str):
    """One cardinality-capped per-tenant series out of ``registry``.

    Tenant strings arrive from REQUESTS, so an uncapped per-tenant series
    is an unbounded-memory hole (every distinct string a metric, forever).
    At most ``IGG_TELEMETRY_MAX_TENANTS`` (default `MAX_TENANTS_DEFAULT`)
    distinct tenant series are created per (prefix, suffix) family; once
    the cap is reached, new tenants fold into the shared ``overflow``
    series (existing tenants keep their own).  Family totals stay exact
    either way — only per-tenant attribution degrades past the cap.
    Caller does NOT hold `_lock`.
    """
    name = f"{prefix}{tenant}{suffix}"
    with _lock:
        m = registry.get(name)
        if m is None:
            env = _config.telemetry_max_tenants_env()
            cap = MAX_TENANTS_DEFAULT if env is None else env
            distinct = sum(
                1
                for k in registry
                if k.startswith(prefix) and k.endswith(suffix)
                and k != overflow
            )
            if name != overflow and distinct >= cap:
                name = overflow
                m = registry.get(name)
            if m is None:
                m = registry[name] = factory(name)
        return m


def tenant_counter(tenant: str) -> Counter | _Noop:
    """The ``serving.tenant.<tenant>.steps`` counter, cardinality-capped
    (see `_capped_tenant_metric` for the fold-over contract)."""
    if not enabled():
        return NOOP
    return _capped_tenant_metric(
        _counters, Counter, tenant, _TENANT_PREFIX, _TENANT_SUFFIX,
        TENANT_OVERFLOW,
    )


#: the fold-over series of the front door's per-tenant latency family
FRONTDOOR_TENANT_OVERFLOW = "frontdoor.tenant.__other__.request_seconds"

_FD_TENANT_PREFIX, _FD_TENANT_SUFFIX = "frontdoor.tenant.", ".request_seconds"


def frontdoor_tenant_counter(tenant: str, kind: str) -> Counter | _Noop:
    """``frontdoor.tenant.<tenant>.<kind>`` counter (``kind`` in
    ``admitted``/``rejected`` — the per-tenant admission ledger the
    ``/healthz`` frontdoor section and `scripts/igg_top.py`'s reject-rate
    column read), cardinality-capped like `tenant_counter`."""
    if not enabled():
        return NOOP
    return _capped_tenant_metric(
        _counters, Counter, tenant, _FD_TENANT_PREFIX, f".{kind}",
        f"frontdoor.tenant.__other__.{kind}",
    )


def tenant_histogram(tenant: str) -> Histogram | _Noop:
    """The ``frontdoor.tenant.<tenant>.request_seconds`` histogram,
    cardinality-capped like `tenant_counter` (the per-tenant submit→result
    latency family of `serving.frontdoor`; its rolling window rides the
    ``slo.*`` gauge publication because the name ends in
    ``request_seconds``)."""
    if not enabled():
        return NOOP
    return _capped_tenant_metric(
        _histograms, Histogram, tenant, _FD_TENANT_PREFIX, _FD_TENANT_SUFFIX,
        FRONTDOOR_TENANT_OVERFLOW,
    )


def reset() -> None:
    """Drop every metric and close the event-log descriptors (test hook)."""
    global _rank_hint, _progress
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()
        for fd in _event_fds.values():
            try:
                os.close(fd)
            except OSError:
                pass
        _event_fds.clear()
    _rank_hint = None
    _progress = None


# -- Run progress (the live plane's last-step-age source) ---------------------

# The newest completed unit of work of this process — ``{wall, kind, step,
# init, done}`` — written by the instrumented loops (one dict write per
# step) and read by `utils.liveplane`'s ``/healthz`` endpoint and its
# step-stall anomaly rule.  ``init=True`` marks the pre-first-step phase
# (bring-up + first compile: a stall alarm there would be noise);
# ``done=True`` marks a completed run (the server outlives the loop — age
# keeps growing, but nothing is stalled).
_progress: dict | None = None


def note_progress(kind: str, step: int, *, init: bool = False,
                  done: bool = False) -> None:
    """Record the newest completed work unit (see `_progress`)."""
    global _progress
    _progress = {
        "wall": time.time(),
        "kind": kind,
        "step": int(step),
        "init": init,
        "done": done,
    }


def last_progress() -> dict | None:
    """The newest progress record plus its ``age_s``, or None before any."""
    p = _progress
    if p is None:
        return None
    out = dict(p)
    out["age_s"] = time.time() - p["wall"]
    return out


def proc_rss_bytes() -> int | None:
    """This process's resident set size in bytes, or None when unknown.

    ``/proc/self/statm`` (Linux) is the primary source; the
    ``resource.getrusage`` peak-RSS fallback covers platforms without
    procfs (a PEAK, not current — good enough for the growth-rule and
    leak-triage consumers, and graceful absence beats a wrong number).
    """
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGESIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        maxrss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        if not maxrss:
            return None
        # ru_maxrss is KILOBYTES on Linux/BSD but BYTES on macOS — the
        # platform this fallback exists for (no procfs there)
        return int(maxrss) if sys.platform == "darwin" else int(maxrss) * 1024
    except Exception:
        return None


# -- Identity tagging ---------------------------------------------------------

# Rank during bring-up, BEFORE the runtime can answer `jax.process_index()`:
# `parallel.distributed.init_distributed` stages its resolved process_id here
# so retry/fault events fired mid-bring-up land in the right per-rank file
# with the right tag (otherwise every process would claim rank 0 and write
# into rank 0's events.jsonl — exactly the events most worth attributing).
# Auto-detected pods without an explicit process_id cannot stage a hint; their
# bring-up events fall back to rank 0 (the pid field still disambiguates).
_rank_hint: int | None = None


def set_rank_hint(rank: int | None) -> None:
    """Stage the process rank for event tagging during runtime bring-up."""
    global _rank_hint
    _rank_hint = None if rank is None else int(rank)


def _proc_index() -> int:
    """Process rank without touching an absent runtime (hint/0 during
    bring-up — see `_rank_hint`)."""
    try:
        import jax

        from ..parallel import distributed as _dist

        if _dist.is_distributed_initialized():
            return jax.process_index()
    except Exception:
        pass
    return _rank_hint if _rank_hint is not None else 0


def process_count() -> int:
    """Process count without touching an absent runtime (1 then) — the ONE
    probe behind every "is this multi-process" gate (the SPMD-divergence
    guards in `resilience.RunGuard` and `serving.ServingLoop` key on it)."""
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def _grid_coords() -> list[int] | None:
    try:
        from ..parallel import grid as _grid

        if _grid.grid_is_initialized():
            return list(_grid.global_grid().coords)
    except Exception:
        pass
    return None


# -- Event log ----------------------------------------------------------------


def _event_filename(rank: int) -> str:
    return "events.jsonl" if rank == 0 else f"events.p{rank}.jsonl"


def event(etype: str, **payload: Any) -> None:
    """Append one rank/coords-tagged event line to this process's JSONL log.

    No-op unless telemetry is enabled AND ``IGG_TELEMETRY_DIR`` is set.
    The line is serialized first and written with one ``os.write`` on an
    ``O_APPEND`` descriptor — crash-safe (complete lines or nothing, even
    through an ``os._exit`` right after) and safe against cross-process
    interleaving in a shared directory.  Non-serializable payload values
    are stringified rather than dropped (an event log must never raise out
    of a hot path or a crash handler).
    """
    if not enabled():
        return
    directory = _config.telemetry_dir_env()
    if not directory:
        return
    rank = _proc_index()
    rec = {
        "ts": time.time(),
        "type": etype,
        "rank": rank,
        "pid": os.getpid(),
        "coords": _grid_coords(),
    }
    # Supervised runs thread the incarnation's generation token through
    # every event line (docs/robustness.md): a post-mortem timeline from a
    # shared directory attributes each event to its incarnation, and a
    # zombie's late writes are visibly stale.  Absent when unfenced.
    gen = _config.generation_env()
    if gen is not None:
        rec["gen"] = gen
    # An event emitted inside a request-scoped span inherits that request's
    # trace_id (lazy import: tracing imports telemetry at module scope, so
    # this edge must stay function-local), letting `igg_trace.py request`
    # line events up against a request's causal tree.  Absent outside any
    # request context or when the payload already names one.
    if "trace_id" not in payload:
        from . import tracing as _tracing

        ctx = _tracing.current_context()
        if ctx is not None and "trace_id" in ctx:
            rec["trace_id"] = ctx["trace_id"]
    rec.update(payload)
    try:
        line = json.dumps(rec, default=str) + "\n"
    except (TypeError, ValueError):
        line = json.dumps({k: str(v) for k, v in rec.items()}) + "\n"
    key = (directory, _event_filename(rank))
    try:
        with _lock:
            fd = _event_fds.get(key)
            if fd is None:
                os.makedirs(directory, exist_ok=True)
                fd = os.open(
                    os.path.join(*key),
                    os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                    0o644,
                )
                _event_fds[key] = fd
        os.write(fd, line.encode())
    except OSError:
        pass  # a full/unwritable disk must not take the run down


def atomic_write_json(path: str | os.PathLike, doc, *, fsync: bool = True,
                      indent: int | None = None) -> str:
    """Publish ``doc`` as JSON at ``path`` whole-or-not-at-all: write a
    ``.tmp`` sibling, flush + (by default) fsync, then ONE ``os.replace``.

    The shared crash-safety primitive behind every JSON artifact a consumer
    discovers by path (bench round records, the front door's endpoint file
    and ``resize.json``, the liveplane endpoint file) — round 5's bench
    record was lost to a mid-capture crash precisely because its only copy
    was a half-flushed stream.  ``fsync=False`` trades power-loss safety
    for speed where the artifact is advisory.
    """
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=indent, default=str)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_events(path: str | os.PathLike) -> list[dict]:
    """Parse one JSONL event file (helper for tests/tools); skips any
    torn trailing line."""
    out = []
    with open(os.fspath(path)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


# -- Snapshot + exposition ----------------------------------------------------


def snapshot() -> dict:
    """The whole registry as plain data (JSON-serializable)."""
    with _lock:
        return {
            "enabled": enabled(),
            "rank": _proc_index(),
            "pid": os.getpid(),
            "coords": _grid_coords(),
            "ts": time.time(),
            "counters": {n: c.value for n, c in _counters.items()},
            "gauges": {n: g.value for n, g in _gauges.items()},
            "histograms": {n: h.summary() for n, h in _histograms.items()},
        }


#: public-API alias (exported as ``igg.telemetry_snapshot``)
telemetry_snapshot = snapshot


def _prom_name(name: str) -> str:
    """Prometheus metric name: ``igg_`` prefix, dots/dashes to underscores."""
    safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return f"igg_{safe}"


def prometheus_text(snap: dict | None = None) -> str:
    """Prometheus text exposition (format 0.0.4) of a registry snapshot.

    Counters as ``counter``, gauges as ``gauge``, histograms as ``summary``
    (reservoir quantiles + ``_sum``/``_count``).  Every line group carries
    its ``# TYPE`` header, so standard parsers/scrapers accept the output.
    """
    if snap is None:
        snap = snapshot()
    lines: list[str] = []
    for name, value in sorted(snap.get("counters", {}).items()):
        pn = _prom_name(name) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {value}")
    for name, value in sorted(snap.get("gauges", {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {value}")
    for name, s in sorted(snap.get("histograms", {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} summary")
        for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            v = s.get(key)
            if v is not None:
                lines.append(f'{pn}{{quantile="{q}"}} {v}')
        lines.append(f"{pn}_sum {s.get('sum', 0.0)}")
        lines.append(f"{pn}_count {s.get('count', 0)}")
    return "\n".join(lines) + "\n"


def dump_metrics(path: str | os.PathLike) -> tuple[str, str]:
    """Write the registry snapshot as JSON and Prometheus text.

    ``path`` is the basename: ``<path>.json`` and ``<path>.prom`` are
    written (a ``path`` already ending in ``.json`` keeps that name and the
    exposition drops the suffix).  Returns ``(json_path, prom_path)``.
    Exported as ``igg.dump_metrics``.
    """
    path = os.fspath(path)
    base = path[: -len(".json")] if path.endswith(".json") else path
    json_path, prom_path = base + ".json", base + ".prom"
    snap = snapshot()
    d = os.path.dirname(base)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(json_path, "w") as f:
        json.dump(snap, f, indent=1, default=str)
    with open(prom_path, "w") as f:
        f.write(prometheus_text(snap))
    return json_path, prom_path


# -- Step-loop instrumentation ------------------------------------------------


def teff_bytes(fields: Sequence) -> int:
    """The solver's bytes-moved-per-step model from its must-stream fields.

    Reference perf convention (ParallelStencil/IGG papers; bench.py's
    ``A_eff``): only arrays that MUST stream once in and once out per step
    count, i.e. ``2 * sum(nbytes)`` of the evolving state — reads of
    read-only parameter fields and the halo traffic are free on top, so
    ``T_eff = teff_bytes / t_step`` is a lower bound on achieved traffic.
    Per solver (docs/observability.md): diffusion counts T; acoustic counts
    P, Vx, Vy, Vz; porous convection counts T, Pf, qDx, qDy, qDz.  Sizes
    are the GLOBAL arrays' (aggregate throughput; divide by block count for
    a per-device figure).
    """
    total = 0
    for A in fields:
        nbytes = getattr(A, "nbytes", None)
        if nbytes is None:
            import numpy as np

            nbytes = int(np.prod(A.shape)) * np.dtype(A.dtype).itemsize
        total += int(nbytes)
    return 2 * total


class _StepLoop:
    """Per-step recorder handed to the models' time loops (see `step_loop`)."""

    def __init__(self, model: str, bytes_per_step: int | None,
                 start_step: int, total_steps: int, heartbeat_every: int):
        self.model = model
        self.bytes_per_step = bytes_per_step
        self.total_steps = total_steps
        self.heartbeat_every = heartbeat_every
        self._is_rank0 = _proc_index() == 0
        self._steps = counter(f"{model}.steps")
        self._step_s = histogram(f"{model}.step_seconds")
        self._sps = gauge(f"{model}.steps_per_s")
        self._teff = histogram(f"{model}.t_eff_gbs") if bytes_per_step else None
        self._teff_g = gauge(f"{model}.t_eff_gbs_last") if bytes_per_step else None
        self._t_last = time.perf_counter()
        # last-window accumulator for the all-ranks skew probe (one window
        # per heartbeat interval; docs/observability.md straggler section)
        self._win_sum = 0.0
        self._win_n = 0
        # Live plane (utils.liveplane): bring the per-rank scrape server up
        # (no-op unless IGG_METRICS_PORT is set) and mark the pre-first-step
        # phase so the step-stall rule ignores bring-up/compile time.
        note_progress(model, start_step, init=True)
        from . import liveplane as _liveplane

        _liveplane.ensure_server()
        # Device-timeline capture (utils.profiling, docs/observability.md):
        # armed per run from the step pipeline exactly like the live-plane
        # server above — None unless IGG_PROFILE names a step window.
        from . import profiling as _profiling

        self._profile = _profiling.maybe_arm(start_step)
        event("run.start", model=model, start_step=start_step,
              total_steps=total_steps, bytes_per_step=bytes_per_step)

    def on_step(self, it: int) -> None:
        """Record one completed step (wall time since the previous call)."""
        now = time.perf_counter()
        dt = now - self._t_last
        self._t_last = now
        self._steps.inc()
        self._step_s.record(dt)
        self._win_sum += dt
        self._win_n += 1
        if dt > 0:
            self._sps.set(1.0 / dt)
        gbs = None
        if self._teff is not None and dt > 0:
            gbs = self.bytes_per_step / dt / 1e9
            self._teff.record(gbs)
            self._teff_g.set(gbs)
        note_progress(self.model, it)
        if self._profile is not None:
            self._profile.on_step(it)
        if self.heartbeat_every and it % self.heartbeat_every == 0:
            # The skew probe is a COLLECTIVE: every rank must run it at the
            # same step (hence outside the rank-0 gate below; single-process
            # grids return None without touching any transport).
            skew = None
            if self._win_n:
                from . import tracing as _tracing

                skew = _tracing.skew_probe(self._win_sum / self._win_n)
            self._win_sum = 0.0
            self._win_n = 0
            # Live-plane heartbeat tick on EVERY rank (strictly local — no
            # collectives): publish the proc.rss_bytes gauge and the slo.*
            # windowed quantiles, then evaluate the anomaly rules
            # (docs/observability.md live-plane section).
            rss = proc_rss_bytes()
            if rss is not None:
                gauge("proc.rss_bytes").set(rss)
            from . import liveplane as _liveplane

            _liveplane.heartbeat_tick(model=self.model)
            if self._is_rank0:
                import sys

                teff_s = f" T_eff {gbs:.2f} GB/s" if gbs is not None else ""
                skew_s = (
                    f" skew {skew['ratio']:.2f} (slowest rank "
                    f"{skew['slowest_rank']})" if skew else ""
                )
                print(
                    f"[igg.telemetry] {self.model} step {it}/"
                    f"{self.total_steps} "
                    f"{dt * 1e3:.2f} ms/step {1.0 / dt if dt > 0 else 0.0:.1f} "
                    f"steps/s{teff_s}{skew_s}",
                    file=sys.stderr,
                    flush=True,
                )
                event("heartbeat", model=self.model, step=it,
                      step_seconds=dt, t_eff_gbs=gbs,
                      **_heartbeat_context(skew))

    def finish(self, it: int) -> None:
        if self._profile is not None:
            # a window still open past the last step (nt inside it) stops
            # here so the capture lands with the run's artifacts
            self._profile.close("run_complete")
        note_progress(self.model, it, done=True)
        event("run.complete", model=self.model, step=it)


def _heartbeat_context(skew: dict | None) -> dict:
    """The heartbeat event's extended context (docs/observability.md):
    the current skew gauges (fresh probe result preferred, else the last
    published gauges) and the serving pool occupancy — each attached only
    when something actually recorded it."""
    ctx: dict = {}
    if skew is not None:
        ctx["skew"] = {
            "step_seconds_max_over_min": skew["ratio"],
            "slowest_rank": skew["slowest_rank"],
        }
    else:
        ratio = gauge_value("skew.step_seconds_max_over_min")
        if ratio is not None:
            ctx["skew"] = {
                "step_seconds_max_over_min": ratio,
                "slowest_rank": gauge_value("skew.slowest_rank"),
            }
    active = gauge_value("serving.active_members")
    if active is not None:
        ctx["serving"] = {
            "active_members": active,
            "queue_depth": gauge_value("serving.queue_depth"),
        }
    # The live plane's scrape endpoint, when one is serving: the rank-0
    # heartbeat is the discovery channel for an ephemeral (port 0) bind.
    port = gauge_value("liveplane.port")
    if port is not None:
        ctx["liveplane"] = {"port": int(port)}
    return ctx


def step_loop(
    model: str,
    *,
    bytes_per_step: int | None = None,
    start_step: int = 0,
    total_steps: int = 0,
) -> _StepLoop | None:
    """A per-step recorder for a host-side time loop, or ``None`` disabled.

    The ``None`` return IS the zero-overhead contract: the caller's loop
    guards every telemetry touch behind ``if tele is not None`` and the
    disabled path allocates nothing per step (``tests/test_telemetry.py``
    pins this).  ``bytes_per_step`` (see `teff_bytes`) switches on the
    built-in ``T_eff``; heartbeat cadence comes from ``IGG_HEARTBEAT_EVERY``.
    """
    if not enabled():
        return None
    hb = _config.heartbeat_every_env() or 0
    return _StepLoop(model, bytes_per_step, start_step, total_steps, hb)
