"""Checkpoint/restart for global-block fields.

A preempted worker must not lose the simulation (ROADMAP north star: serve
heavy production traffic — preemption is routine there).  The reference has
no restart story at all; this module adds one that respects the implicit
global grid's memory contract: the de-duplicated global array is NEVER
materialized.  Each process writes only its own *local shards* (the blocks
its devices hold, halos included) plus a small JSON of grid/topology
metadata, and restore round-trips through `init_global_grid` — a restarted
job that re-inits with the same ``dims`` resumes mid-simulation with
bit-identical fields.

On-disk layout (one directory per checkpointed step)::

    <dir>/step_00000012/
        shards_p0.npz      per-process: raw shard bytes + global offsets
        shards_p1.npz
        meta.json          written LAST by process 0 after a barrier —
                           its presence marks the checkpoint complete

Shard payloads are stored as raw bytes + dtype string, so every JAX dtype
(incl. ``bfloat16`` and other ``ml_dtypes`` extensions NumPy cannot
serialize natively) round-trips bit-exactly.  A crash mid-save leaves a
directory without ``meta.json``, which `latest_checkpoint` ignores — the
previous complete checkpoint stays authoritative.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Sequence

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES

FORMAT_VERSION = 1
_META = "meta.json"


def _step_dirname(step: int) -> str:
    return f"step_{step:08d}"


def _dtype_to_str(dt) -> str:
    return np.dtype(dt).name


def _dtype_from_str(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError):
        raise ValueError(
            f"Checkpoint field dtype {name!r} is not constructible in this "
            f"environment (numpy and ml_dtypes both lack it)."
        )


def _index_starts(index, shape) -> tuple[int, ...]:
    return tuple(
        0 if sl.start is None else int(sl.start)
        for sl, _ in zip(index, shape)
    )


#: keys of `GlobalGrid.checkpoint_meta` a restore must match (device_type is
#: informational: restoring a CPU-written checkpoint on TPU is legitimate).
_MATCH_KEYS = ("dims", "nxyz", "nxyz_g", "overlaps", "periods", "disp", "nprocs")


def save_checkpoint(
    directory: str | os.PathLike,
    state: Sequence,
    step: int,
    *,
    extra: dict | None = None,
) -> str:
    """Write a checkpoint of ``state`` (a sequence of global-block arrays).

    Collective: every process must call it (each writes its own shards; a
    barrier orders the completion marker after all shard files).  Returns
    the step directory path.  Memory-scalable: only local shards touch the
    host, never the assembled global array.
    """
    import jax

    _grid.check_initialized()
    gg = _grid.global_grid()
    state = tuple(state)
    if not state:
        raise ValueError("save_checkpoint requires a non-empty state.")
    step = int(step)
    if step < 0:
        raise ValueError(f"step must be >= 0 (got {step})")

    pid = jax.process_index()
    step_dir = os.path.join(os.fspath(directory), _step_dirname(step))
    os.makedirs(step_dir, exist_ok=True)
    # A complete marker from a previous visit to this step (rollback, rerun)
    # must not vouch for the shards we are about to replace.
    if pid == 0:
        try:
            os.remove(os.path.join(step_dir, _META))
        except FileNotFoundError:
            pass

    payload: dict[str, np.ndarray] = {}
    fields_meta = []
    for i, A in enumerate(state):
        if not isinstance(A, jax.Array):
            raise TypeError(
                f"save_checkpoint: state[{i}] is {type(A).__name__}, expected "
                f"a global-block jax.Array (create fields with the igg "
                f"constructors)."
            )
        fields_meta.append(
            {
                "global_shape": list(A.shape),
                "dtype": _dtype_to_str(A.dtype),
            }
        )
        seen = set()
        for shard in A.addressable_shards:
            starts = _index_starts(shard.index, A.shape)
            if starts in seen:
                continue  # replicated field: one copy of the block is enough
            seen.add(starts)
            data = np.asarray(shard.data)
            key = "f%d_o%s" % (i, "_".join(map(str, starts)))
            payload[key] = np.frombuffer(
                np.ascontiguousarray(data).tobytes(), dtype=np.uint8
            )
            payload[key + "_shape"] = np.asarray(data.shape, dtype=np.int64)

    shard_path = os.path.join(step_dir, f"shards_p{pid}.npz")
    tmp = shard_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, shard_path)

    # All shard files on disk before the completion marker exists.
    from ..parallel import distributed as _dist

    _dist.sync_all_processes()
    if pid == 0:
        meta = {
            "format": FORMAT_VERSION,
            "step": step,
            "nfields": len(state),
            "fields": fields_meta,
            "grid": gg.checkpoint_meta(),
            "process_count": int(jax.process_count()),
            "extra": extra or {},
        }
        tmp = os.path.join(step_dir, _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, os.path.join(step_dir, _META))
    return step_dir


def latest_checkpoint(directory: str | os.PathLike) -> str | None:
    """Newest COMPLETE checkpoint directory under ``directory``, or None.

    Completeness = ``meta.json`` present (written last, after the barrier);
    directories a crash left half-written are skipped.
    """
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return None
    best: tuple[int, str] | None = None
    for name in os.listdir(directory):
        if not name.startswith("step_"):
            continue
        path = os.path.join(directory, name)
        if not os.path.isfile(os.path.join(path, _META)):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            continue
        if best is None or step > best[0]:
            best = (step, path)
    return None if best is None else best[1]


def checkpoint_meta(path: str | os.PathLike) -> dict:
    """Read a checkpoint's ``meta.json`` (raises if incomplete/missing)."""
    meta_path = os.path.join(os.fspath(path), _META)
    try:
        with open(meta_path) as f:
            return json.load(f)
    except FileNotFoundError:
        raise FileNotFoundError(
            f"No complete checkpoint at {os.fspath(path)!r} (missing "
            f"{_META}; was the save interrupted?)."
        )


def restore_checkpoint(
    path: str | os.PathLike,
    *,
    like: Sequence | None = None,
) -> tuple[tuple, int, dict]:
    """Restore ``(state, step, extra)`` from a checkpoint directory.

    Requires an initialized grid matching the checkpoint's topology (the
    round-trip-through-`init_global_grid` contract: re-init with the same
    local sizes and ``dims``, then restore).  Each process reads only its
    own shard file; arrays are rebuilt with the field constructors'
    sharding (or ``like``'s, when given) — bit-exact for every dtype.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P, SingleDeviceSharding

    _grid.check_initialized()
    gg = _grid.global_grid()
    path = os.fspath(path)
    meta = checkpoint_meta(path)
    if meta.get("format") != FORMAT_VERSION:
        raise ValueError(
            f"Checkpoint {path!r} has format {meta.get('format')!r}; this "
            f"build reads format {FORMAT_VERSION}."
        )
    saved_grid = meta["grid"]
    current = gg.checkpoint_meta()
    mismatch = [k for k in _MATCH_KEYS if saved_grid.get(k) != current[k]]
    if mismatch:
        detail = ", ".join(
            f"{k}: checkpoint {saved_grid.get(k)} vs current {current[k]}"
            for k in mismatch
        )
        raise ValueError(
            f"Checkpoint {path!r} was written for a different grid "
            f"topology ({detail}). Re-init the global grid with the same "
            f"local sizes and dims to restore it."
        )
    if meta["process_count"] != jax.process_count():
        raise ValueError(
            f"Checkpoint {path!r} was written by {meta['process_count']} "
            f"process(es) but this job runs {jax.process_count()}; restart "
            f"with the same process count."
        )
    if like is not None and len(tuple(like)) != meta["nfields"]:
        raise ValueError(
            f"Checkpoint {path!r} holds {meta['nfields']} field(s) but "
            f"`like` has {len(tuple(like))}."
        )

    pid = jax.process_index()
    shard_path = os.path.join(path, f"shards_p{pid}.npz")
    if not os.path.isfile(shard_path):
        raise FileNotFoundError(
            f"Checkpoint {path!r} has no shard file for process {pid} "
            f"({shard_path}); it was written by a different process layout."
        )
    npz = np.load(shard_path)

    state = []
    for i, fmeta in enumerate(meta["fields"]):
        gshape = tuple(fmeta["global_shape"])
        dtype = _dtype_from_str(fmeta["dtype"])
        if like is not None:
            sharding = tuple(like)[i].sharding
            if tuple(tuple(like)[i].shape) != gshape:
                raise ValueError(
                    f"Checkpoint field {i} has global shape {gshape} but "
                    f"`like[{i}]` has {tuple(tuple(like)[i].shape)}."
                )
        elif gg.nprocs == 1 and not gg.force_spmd:
            sharding = SingleDeviceSharding(gg.mesh.devices.flat[0])
        else:
            sharding = NamedSharding(gg.mesh, P(*AXIS_NAMES[: len(gshape)]))

        prefix = f"f{i}_o"

        def lookup(index, i=i, gshape=gshape, dtype=dtype, prefix=prefix):
            starts = _index_starts(index, gshape)
            key = prefix + "_".join(map(str, starts))
            if key not in npz:
                raise KeyError(
                    f"Checkpoint {path!r} shard file for process {pid} has "
                    f"no block at offsets {starts} for field {i}; the "
                    f"device-to-process layout changed since the save."
                )
            shape = tuple(int(s) for s in npz[key + "_shape"])
            return np.frombuffer(npz[key].tobytes(), dtype=dtype).reshape(shape)

        state.append(jax.make_array_from_callback(gshape, sharding, lookup))
    return tuple(state), int(meta["step"]), meta.get("extra", {})


def prune_checkpoints(directory: str | os.PathLike, *, keep: int = 2) -> list[str]:
    """Delete all but the newest ``keep`` complete checkpoints (process 0
    only; other ranks no-op).  Returns the removed paths."""
    import jax

    if keep < 1:
        raise ValueError(f"keep must be >= 1 (got {keep})")
    if jax.process_index() != 0:
        return []
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return []
    complete = []
    for name in sorted(os.listdir(directory)):
        path = os.path.join(directory, name)
        if name.startswith("step_") and os.path.isfile(os.path.join(path, _META)):
            try:
                complete.append((int(name[len("step_"):]), path))
            except ValueError:
                continue
    complete.sort()
    removed = []
    for _, path in complete[:-keep]:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    return removed
