"""Checkpoint/restart for global-block fields.

A preempted worker must not lose the simulation (ROADMAP north star: serve
heavy production traffic — preemption is routine there).  The reference has
no restart story at all; this module adds one that respects the implicit
global grid's memory contract: the de-duplicated global array is NEVER
materialized on the fast path.  Each process writes only its own *local
shards* (the blocks its devices hold, halos included) plus a small JSON of
grid/topology metadata, and restore round-trips through `init_global_grid`.

On-disk layout (one directory per checkpointed step)::

    <dir>/step_00000012/
        shards_p0.npz      per-process: raw shard bytes + global offsets
        shards_p1.npz
        meta.json          manifest: grid topology, per-shard CRC32s/sizes;
                           written LAST inside a hidden temp directory that
                           is atomically renamed to step_* once complete

Shard payloads are stored as raw bytes + dtype string, so every JAX dtype
(incl. ``bfloat16`` and other ``ml_dtypes`` extensions NumPy cannot
serialize natively) round-trips bit-exactly.

Integrity (format 2): the whole step directory is staged under a hidden
``.step_*.tmp`` name and only renamed into place after every shard file and
the manifest are on disk — a crash mid-save never leaves a visible
``step_*`` directory at all.  The manifest carries per-shard CRC32s and
byte counts; `verify_checkpoint` replays them, and `latest_checkpoint`
falls back generation by generation to the newest checkpoint that passes —
a torn or bit-flipped shard is detected and *skipped*, never restored into
a silently wrong run.  The manifest additionally carries rolling per-field
lineage digests (`integrity.lineage`), hashed from the live arrays before
any byte hits disk: a CRC-clean generation whose stored bytes contradict
its lineage was already corrupt when saved (silent data corruption in the
writer path), and the same fallback walks past it.  Format-1 directories
(pre-manifest) stay readable: their completion marker is the presence of
``meta.json``.

Elastic restore: the global grid is *implicit* — any ``(nxyz, dims,
overlaps, periods)`` implying the same ``nxyz_g`` describes the same
physical grid (`parallel.topology.implied_global_shape`) — so
`restore_checkpoint` accepts any admissible target topology: when the
current grid differs from the save (different ``dims``, process count, or
device-to-process layout), each field's de-duplicated global array is
reassembled from the saved per-block offsets (`ops.gather.assemble_dedup`,
the same owner-wise rule `gather(dedup=True)` uses) and re-sliced under the
current grid's sharding.  ``strict=True`` preserves the bit-exact
same-topology-only contract.  The elastic path materializes one field's
global array at a time on each process and needs every shard file readable
(a shared checkpoint directory); the same-topology fast path keeps the
per-process-shards-only memory bound.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import shutil
import sys
import zlib
from typing import Any, Sequence

import numpy as np

from ..integrity import lineage as _lineage
from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES
from . import config as _config
from . import telemetry as _telemetry
from . import tracing as _tracing

FORMAT_VERSION = 2
#: formats this build can restore (1 = pre-manifest, no integrity data)
READABLE_FORMATS = (1, 2)
_META = "meta.json"


def _step_dirname(step: int) -> str:
    return f"step_{step:08d}"


def _tmp_dirname(step: int) -> str:
    # Dot-prefixed: never matches the `step_*` scan, so a crash mid-save
    # cannot leave a visible half-written generation.
    return f".{_step_dirname(step)}.tmp"


def _dtype_to_str(dt) -> str:
    return np.dtype(dt).name


def _dtype_from_str(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError):
        raise ValueError(
            f"Checkpoint field dtype {name!r} is not constructible in this "
            f"environment (numpy and ml_dtypes both lack it)."
        )


def _index_starts(index, shape) -> tuple[int, ...]:
    return tuple(
        0 if sl.start is None else int(sl.start)
        for sl, _ in zip(index, shape)
    )


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(buf, crc)


def _shard_name(pid: int) -> str:
    return f"shards_p{pid}.npz"


#: keys of `GlobalGrid.checkpoint_meta` the same-topology fast path must
#: match (device_type is informational: restoring a CPU-written checkpoint
#: on TPU is legitimate).  An elastic restore only needs admissibility
#: (`parallel.grid.elastic_topology_error`).
_MATCH_KEYS = ("dims", "nxyz", "nxyz_g", "overlaps", "periods", "disp", "nprocs")


def save_checkpoint(
    directory: str | os.PathLike,
    state: Sequence,
    step: int,
    *,
    extra: dict | None = None,
) -> str:
    with _tracing.trace_span("igg.checkpoint.save", step=step):
        return _save_checkpoint(directory, state, step, extra=extra)


def _save_checkpoint(
    directory: str | os.PathLike,
    state: Sequence,
    step: int,
    *,
    extra: dict | None = None,
) -> str:
    """Write a checkpoint of ``state`` (a sequence of global-block arrays).

    Collective: every process must call it (each writes its own shards; a
    barrier orders the manifest after all shard files; the staged directory
    is atomically renamed into place by process 0, and a second barrier
    guarantees the returned path is published on every process).  Returns
    the step directory path.  Memory-scalable: only local shards touch the
    host, never the assembled global array.
    """
    import jax

    # Generation fencing (docs/robustness.md): a rank from a superseded
    # incarnation must never publish state.  Checked BEFORE any byte lands
    # on disk; the verdict is rank-uniform (per-incarnation env token vs
    # the shared fence file), so the refusal cannot split the collective
    # save below.  Function-level import: utils must not pull the
    # supervisor package at module load.
    from ..supervisor import generation as _generation

    _generation.check_fence("checkpoint.save")
    _grid.check_initialized()
    gg = _grid.global_grid()
    state = tuple(state)
    if not state:
        raise ValueError("save_checkpoint requires a non-empty state.")
    step = int(step)
    if step < 0:
        raise ValueError(f"step must be >= 0 (got {step})")

    pid = jax.process_index()
    directory = os.fspath(directory)
    step_dir = os.path.join(directory, _step_dirname(step))
    tmp_dir = os.path.join(directory, _tmp_dirname(step))
    os.makedirs(tmp_dir, exist_ok=True)

    payload: dict[str, np.ndarray] = {}
    fields_meta = []
    for i, A in enumerate(state):
        if not isinstance(A, jax.Array):
            raise TypeError(
                f"save_checkpoint: state[{i}] is {type(A).__name__}, expected "
                f"a global-block jax.Array (create fields with the igg "
                f"constructors)."
            )
        fields_meta.append(
            {
                "global_shape": list(A.shape),
                "dtype": _dtype_to_str(A.dtype),
            }
        )
        seen = set()
        for shard in A.addressable_shards:
            starts = _index_starts(shard.index, A.shape)
            if starts in seen:
                continue  # replicated field: one copy of the block is enough
            seen.add(starts)
            data = np.ascontiguousarray(np.asarray(shard.data))
            key = "f%d_o%s" % (i, "_".join(map(str, starts)))
            # zero-copy byte view (a .tobytes() round-trip would double the
            # transient host memory per shard at pod-scale sizes)
            payload[key] = data.view(np.uint8).reshape(-1)
            payload[key + "_shape"] = np.asarray(data.shape, dtype=np.int64)

    # Lineage digests (integrity.lineage): hash every block's payload bytes
    # from the LIVE arrays, BEFORE the npz writer (or the in-tree
    # ``bit_flip:…:ckpt`` injection below) touches them — the digest vouches
    # for the state being saved, the CRC for the bytes as written.  A
    # divergence between the two is the poisoned-at-save class.
    block_digests = {
        key: _lineage.block_digest(buf)
        for key, buf in payload.items()
        if not key.endswith("_shape")
    }
    from . import resilience as _res

    _res.get_fault_injector().maybe_bit_flip_ckpt(payload, step)

    shard_path = os.path.join(tmp_dir, _shard_name(pid))
    tmp = shard_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, shard_path)
    # Sidecar: how process 0 learns every shard's integrity record without a
    # data collective (the checkpoint directory is the shared medium).
    sidecar = {
        "file": _shard_name(pid),
        "bytes": os.path.getsize(shard_path),
        "crc32": _crc32_file(shard_path),
        "blocks": block_digests,
    }
    tmp = shard_path + ".crc.json.tmp"
    with open(tmp, "w") as f:
        json.dump(sidecar, f)
    os.replace(tmp, shard_path + ".crc.json")

    # All shard files + sidecars on disk before the manifest is assembled.
    from ..parallel import distributed as _dist

    _dist.sync_all_processes()
    if pid == 0:
        shards: dict[str, dict] = {}
        all_blocks: dict[str, str] = {}
        for p in range(jax.process_count()):
            sc_path = os.path.join(tmp_dir, _shard_name(p) + ".crc.json")
            try:
                with open(sc_path) as f:
                    rec = json.load(f)
            except (OSError, ValueError) as e:
                raise RuntimeError(
                    f"save_checkpoint: process {p}'s integrity sidecar "
                    f"{sc_path} is unreadable after the barrier ({e!r}); is "
                    f"the checkpoint directory shared by all processes?"
                )
            shards[rec["file"]] = {"bytes": rec["bytes"], "crc32": rec["crc32"]}
            all_blocks.update(rec.get("blocks") or {})
        # Roll the lineage chain forward from the newest OLDER published
        # generation (a same-step rerun replaces its generation, so it must
        # not chain against itself); absent/foreign predecessors reset to
        # genesis inside `chain_field_digests`.
        prev_meta_path = None
        prev_step = None
        for s, p in reversed(checkpoint_steps(directory)):
            if s < step:
                prev_meta_path, prev_step = os.path.join(p, _META), s
                break
        field_digests = _lineage.field_digests_from_blocks(
            all_blocks, len(state)
        )
        chain = _lineage.chain_field_digests(
            field_digests,
            _lineage.read_prev_chain(prev_meta_path, len(state)),
        )
        meta = {
            "format": FORMAT_VERSION,
            "step": step,
            "nfields": len(state),
            "fields": fields_meta,
            "grid": gg.checkpoint_meta(),
            "process_count": int(jax.process_count()),
            "shards": shards,
            "lineage": {
                "fields": [
                    {"digest": d, "chain": c}
                    for d, c in zip(field_digests, chain)
                ],
                "blocks": all_blocks,
                "prev_step": prev_step,
            },
            "extra": extra or {},
        }
        # The writing incarnation's generation token (docs/robustness.md):
        # lets a supervisor attribute every generation on disk to the
        # incarnation that produced it.  Absent on unfenced runs — the
        # format is unchanged, the key is additive.
        gen = _config.generation_env()
        if gen is not None:
            meta["generation"] = gen
        tmp = os.path.join(tmp_dir, _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, os.path.join(tmp_dir, _META))
        for sc in _glob.glob(os.path.join(tmp_dir, "*.crc.json")):
            try:
                os.remove(sc)
            except OSError:
                pass
        # Atomic publish: the complete staged directory takes the step name
        # in one rename; a pre-existing generation of the same step (a
        # rolled-back rerun) is replaced.
        shutil.rmtree(step_dir, ignore_errors=True)
        os.rename(tmp_dir, step_dir)
        # In-tree fault injection (``ckpt_corrupt``/``ckpt_truncate``):
        # damage the published generation AFTER the manifest vouched for it,
        # so the integrity fallback is provable end to end.
        from . import resilience as _res

        _res.get_fault_injector().maybe_damage_checkpoint(step_dir, step)
    # Second barrier: the returned path must exist (published) on EVERY
    # process — without it a non-root caller could verify/restore the path
    # before process 0's rename lands.
    _dist.sync_all_processes()
    _telemetry.event(
        "checkpoint.saved",
        step=step,
        path=step_dir,
        shard_bytes=sidecar["bytes"],
    )
    _telemetry.counter("checkpoint.saves").inc()
    _telemetry.counter("checkpoint.shard_bytes").inc(sidecar["bytes"])
    return step_dir


# The public entry wraps the implementation in the ``igg.checkpoint.save``
# host span (docs/observability.md); same docstring, same contract.
save_checkpoint.__doc__ = _save_checkpoint.__doc__


def checkpoint_steps(directory: str | os.PathLike) -> list[tuple[int, str]]:
    """All published checkpoint generations under ``directory``, sorted by
    step ascending, as ``(step, path)`` pairs.  Published = the ``step_*``
    rename happened and ``meta.json`` is present; integrity is NOT checked
    here (see `verify_checkpoint` / `latest_checkpoint`)."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        if not name.startswith("step_"):
            continue
        path = os.path.join(directory, name)
        if not os.path.isfile(os.path.join(path, _META)):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            continue
        out.append((step, path))
    out.sort()
    return out


def verify_checkpoint(path: str | os.PathLike) -> str | None:
    """Why checkpoint ``path`` fails integrity verification, or None.

    Format 2: every manifest-listed shard file must exist with the recorded
    byte count and CRC32 — detects truncation (torn write) and corruption
    (bit flips) before a restore can propagate them.  After the CRC pass,
    the manifest's lineage digests are replayed (`integrity.lineage`,
    streamed in bounded chunks so a sweep over pod-scale shards never
    spikes RSS): a CRC-clean generation whose bytes do not reproduce the
    per-field digest chain was already corrupt WHEN SAVED — a poisoned
    generation `latest_checkpoint` walks past like any other invalid one.
    Format 1 predates the manifest: the completion marker is the only
    check (legacy semantics).
    """
    path = os.fspath(path)
    meta_path = os.path.join(path, _META)
    if not os.path.isfile(meta_path):
        return f"no completion marker ({_META})"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        return f"unreadable {_META} ({e})"
    fmt = meta.get("format")
    if fmt not in READABLE_FORMATS:
        return f"unknown checkpoint format {fmt!r} (this build reads {READABLE_FORMATS})"
    shards = meta.get("shards")
    if shards is None:
        return None  # format 1: no integrity data to replay
    for fname, rec in shards.items():
        fpath = os.path.join(path, fname)
        if not os.path.isfile(fpath):
            return f"missing shard file {fname}"
        size = os.path.getsize(fpath)
        if size != rec["bytes"]:
            return (
                f"shard {fname} truncated: {size} bytes on disk vs "
                f"{rec['bytes']} in the manifest"
            )
        crc = _crc32_file(fpath)
        if crc != rec["crc32"]:
            return (
                f"shard {fname} corrupt: CRC32 {crc:#010x} on disk vs "
                f"{rec['crc32']:#010x} in the manifest"
            )
    return _lineage.lineage_problem(path, meta)


def latest_checkpoint(
    directory: str | os.PathLike, *, verify: bool = True
) -> str | None:
    """Newest VALID checkpoint directory under ``directory``, or None.

    Walks generations newest-first: a generation failing
    `verify_checkpoint` (torn, bit-flipped, missing shards) is reported to
    stderr and SKIPPED, falling back to the next older one — the newest
    generation being damaged must degrade a restart by one checkpoint
    interval, not poison it.  ``verify=False`` restores the cheap
    marker-only scan (format-1 semantics) for callers that only need the
    newest published path.

    Every verifying walk publishes the ``checkpoint.fallback_depth`` gauge
    (generations skipped before the winner — 0 on a healthy pick), so the
    supervisor and ``igg_top`` can tell a healthy restart from one limping
    on old state without replaying the event log.
    """
    skipped = 0
    for step, path in reversed(checkpoint_steps(directory)):
        if not verify:
            return path
        problem = verify_checkpoint(path)
        if problem is None:
            _telemetry.gauge("checkpoint.fallback_depth").set(skipped)
            if skipped:
                _telemetry.event(
                    "checkpoint.fallback_depth", depth=skipped, path=path
                )
            return path
        skipped += 1
        _telemetry.event("checkpoint.fallback", path=path, problem=problem)
        _telemetry.counter("checkpoint.fallbacks").inc()
        print(
            f"[igg.checkpoint] skipping invalid checkpoint {path}: {problem} "
            f"(falling back to the previous generation)",
            file=sys.stderr,
            flush=True,
        )
    return None


def checkpoint_meta(path: str | os.PathLike) -> dict:
    """Read a checkpoint's ``meta.json`` (raises if incomplete/missing)."""
    meta_path = os.path.join(os.fspath(path), _META)
    try:
        with open(meta_path) as f:
            return json.load(f)
    except FileNotFoundError:
        raise FileNotFoundError(
            f"No complete checkpoint at {os.fspath(path)!r} (missing "
            f"{_META}; was the save interrupted?)."
        )


def restore_checkpoint(
    path: str | os.PathLike,
    *,
    like: Sequence | None = None,
    strict: bool = False,
    verify: bool = True,
) -> tuple[tuple, int, dict]:
    with _tracing.trace_span("igg.checkpoint.restore", path=os.fspath(path)):
        return _restore_checkpoint(path, like=like, strict=strict,
                                   verify=verify)


def _restore_checkpoint(
    path: str | os.PathLike,
    *,
    like: Sequence | None = None,
    strict: bool = False,
    verify: bool = True,
) -> tuple[tuple, int, dict]:
    """Restore ``(state, step, extra)`` from a checkpoint directory.

    Requires an initialized grid.  When the current topology matches the
    save exactly (dims, local sizes, overlaps, periods, process count and
    device-to-process layout), each process reads only its own shard file —
    bit-exact for every dtype, the per-process memory bound.  Otherwise the
    ELASTIC path engages (unless ``strict=True``): the target topology is
    validated admissible (same implied ``nxyz_g`` and periodicity,
    `parallel.grid.elastic_topology_error`), each field's de-duplicated
    global array is assembled from the saved per-block offsets and
    re-sliced under the current grid's sharding — also bit-exact, since
    every target cell is a byte copy of its owning saved block's cell.

    ``verify=True`` (default) replays the manifest CRCs first; a damaged
    checkpoint raises instead of restoring garbage (use `latest_checkpoint`
    to fall back to the newest valid generation).  ``like`` supplies the
    target arrays' shardings (and validates shapes).
    """
    import jax

    _grid.check_initialized()
    gg = _grid.global_grid()
    path = os.fspath(path)
    meta = checkpoint_meta(path)
    if meta.get("format") not in READABLE_FORMATS:
        raise ValueError(
            f"Checkpoint {path!r} has format {meta.get('format')!r}; this "
            f"build reads formats {READABLE_FORMATS}."
        )
    if verify:
        problem = verify_checkpoint(path)
        if problem is not None:
            _telemetry.event(
                "checkpoint.verify_failed", path=path, problem=problem
            )
            raise ValueError(
                f"Checkpoint {path!r} failed integrity verification: "
                f"{problem}. Use latest_checkpoint() to fall back to the "
                f"newest valid generation."
            )
    saved_grid = meta["grid"]
    current = gg.checkpoint_meta()
    mismatch = [k for k in _MATCH_KEYS if saved_grid.get(k) != current[k]]
    pid = jax.process_index()
    same_procs = meta["process_count"] == jax.process_count()
    shard_path = os.path.join(path, _shard_name(pid))
    if like is not None and len(tuple(like)) != meta["nfields"]:
        raise ValueError(
            f"Checkpoint {path!r} holds {meta['nfields']} field(s) but "
            f"`like` has {len(tuple(like))}."
        )

    if strict:
        if mismatch:
            detail = ", ".join(
                f"{k}: checkpoint {saved_grid.get(k)} vs current {current[k]}"
                for k in mismatch
            )
            raise ValueError(
                f"Checkpoint {path!r} was written for a different grid "
                f"topology ({detail}). Re-init the global grid with the same "
                f"local sizes and dims to restore it (or drop strict=True "
                f"for an elastic restore)."
            )
        if not same_procs:
            raise ValueError(
                f"Checkpoint {path!r} was written by {meta['process_count']} "
                f"process(es) but this job runs {jax.process_count()}; restart "
                f"with the same process count (or drop strict=True for an "
                f"elastic restore)."
            )

    if not mismatch and same_procs and os.path.isfile(shard_path):
        try:
            return _restore_same_topology(path, meta, gg, like)
        except KeyError:
            # Same topology and process count but a different
            # device-to-process layout: this process's shard file lacks a
            # block it now needs.  Strict keeps the original error; the
            # elastic path below reassembles from all shard files.
            if strict:
                raise
    elif strict:
        raise FileNotFoundError(
            f"Checkpoint {path!r} has no shard file for process {pid} "
            f"({shard_path}); it was written by a different process layout."
        )
    return _restore_elastic(path, meta, gg, like)


restore_checkpoint.__doc__ = _restore_checkpoint.__doc__


def _restore_same_topology(path, meta, gg, like):
    """The bit-exact fast path: this process reads only its own shard file."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P, SingleDeviceSharding

    pid = jax.process_index()
    shard_path = os.path.join(path, _shard_name(pid))
    npz = np.load(shard_path)

    state = []
    for i, fmeta in enumerate(meta["fields"]):
        gshape = tuple(fmeta["global_shape"])
        dtype = _dtype_from_str(fmeta["dtype"])
        if like is not None:
            sharding = tuple(like)[i].sharding
            if tuple(tuple(like)[i].shape) != gshape:
                raise ValueError(
                    f"Checkpoint field {i} has global shape {gshape} but "
                    f"`like[{i}]` has {tuple(tuple(like)[i].shape)}."
                )
        elif gg.nprocs == 1 and not gg.force_spmd:
            sharding = SingleDeviceSharding(gg.mesh.devices.flat[0])
        else:
            sharding = NamedSharding(gg.mesh, P(*AXIS_NAMES[: len(gshape)]))

        prefix = f"f{i}_o"

        def lookup(index, i=i, gshape=gshape, dtype=dtype, prefix=prefix):
            starts = _index_starts(index, gshape)
            key = prefix + "_".join(map(str, starts))
            if key not in npz:
                raise KeyError(
                    f"Checkpoint {path!r} shard file for process {pid} has "
                    f"no block at offsets {starts} for field {i}; the "
                    f"device-to-process layout changed since the save."
                )
            shape = tuple(int(s) for s in npz[key + "_shape"])
            return npz[key].view(dtype).reshape(shape)

        state.append(jax.make_array_from_callback(gshape, sharding, lookup))
    _telemetry.event(
        "checkpoint.restore",
        mode="same_topology",
        step=int(meta["step"]),
        path=path,
    )
    _telemetry.counter("checkpoint.restores").inc()
    return tuple(state), int(meta["step"]), meta.get("extra", {})


def _saved_shard_files(path: str, meta: dict) -> list[str]:
    """Every shard file of a checkpoint (manifest-driven for format 2, so
    stray files from crashed earlier attempts cannot pollute an assembly)."""
    shards = meta.get("shards")
    if shards is not None:
        return [os.path.join(path, name) for name in sorted(shards)]
    return sorted(_glob.glob(os.path.join(path, "shards_p*.npz")))


def _restore_elastic(path, meta, gg, like):
    """Reshard-on-restore: reassemble each field's de-duplicated global
    array from the saved per-block offsets (every shard file) and re-slice
    it under the current grid's sharding."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P, SingleDeviceSharding

    from ..ops import gather as _gather
    from ..parallel.grid import elastic_topology_error

    saved_grid = meta["grid"]
    err = elastic_topology_error(saved_grid, gg.checkpoint_meta())
    if err is not None:
        raise ValueError(
            f"Checkpoint {path!r} cannot be elastically restored on the "
            f"current grid: {err}."
        )
    npzs = [np.load(p) for p in _saved_shard_files(path, meta)]
    if not npzs:
        raise FileNotFoundError(
            f"Checkpoint {path!r} has no shard files to reassemble from."
        )
    nxyz_s = tuple(saved_grid["nxyz"])
    over_s = tuple(saved_grid["overlaps"])
    periods = tuple(saved_grid["periods"])
    replicated_target = (
        SingleDeviceSharding(gg.mesh.devices.flat[0])
        if gg.nprocs == 1 and not gg.force_spmd
        else NamedSharding(gg.mesh, P())
    )

    state = []
    for i, fmeta in enumerate(meta["fields"]):
        gshape = tuple(fmeta["global_shape"])
        dtype = _dtype_from_str(fmeta["dtype"])
        prefix = f"f{i}_o"
        blocks: dict[tuple[int, ...], np.ndarray] = {}
        bshape = None
        for npz in npzs:
            for key in npz.files:
                if not key.startswith(prefix) or key.endswith("_shape"):
                    continue
                starts = tuple(int(s) for s in key[len(prefix):].split("_"))
                shape = tuple(int(s) for s in npz[key + "_shape"])
                if bshape is None:
                    bshape = shape
                elif shape != bshape:
                    raise ValueError(
                        f"Checkpoint {path!r} field {i} has blocks of "
                        f"differing shapes ({bshape} vs {shape}); cannot "
                        f"reassemble."
                    )
                coords = tuple(s // b for s, b in zip(starts, shape))
                if coords in blocks:
                    continue  # replicated block: every copy is identical
                blocks[coords] = npz[key].view(dtype).reshape(shape)
        if not blocks:
            raise ValueError(
                f"Checkpoint {path!r} holds no blocks for field {i}."
            )

        if bshape == gshape and (
            like is None or tuple(tuple(like)[i].shape) == gshape
        ):
            # Fully replicated field — or a grid field whose SAVED grid had
            # one block per dim and whose target keeps the same extents:
            # either way one block IS the global value.  A one-block GRID
            # field headed for a decomposed target (`like` with a different
            # shape — the scale-UP restore) falls through to the
            # reassembly path below, which duplicates the new overlap
            # regions the one-block layout never stored twice.
            block = blocks[(0,) * len(gshape)]
            sharding = (
                tuple(like)[i].sharding if like is not None else replicated_target
            )
            state.append(
                jax.make_array_from_callback(
                    gshape, sharding, lambda index, b=block: b[index]
                )
            )
            continue

        ndim = len(gshape)
        nblocks = tuple(g // b for g, b in zip(gshape, bshape))
        if len(blocks) != int(np.prod(nblocks)):
            raise ValueError(
                f"Checkpoint {path!r} field {i}: expected "
                f"{int(np.prod(nblocks))} blocks ({nblocks} per dim), found "
                f"{len(blocks)} across {len(npzs)} shard file(s); the "
                f"checkpoint is incomplete."
            )
        # Leading non-grid axes (a batched serving pool's ensemble axis B,
        # `models._batched`): replicated across the mesh, so every block
        # spans the full extent.  They participate in the reassembly as
        # degenerate grid dims — 1 block, overlap 0, aperiodic — which
        # makes every formula below collapse to the identity on them.
        lead = max(0, ndim - len(nxyz_s))
        if lead and bshape[:lead] != gshape[:lead]:
            raise ValueError(
                f"Checkpoint {path!r} field {i}: leading axis extents "
                f"{bshape[:lead]} per block vs {gshape[:lead]} global — "
                f"only UNSHARDED leading (batch) axes are elastically "
                f"restorable."
            )
        nxyz_sf = bshape[:lead] + nxyz_s
        over_sf = (0,) * lead + over_s
        periods_f = (0,) * lead + periods
        nxyz_tf = bshape[:lead] + tuple(gg.nxyz)
        over_tf = (0,) * lead + tuple(gg.overlaps)
        dims_tf = (1,) * lead + tuple(gg.dims)
        # Per-dim overlap of THIS field under the saved grid (shape-aware:
        # staggered n+1 fields carry overlap+1), then the de-dup extent.
        ols_s = tuple(bshape[d] - (nxyz_sf[d] - over_sf[d]) for d in range(ndim))
        if any(o < 0 for o in ols_s):
            raise ValueError(
                f"Checkpoint {path!r} field {i} (local shape {bshape}) does "
                f"not follow the halo size convention (negative overlap "
                f"{ols_s}); elastic restore cannot reassemble it."
            )
        glens = tuple(
            _gather.dedup_length(nblocks[d], bshape[d], ols_s[d], bool(periods_f[d]))
            for d in range(ndim)
        )
        glob = _gather.assemble_dedup(
            blocks, bshape, nblocks, ols_s, periods_f[:ndim], dtype
        )

        # Target layout: the field keeps its stagger offset relative to the
        # grid's local size (e.g. a +1-staggered Vx stays +1-staggered).
        tshape = tuple(
            nxyz_tf[d] + (bshape[d] - nxyz_sf[d]) for d in range(ndim)
        )
        ols_t = tuple(
            tshape[d] - (nxyz_tf[d] - over_tf[d]) for d in range(ndim)
        )
        if any(o < 0 for o in ols_t) or any(s < 1 for s in tshape):
            raise ValueError(
                f"Checkpoint {path!r} field {i}: target local shape {tshape} "
                f"(overlaps {ols_t}) is not realizable on the current grid."
            )
        glens_t = tuple(
            _gather.dedup_length(dims_tf[d], tshape[d], ols_t[d], bool(periods_f[d]))
            for d in range(ndim)
        )
        if glens_t != glens:
            raise ValueError(
                f"Checkpoint {path!r} field {i}: de-duplicated global extent "
                f"{glens} under the save does not match {glens_t} under the "
                f"current grid."
            )
        new_gshape = tuple(dims_tf[d] * tshape[d] for d in range(ndim))
        if like is not None:
            sharding = tuple(like)[i].sharding
            if tuple(tuple(like)[i].shape) != new_gshape:
                raise ValueError(
                    f"Checkpoint field {i} reshards to global shape "
                    f"{new_gshape} on the current grid but `like[{i}]` has "
                    f"{tuple(tuple(like)[i].shape)}."
                )
        elif gg.nprocs == 1 and not gg.force_spmd:
            sharding = SingleDeviceSharding(gg.mesh.devices.flat[0])
        else:
            sharding = NamedSharding(
                gg.mesh, P(*((None,) * lead + AXIS_NAMES[: ndim - lead]))
            )

        def lookup(index, glob=glob, tshape=tshape, ols_t=ols_t, glens=glens,
                   new_gshape=new_gshape):
            starts = _index_starts(index, new_gshape)
            idxs = [
                _gather.dedup_indices(
                    starts[d] // tshape[d], 0, tshape[d], tshape[d], ols_t[d],
                    glens[d],
                )
                for d in range(len(tshape))
            ]
            return glob[np.ix_(*idxs)]

        state.append(jax.make_array_from_callback(new_gshape, sharding, lookup))
        del glob
    # The RESHARD marker of the failover timeline: a restore that crossed
    # topologies (different dims / process count / device layout).
    _telemetry.event(
        "checkpoint.restore",
        mode="elastic",
        step=int(meta["step"]),
        path=path,
        saved_dims=list(saved_grid["dims"]),
        current_dims=list(gg.dims),
    )
    _telemetry.counter("checkpoint.restores").inc()
    _telemetry.counter("checkpoint.elastic_restores").inc()
    return tuple(state), int(meta["step"]), meta.get("extra", {})


def prune_checkpoints(
    directory: str | os.PathLike, *, keep: int = 2, protect_valid: bool = True
) -> list[str]:
    """Delete all but the newest ``keep`` checkpoints (process 0 only; other
    ranks no-op).  Returns the removed paths.

    ``protect_valid`` (default): pruning refuses to delete the only
    integrity-verified generation — if none of the ``keep`` newest pass
    `verify_checkpoint`, the newest VALID older generation is retained too,
    so retention can never destroy the last restorable state.
    """
    import jax

    if keep < 1:
        raise ValueError(f"keep must be >= 1 (got {keep})")
    if jax.process_index() != 0:
        return []
    complete = checkpoint_steps(directory)
    doomed = complete[:-keep]
    if protect_valid and doomed:
        # Newest-first: on the hot cadence (RunGuard prunes right after a
        # save) the first candidate is the just-published generation — one
        # warm CRC pass short-circuits the scan in the all-healthy case.
        kept = complete[-keep:]
        if not any(verify_checkpoint(p) is None for _, p in reversed(kept)):
            for entry in reversed(doomed):
                if verify_checkpoint(entry[1]) is None:
                    doomed.remove(entry)
                    print(
                        f"[igg.checkpoint] prune: keeping {entry[1]} — it is "
                        f"the only generation passing integrity verification",
                        file=sys.stderr,
                        flush=True,
                    )
                    break
    removed = []
    for _, path in doomed:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    if removed:
        _telemetry.event("checkpoint.prune", removed=removed, keep=keep)
        _telemetry.counter("checkpoint.prunes").inc(len(removed))
    return removed
