"""Cross-rank observability plane: host spans, merged timelines, straggler
detection and the crash flight recorder.

The telemetry layer (`utils.telemetry`, PR 4) is strictly process-local —
each rank keeps its own registry and event log, and nothing ever answers
the questions a *cluster-level* claim (T_eff at scale, weak-scaling
efficiency) actually raises: where did rank 3's step time go, which rank is
the straggler, what was in flight when the run died.  This module is the
cross-rank half (docs/observability.md):

* **Spans** — `trace_span("igg.step", step=n)` is a nestable host-side
  context manager recording ``(name, t0, dur, tags)`` into a bounded
  per-process ring buffer (``IGG_TRACE_RING``, default `RING_DEFAULT`).
  Span names reuse the compiled-HLO annotation names where one exists
  (``igg_halo_exchange``, ``igg_slab_exchange_begin`` ... — see
  `utils.compat.named_scope`), so a host span and the device ops it
  dispatched correlate BY NAME across a merged trace and a profiler
  capture.  With ``IGG_TELEMETRY=0`` (or ``IGG_TRACE_RING=0``) every call
  returns one shared no-op singleton — no allocation, no clock reads.
* **Merged timeline** — `dump_trace(dir)` writes this rank's spans plus its
  clock-sync anchor as ``trace.p<rank>.json``; `merge_trace_files` joins
  any set of per-rank files into ONE valid Chrome-trace/Perfetto JSON with
  one track (pid) per rank on a shared clock.  Cross-rank alignment comes
  from the barrier-timestamped sync `record_clock_sync` takes at
  `init_global_grid`: every rank leaves the same barrier at (approximately)
  the same true instant, so per-rank ``perf_counter`` readings taken right
  at barrier exit anchor one common time zero.  The *honesty bound*: ranks
  do not exit a barrier simultaneously — the alignment error is bounded by
  each rank's measured barrier duration (microseconds on ICI, up to
  milliseconds on slow fabrics), and the merged trace records the per-rank
  offset AND that uncertainty in its metadata rather than pretending ns
  precision.
* **Straggler detection** — `skew_probe(step_seconds)` shares each rank's
  last-window mean step wall time with every other rank through ONE tiny
  replicated collective (the same scatter/psum shape as
  `resilience.check_fields`' probe and the chunked gather's block fetch —
  host-dispatched at heartbeat cadence, never inside the step program) and
  publishes ``skew.step_seconds_max_over_min`` / ``skew.slowest_rank``
  gauges plus a rank-tagged ``skew.straggler`` event when the ratio
  exceeds ``IGG_SKEW_WARN``.  Single-process grids skip the probe
  entirely.  The probe is a COLLECTIVE: every process must call it at the
  same cadence (the step-count cadence guarantees that), and ranks must
  agree on ``IGG_TELEMETRY`` / ``IGG_HEARTBEAT_EVERY`` or the others hang
  waiting — same contract as every other collective in the package.
* **Flight recorder** — `dump_flight_recorder(reason, ...)` bundles the
  span ring, the current metrics snapshot and the active config into ONE
  crash-safe ``flight_<rank>.json`` line (single ``O_APPEND`` ``os.write``,
  the event-log discipline: complete lines or nothing, even through an
  ``os._exit`` right after).  `utils.resilience` calls it on a guard trip,
  a watchdog deadline and an injected worker crash.

Layering: imports only `config` and `telemetry` at module scope; jax and
the grid are reached lazily so the module stays importable in a broken
accelerator env (the flight recorder is most valuable exactly then).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Sequence

from . import config as _config
from . import telemetry as _telemetry

__all__ = [
    "trace_span",
    "span_records",
    "span_summary",
    "open_spans",
    "span_stats",
    "record_clock_sync",
    "clock_sync",
    "dump_trace",
    "merge_trace_files",
    "validate_chrome_trace",
    "skew_probe",
    "arm_collective_delay",
    "dump_flight_recorder",
    "reset",
]

#: default span ring capacity (``IGG_TRACE_RING`` overrides; 0 disables).
#: 4096 spans ≈ a few hundred KB — bounded however long the run.
RING_DEFAULT = 4096

#: per-rank trace file schema version (`dump_trace` / `merge_trace_files`)
TRACE_SCHEMA = 1


def _ring_capacity() -> int:
    val = _config.trace_ring_env()
    return RING_DEFAULT if val is None else val


def enabled() -> bool:
    """Span recording is on: telemetry master switch AND a nonzero ring."""
    return _telemetry.enabled() and _ring_capacity() > 0


# -- the span ring ------------------------------------------------------------

_lock = threading.Lock()
_ring: collections.deque | None = None
_ring_cap = 0


def _get_ring(cap: int) -> collections.deque:
    """The process ring, re-bounded when ``IGG_TRACE_RING`` changed."""
    global _ring, _ring_cap
    with _lock:
        if _ring is None or _ring_cap != cap:
            _ring = collections.deque(_ring, maxlen=cap) if _ring else \
                collections.deque(maxlen=cap)
            _ring_cap = cap
        return _ring


# Per-thread stacks of the spans currently EXECUTING — the spans a crash
# bundle most wants (the closed-span ring by definition misses them) and
# what the live plane's ``/spans`` endpoint shows as in-flight.  Keyed by
# thread ident; list append/pop are GIL-atomic, so enter/exit pay no lock.
_open_stacks: dict[int, list] = {}


def open_spans() -> list[dict]:
    """Every thread's currently-open spans, innermost last, each marked
    ``open: true`` with its age-so-far as ``dur`` (readers must not
    mistake an in-flight span for a completed one)."""
    now = time.perf_counter()
    out = []
    for ident, stack in list(_open_stacks.items()):
        for name, t0, tags in list(stack):
            rec = {
                "name": name,
                "t0": t0,
                "dur": now - t0,
                "open": True,
                "thread": ident,
            }
            if tags:
                rec["args"] = tags
            out.append(rec)
    return out


class _Span:
    """One live span.  Records itself into the ring on exit; re-entrant
    use records one span per enter/exit pair.  While executing it sits on
    this thread's open-span stack (see `open_spans`)."""

    __slots__ = ("name", "tags", "t0")

    def __init__(self, name: str, tags: dict | None):
        self.name = name
        self.tags = tags

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter()
        ident = threading.get_ident()
        stack = _open_stacks.get(ident)
        if stack is None:
            stack = _open_stacks[ident] = []
        stack.append((self.name, self.t0, self.tags))
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        ident = threading.get_ident()
        stack = _open_stacks.get(ident)
        if stack:
            stack.pop()
            if not stack:
                _open_stacks.pop(ident, None)  # no thread-lifetime leak
        _get_ring(_ring_capacity()).append(
            (self.name, self.t0, t1 - self.t0, self.tags)
        )


class _NoopSpan:
    """Shared disabled-mode singleton (identity-stable, like
    `telemetry.NOOP`): no clock reads, no allocation."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def trace_span(name: str, **tags: Any):
    """A host-side span context manager recording into the process ring.

    Nestable (Chrome-trace ``X`` events on one track render nesting from
    containment); tags become the span's ``args`` in a merged trace.
    Returns the shared `NOOP_SPAN` when tracing is disabled — the
    zero-overhead contract of the rest of the registry.
    """
    if not enabled():
        return NOOP_SPAN
    return _Span(name, tags or None)


def span_records() -> list[dict]:
    """The current ring as plain dicts (oldest first; test/dump hook)."""
    with _lock:
        items = list(_ring) if _ring else []
    return [
        {"name": n, "t0": t0, "dur": dur, **({"args": tags} if tags else {})}
        for n, t0, dur, tags in items
    ]


def span_summary() -> dict:
    """``{span name: {count, total_s, mean_s, max_s}}`` over the ring —
    the aggregate view `bench.py` ships in its artifact."""
    agg: dict[str, list] = {}
    with _lock:
        items = list(_ring) if _ring else []
    for name, _t0, dur, _tags in items:
        rec = agg.setdefault(name, [0, 0.0, 0.0])
        rec[0] += 1
        rec[1] += dur
        rec[2] = max(rec[2], dur)
    return {
        name: {
            "count": c,
            "total_s": total,
            "mean_s": total / c,
            "max_s": mx,
        }
        for name, (c, total, mx) in sorted(agg.items())
    }


def span_stats(span_lists: Sequence[Sequence[dict]]) -> dict:
    """``{span name: {count, total_s, mean_s, p50_s, p99_s, max_s}}`` over
    any number of span-record lists (the `span_records` / ``trace.p*.json``
    schema) — the aggregation behind ``scripts/igg_trace.py summarize``.
    Quantiles are nearest-rank over ALL matching spans' durations (no
    reservoir: a dump is already bounded by the ring).  Open spans
    (``open: true``) are excluded — their durations are ages, not totals.
    """
    durs: dict[str, list[float]] = {}
    for spans in span_lists:
        for s in spans:
            if s.get("open"):
                continue
            durs.setdefault(s["name"], []).append(float(s["dur"]))
    out = {}
    for name in sorted(durs):
        ds = sorted(durs[name])
        n = len(ds)

        def q(frac: float) -> float:
            return ds[min(n - 1, max(0, round(frac * (n - 1))))]

        out[name] = {
            "count": n,
            "total_s": sum(ds),
            "mean_s": sum(ds) / n,
            "p50_s": q(0.50),
            "p99_s": q(0.99),
            "max_s": ds[-1],
        }
    return out


# -- clock sync ---------------------------------------------------------------

# The barrier-timestamped anchor (set once per grid epoch by
# `record_clock_sync`): {"wall", "perf", "uncertainty_s", "epoch",
# "barrier": bool}.  ``perf`` is this process's perf_counter at barrier
# exit; all ranks' ``perf`` values name (approximately) the same true
# instant, which is what merge alignment uses.
_clock_sync: dict | None = None


def record_clock_sync(barrier_fn=None, *, epoch: int | None = None) -> dict:
    """Take the cross-rank clock-sync sample (called at `init_global_grid`).

    ``barrier_fn`` (multi-process grids): a callable that returns only when
    every process reached it — the ranks' clock samples taken right after
    it anchor one shared instant.  The recorded ``uncertainty_s`` is the
    measured barrier duration: a rank can exit at most one barrier-length
    after the first exiter, so per-rank alignment error is bounded by it
    (document-honest — no ns claims).  Without a barrier (single process)
    the sample is exact by construction (uncertainty 0).
    """
    global _clock_sync
    uncertainty = 0.0
    if barrier_fn is not None:
        tb = time.perf_counter()
        barrier_fn()
        uncertainty = time.perf_counter() - tb
    perf = time.perf_counter()
    wall = time.time()
    _clock_sync = {
        "wall": wall,
        "perf": perf,
        "uncertainty_s": uncertainty,
        "epoch": epoch,
        "barrier": barrier_fn is not None,
    }
    _telemetry.event(
        "clock.sync",
        wall=wall,
        perf=perf,
        uncertainty_s=uncertainty,
        barrier=barrier_fn is not None,
    )
    return _clock_sync


def clock_sync() -> dict:
    """The active sync anchor; synthesized (``barrier: False``) when no
    grid init ran — the merge then aligns by wall clocks only and says so."""
    if _clock_sync is not None:
        return _clock_sync
    return {
        "wall": time.time(),
        "perf": time.perf_counter(),
        "uncertainty_s": None,
        "epoch": None,
        "barrier": False,
    }


# -- per-rank dump + merge ----------------------------------------------------


def trace_filename(rank: int) -> str:
    return f"trace.p{rank}.json"


def dump_trace(directory: str | os.PathLike | None = None) -> str | None:
    """Write this rank's span file (``trace.p<rank>.json``) into
    ``directory`` (default ``IGG_TELEMETRY_DIR``).  Returns the path, or
    None when telemetry is disabled / no directory resolves.  Exported as
    ``igg.dump_trace``; merge any set of ranks' files with
    ``scripts/igg_trace.py merge`` (or `merge_trace_files`)."""
    if not _telemetry.enabled():
        return None
    directory = os.fspath(directory) if directory else _config.telemetry_dir_env()
    if not directory:
        return None
    rank = _telemetry._proc_index()
    doc = {
        "schema": TRACE_SCHEMA,
        "rank": rank,
        "pid": os.getpid(),
        "coords": _telemetry._grid_coords(),
        "clock_sync": clock_sync(),
        "spans": span_records(),
    }
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, trace_filename(rank))
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
    return path


def _load_rank_trace(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != TRACE_SCHEMA:
        raise ValueError(
            f"{path}: unsupported trace schema {doc.get('schema')!r} "
            f"(expected {TRACE_SCHEMA})."
        )
    if "rank" not in doc or "spans" not in doc or "clock_sync" not in doc:
        raise ValueError(f"{path}: not a per-rank trace file (missing keys).")
    return doc


#: max wall-clock disagreement (s) between two ranks' barrier-exit samples
#: before the merge refuses to treat them as the SAME barrier.  Same-run
#: samples differ by barrier-exit skew + NTP skew (well under a second);
#: anything bigger means the files come from different runs — the classic
#: stale-dump-in-a-reused-IGG_TELEMETRY_DIR hazard.
BARRIER_WALL_TOL_S = 2.0


def merge_trace_files(paths: Sequence[str | os.PathLike]) -> dict:
    """Join per-rank span files into one Chrome-trace/Perfetto JSON object.

    One track (pid) per rank; ``X`` (complete) events carry the span tags
    as ``args``.  Alignment: the lowest rank is the anchor — its
    barrier-exit wall time defines the absolute axis, and every rank's
    spans shift by ``(own perf at barrier exit)`` so all tracks share the
    barrier instant as time zero.  The per-rank offset and its uncertainty
    (the measured barrier duration — the honesty bound on cross-rank
    ordering) land in ``otherData.clock_alignment``; a rank whose sync was
    not barrier-anchored (``barrier: false``) is aligned by wall clock
    instead and flagged, since nothing ties its perf counter to the
    others'.  Events are sorted by (pid, ts), so each track's timestamps
    are monotonic — the tier-1 validity pin.

    Barrier-anchored inputs must describe the SAME barrier, or the merged
    "aligned" clock is a lie: the merge refuses files whose grid epochs
    differ or whose barrier-exit wall samples disagree by more than
    `BARRIER_WALL_TOL_S` (a stale ``trace.p*.json`` from a previous run
    left in a reused telemetry dir is exactly this shape — delete it, or
    pass the current run's files explicitly).
    """
    docs = sorted(
        (_load_rank_trace(os.fspath(p)) for p in paths),
        key=lambda d: d["rank"],
    )
    if not docs:
        raise ValueError("merge_trace_files: no per-rank trace files given.")
    ranks = [d["rank"] for d in docs]
    if len(set(ranks)) != len(ranks):
        raise ValueError(
            f"merge_trace_files: duplicate rank(s) in inputs ({ranks}) — "
            f"each rank contributes exactly one file."
        )
    anchor = docs[0]["clock_sync"]
    for doc in docs[1:]:
        sync = doc["clock_sync"]
        if not (sync.get("barrier") and anchor.get("barrier")):
            continue  # wall-aligned below, flagged — no same-barrier claim
        wall_delta = abs(sync["wall"] - anchor["wall"])
        if (
            sync.get("epoch") != anchor.get("epoch")
            or wall_delta > BARRIER_WALL_TOL_S
        ):
            raise ValueError(
                f"merge_trace_files: rank {doc['rank']}'s barrier anchor "
                f"does not match rank {docs[0]['rank']}'s (epoch "
                f"{sync.get('epoch')} vs {anchor.get('epoch')}, barrier "
                f"wall samples {wall_delta:.1f}s apart > "
                f"{BARRIER_WALL_TOL_S}s) — the files describe different "
                f"runs/barriers and cannot share an aligned clock.  A "
                f"stale trace.p*.json from a previous run in a reused "
                f"telemetry dir looks exactly like this: delete it, or "
                f"merge the current run's files explicitly."
            )
    events: list[dict] = []
    alignment: dict[str, Any] = {
        "anchor_rank": docs[0]["rank"],
        "anchor_wall_unix_s": anchor["wall"],
        "note": (
            "per-rank perf_counter timelines are aligned on the barrier "
            "instant recorded at init_global_grid; cross-rank ordering is "
            "trustworthy only beyond each rank's uncertainty_s (the "
            "measured barrier duration) — wall-clock-aligned ranks "
            "(barrier_aligned=false) carry whatever NTP skew the hosts "
            "have."
        ),
        "per_rank": {},
    }
    for doc in docs:
        sync = doc["clock_sync"]
        barrier_aligned = bool(sync.get("barrier")) and bool(
            anchor.get("barrier")
        )
        if barrier_aligned:
            # span perf t -> seconds since the shared barrier instant.
            offset = -sync["perf"]
        else:
            # No shared barrier: fall back to wall-clock alignment, re-based
            # so the anchor rank's barrier (or sample) instant is still zero.
            offset = (sync["wall"] - anchor["wall"]) - sync["perf"]
        alignment["per_rank"][str(doc["rank"])] = {
            "barrier_aligned": barrier_aligned,
            "offset_s": offset,
            "uncertainty_s": sync.get("uncertainty_s"),
            "wall_at_sync_unix_s": sync.get("wall"),
        }
        pid = doc["rank"]
        coords = doc.get("coords")
        name = f"rank {pid}" + (f" coords {tuple(coords)}" if coords else "")
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": name},
            }
        )
        for s in doc["spans"]:
            ev = {
                "ph": "X",
                "name": s["name"],
                "pid": pid,
                "tid": 0,
                "ts": (s["t0"] + offset) * 1e6,
                "dur": s["dur"] * 1e6,
            }
            if s.get("args"):
                ev["args"] = s["args"]
            events.append(ev)
    # Re-base so the earliest event sits at ts=0 (viewers dislike huge or
    # negative timestamps); the absolute anchor lives in the metadata.
    xs = [e["ts"] for e in events if e["ph"] == "X"]
    base = min(xs) if xs else 0.0
    for e in events:
        if e["ph"] == "X":
            e["ts"] -= base
    alignment["ts_zero_offset_s"] = base / 1e6
    events.sort(key=lambda e: (e["pid"], e.get("ts", -1.0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"clock_alignment": alignment},
    }


def validate_chrome_trace(doc: dict) -> list[str]:
    """Problems with a merged trace (empty list = valid): the tier-1 /
    soak check that the artifact really is loadable Chrome-trace JSON with
    per-track monotonic timestamps and alignment metadata.  NaN/inf
    timestamps are rejected explicitly — Python's json writes them but
    strict parsers (and the trace viewers) refuse the artifact, and a NaN
    would additionally sail through the monotonicity comparison."""
    import math

    problems: list[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is missing or not a list"]
    last_ts: dict[Any, float] = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e or "pid" not in e:
            problems.append(f"event {i} malformed: {e!r}")
            continue
        if e["ph"] != "X":
            continue
        for key in ("name", "ts", "dur"):
            if key not in e:
                problems.append(f"event {i} missing {key!r}")
        ts = e.get("ts")
        if (
            not isinstance(ts, (int, float))
            or not math.isfinite(ts)
            or ts < 0
        ):
            problems.append(f"event {i} has non-finite/negative ts {ts!r}")
            continue
        dur = e.get("dur")
        if isinstance(dur, (int, float)) and (
            not math.isfinite(dur) or dur < 0
        ):
            problems.append(f"event {i} has non-finite/negative dur {dur!r}")
        if ts < last_ts.get(e["pid"], float("-inf")):
            problems.append(
                f"event {i} breaks track pid={e['pid']} monotonicity "
                f"({ts} after {last_ts[e['pid']]})"
            )
        last_ts[e["pid"]] = ts
    if "clock_alignment" not in doc.get("otherData", {}):
        problems.append("otherData.clock_alignment metadata missing")
    return problems


# -- straggler detection ------------------------------------------------------

#: default ``IGG_SKEW_WARN`` threshold on max/min per-rank step seconds
SKEW_WARN_DEFAULT = 2.0

_skew_cache: dict = {}


def _clear_caches() -> None:
    _skew_cache.clear()


def _skew_fn(gg):
    """The jitted all-ranks share of one host scalar per block: the same
    scatter-into-one-hot + all-axes psum shape as `resilience.check_fields`
    and the chunked gather's block fetch (`ops.gather._block_fetch_fn`) —
    the one collective pattern proven on every supported transport.  The
    result is a tiny replicated ``dims``-shaped array every process reads
    host-side."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES, NDIMS
    from .compat import shard_map

    key = gg.epoch
    fn = _skew_cache.get(key)
    if fn is not None:
        return fn

    def per_block(x):
        onehot = jnp.zeros(tuple(gg.dims), jnp.float32)
        coords = tuple(
            lax.axis_index(AXIS_NAMES[d]) if gg.dims[d] > 1 else jnp.int32(0)
            for d in range(NDIMS)
        )
        onehot = lax.dynamic_update_slice(
            onehot, x.astype(jnp.float32).reshape((1, 1, 1)), coords
        )
        return lax.psum(onehot, AXIS_NAMES)

    mapped = shard_map(
        per_block,
        mesh=gg.mesh,
        in_specs=P(*AXIS_NAMES),
        out_specs=P(),
        check_vma=False,
    )
    fn = jax.jit(mapped)
    _skew_cache[key] = fn
    return fn


#: one-shot latency armed on the next host control collective (the
#: ``net_delay`` fault kind, `utils.resilience`): seconds slept before this
#: process dispatches into `all_ranks_value` — its peers block with it,
#: which is exactly the transient network fault the chaos plane models.
_collective_delay = 0.0


def arm_collective_delay(seconds: float) -> None:
    """Arm one-shot latency on this process's next host control collective
    (consumed by `all_ranks_value` — the skew-probe / `broadcast_control`
    transport).  The fault-injection hook of ``net_delay``."""
    global _collective_delay
    _collective_delay = max(0.0, float(seconds))


def _consume_collective_delay() -> None:
    global _collective_delay
    delay, _collective_delay = _collective_delay, 0.0
    if delay:
        time.sleep(delay)


def all_ranks_value(value: float):
    """Share one host scalar per process with every process.

    Returns the replicated ``dims``-shaped numpy array (one entry per
    block; every block a process owns carries that process's value), or
    None on single-process grids — the probe is strictly a cross-process
    diagnostic.  COLLECTIVE: every process must call it together.
    """
    import jax

    from ..parallel import grid as _grid

    if not _grid.grid_is_initialized() or jax.process_count() == 1:
        return None
    _consume_collective_delay()
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES

    gg = _grid.global_grid()
    sharding = NamedSharding(gg.mesh, P(*AXIS_NAMES))
    arr = jax.make_array_from_callback(
        tuple(gg.dims),
        sharding,
        lambda idx: np.full((1, 1, 1), value, np.float32),
    )
    return np.asarray(_skew_fn(gg)(arr))


def skew_probe(step_seconds: float, *, warn: float | None = None) -> dict | None:
    """One all-ranks skew probe over the last window's step wall time.

    Publishes the ``skew.step_seconds_max_over_min`` and
    ``skew.slowest_rank`` gauges on every rank, fires a rank-tagged
    ``skew.straggler`` event (plus the ``skew.straggler_total`` counter)
    when the ratio exceeds ``warn`` (default ``IGG_SKEW_WARN``, built-in
    `SKEW_WARN_DEFAULT`; 0 disables the event).  Returns the probe result
    dict, or None on single-process grids (skipped entirely — no
    collective, no gauges).  Collective; call at a deterministic cadence
    on every process (the heartbeat cadence of the instrumented loops).
    """
    vals = all_ranks_value(float(step_seconds))
    if vals is None:
        return None
    import numpy as np

    from ..parallel import grid as _grid

    gg = _grid.global_grid()
    vmax = float(np.max(vals))
    vmin = float(np.min(vals))
    ratio = vmax / vmin if vmin > 0 else float("inf") if vmax > 0 else 1.0
    slow_coords = tuple(
        int(c) for c in np.unravel_index(int(np.argmax(vals)), vals.shape)
    )
    slowest_rank = int(gg.mesh.devices[slow_coords].process_index)
    _telemetry.gauge("skew.step_seconds_max_over_min").set(ratio)
    _telemetry.gauge("skew.slowest_rank").set(slowest_rank)
    if warn is None:
        env = _config.skew_warn_env()
        warn = SKEW_WARN_DEFAULT if env is None else env
    result = {
        "ratio": ratio,
        "slowest_rank": slowest_rank,
        "slowest_coords": list(slow_coords),
        "max_s": vmax,
        "min_s": vmin,
        "mine_s": float(step_seconds),
    }
    if warn and ratio > warn:
        _telemetry.counter("skew.straggler_total").inc()
        _telemetry.event("skew.straggler", warn=warn, **result)
    return result


# -- flight recorder ----------------------------------------------------------


def flight_filename(rank: int) -> str:
    return f"flight_{rank}.json"


def _active_config() -> dict:
    """The run's active configuration for a flight bundle: every ``IGG_*``
    env var plus the live grid's identity (when one is up)."""
    cfg: dict[str, Any] = {
        "env": {k: v for k, v in os.environ.items() if k.startswith("IGG_")},
    }
    try:
        from ..parallel import grid as _grid

        if _grid.grid_is_initialized():
            gg = _grid.global_grid()
            cfg["grid"] = {
                "nxyz_g": list(gg.nxyz_g),
                "nxyz": list(gg.nxyz),
                "dims": list(gg.dims),
                "coords": list(gg.coords),
                "periods": list(gg.periods),
                "overlaps": list(gg.overlaps),
                "nprocs": gg.nprocs,
                "me": gg.me,
                "epoch": gg.epoch,
            }
    except Exception:  # the recorder must never raise out of a crash path
        pass
    return cfg


def dump_flight_recorder(reason: str, **info: Any) -> str | None:
    """Dump the crash flight-recorder bundle for this rank.

    One JSON object — ``{ts, reason, rank, pid, coords, info, config,
    metrics, spans}`` — appended as a single ``O_APPEND`` line to
    ``flight_<rank>.json`` under ``IGG_TELEMETRY_DIR`` (several trips
    append several lines; the last line is the newest bundle).  Crash-safe
    by the event-log discipline: the write is one ``os.write`` of a
    complete line, so a hard ``os._exit`` immediately after loses nothing.
    Returns the path, or None when telemetry is off / no directory is set.
    Never raises: a failing recorder must not mask the fault it records.
    """
    try:
        if not _telemetry.enabled():
            return None
        directory = _config.telemetry_dir_env()
        if not directory:
            return None
        rank = _telemetry._proc_index()
        bundle = {
            "ts": time.time(),
            "reason": reason,
            "rank": rank,
            "pid": os.getpid(),
            "coords": _telemetry._grid_coords(),
            "info": info,
            "config": _active_config(),
            "metrics": _telemetry.snapshot(),
            # Closed ring PLUS the spans currently executing (``open:
            # true``, every thread): the span you most want at crash time
            # is the one that was in flight when the run died.
            "spans": span_records() + open_spans(),
        }
        try:
            # An in-flight device capture (utils.profiling): a crash
            # mid-window is explained by its dir/window/step — and the
            # post-mortem knows a partial profiler dir is expected.
            from . import profiling as _profiling

            cap = _profiling.active_capture()
            if cap is not None:
                bundle["profile"] = cap
        except Exception:
            pass
        try:
            line = json.dumps(bundle, default=str) + "\n"
        except (TypeError, ValueError):
            line = json.dumps(
                {k: str(v) for k, v in bundle.items()}
            ) + "\n"
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, flight_filename(rank))
        fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)
        _telemetry.counter("resilience.flight_dumps").inc()
        return path
    except Exception:
        return None


def read_flight_bundles(path: str | os.PathLike) -> list[dict]:
    """Parse one ``flight_<rank>.json`` (one bundle per line, torn trailing
    line skipped — the `telemetry.read_events` contract)."""
    return _telemetry.read_events(path)


def reset() -> None:
    """Drop the span ring, open stacks, clock sync and probe caches
    (test hook)."""
    global _ring, _ring_cap, _clock_sync, _collective_delay
    with _lock:
        _ring = None
        _ring_cap = 0
    _open_stacks.clear()
    _clock_sync = None
    _collective_delay = 0.0
    _skew_cache.clear()
