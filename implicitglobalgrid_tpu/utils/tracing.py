"""Cross-rank observability plane: host spans, merged timelines, straggler
detection and the crash flight recorder.

The telemetry layer (`utils.telemetry`, PR 4) is strictly process-local —
each rank keeps its own registry and event log, and nothing ever answers
the questions a *cluster-level* claim (T_eff at scale, weak-scaling
efficiency) actually raises: where did rank 3's step time go, which rank is
the straggler, what was in flight when the run died.  This module is the
cross-rank half (docs/observability.md):

* **Spans** — `trace_span("igg.step", step=n)` is a nestable host-side
  context manager recording ``(name, t0, dur, tags)`` into a bounded
  per-process ring buffer (``IGG_TRACE_RING``, default `RING_DEFAULT`).
  Span names reuse the compiled-HLO annotation names where one exists
  (``igg_halo_exchange``, ``igg_slab_exchange_begin`` ... — see
  `utils.compat.named_scope`), so a host span and the device ops it
  dispatched correlate BY NAME across a merged trace and a profiler
  capture.  With ``IGG_TELEMETRY=0`` (or ``IGG_TRACE_RING=0``) every call
  returns one shared no-op singleton — no allocation, no clock reads.
* **Merged timeline** — `dump_trace(dir)` writes this rank's spans plus its
  clock-sync anchor as ``trace.p<rank>.json``; `merge_trace_files` joins
  any set of per-rank files into ONE valid Chrome-trace/Perfetto JSON with
  one track (pid) per rank on a shared clock.  Cross-rank alignment comes
  from the barrier-timestamped sync `record_clock_sync` takes at
  `init_global_grid`: every rank leaves the same barrier at (approximately)
  the same true instant, so per-rank ``perf_counter`` readings taken right
  at barrier exit anchor one common time zero.  The *honesty bound*: ranks
  do not exit a barrier simultaneously — the alignment error is bounded by
  each rank's measured barrier duration (microseconds on ICI, up to
  milliseconds on slow fabrics), and the merged trace records the per-rank
  offset AND that uncertainty in its metadata rather than pretending ns
  precision.
* **Straggler detection** — `skew_probe(step_seconds)` shares each rank's
  last-window mean step wall time with every other rank through ONE tiny
  replicated collective (the same scatter/psum shape as
  `resilience.check_fields`' probe and the chunked gather's block fetch —
  host-dispatched at heartbeat cadence, never inside the step program) and
  publishes ``skew.step_seconds_max_over_min`` / ``skew.slowest_rank``
  gauges plus a rank-tagged ``skew.straggler`` event when the ratio
  exceeds ``IGG_SKEW_WARN``.  Single-process grids skip the probe
  entirely.  The probe is a COLLECTIVE: every process must call it at the
  same cadence (the step-count cadence guarantees that), and ranks must
  agree on ``IGG_TELEMETRY`` / ``IGG_HEARTBEAT_EVERY`` or the others hang
  waiting — same contract as every other collective in the package.
* **Request tracing** — spans optionally carry W3C-trace-context identity
  (`new_trace_id` / `parse_traceparent` / `current_context` /
  `use_context`): a context minted (head-sampled, ``IGG_TRACE_SAMPLE``)
  or adopted at the serving/fleet front doors rides the request ledger,
  the control broadcasts and the checkpoint slot metadata, so every
  rank's serving-round / halo-exchange / checkpoint spans under a request
  are tagged with its ``trace_id`` and `request_tree` can rebuild ONE
  causal tree from any set of per-rank/per-pool dumps — across pools,
  generations and re-routes (``scripts/igg_trace.py request``; OTLP/JSON
  export via `otlp_trace`, latency attribution via `critical_path`).
* **Flight recorder** — `dump_flight_recorder(reason, ...)` bundles the
  span ring, the current metrics snapshot and the active config into ONE
  crash-safe ``flight_<rank>.json`` line (single ``O_APPEND`` ``os.write``,
  the event-log discipline: complete lines or nothing, even through an
  ``os._exit`` right after).  `utils.resilience` calls it on a guard trip,
  a watchdog deadline and an injected worker crash.

Layering: imports only `config` and `telemetry` at module scope; jax and
the grid are reached lazily so the module stays importable in a broken
accelerator env (the flight recorder is most valuable exactly then).
"""

from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
from typing import Any, Sequence

from . import config as _config
from . import telemetry as _telemetry

__all__ = [
    "trace_span",
    "record_span",
    "span_records",
    "span_summary",
    "open_spans",
    "span_stats",
    "spans_dropped",
    "new_trace_id",
    "new_span_id",
    "parse_traceparent",
    "format_traceparent",
    "should_sample",
    "current_context",
    "use_context",
    "record_clock_sync",
    "clock_sync",
    "dump_trace",
    "merge_trace_files",
    "validate_chrome_trace",
    "request_tree",
    "request_chrome_trace",
    "critical_path",
    "otlp_trace",
    "validate_otlp",
    "skew_probe",
    "arm_collective_delay",
    "dump_flight_recorder",
    "reset",
]

#: default span ring capacity (``IGG_TRACE_RING`` overrides; 0 disables).
#: 4096 spans ≈ a few hundred KB — bounded however long the run.
RING_DEFAULT = 4096

#: per-rank trace file schema version (`dump_trace` / `merge_trace_files`)
TRACE_SCHEMA = 1


def _ring_capacity() -> int:
    val = _config.trace_ring_env()
    return RING_DEFAULT if val is None else val


def enabled() -> bool:
    """Span recording is on: telemetry master switch AND a nonzero ring."""
    return _telemetry.enabled() and _ring_capacity() > 0


# -- the span ring ------------------------------------------------------------

_lock = threading.Lock()
_ring: collections.deque | None = None
_ring_cap = 0


def _get_ring(cap: int) -> collections.deque:
    """The process ring, re-bounded when ``IGG_TRACE_RING`` changed."""
    global _ring, _ring_cap
    with _lock:
        if _ring is None or _ring_cap != cap:
            _ring = collections.deque(_ring, maxlen=cap) if _ring else \
                collections.deque(maxlen=cap)
            _ring_cap = cap
        return _ring


# Per-thread stacks of the spans currently EXECUTING — the spans a crash
# bundle most wants (the closed-span ring by definition misses them) and
# what the live plane's ``/spans`` endpoint shows as in-flight.  Keyed by
# thread ident; list append/pop are GIL-atomic, so enter/exit pay no lock.
_open_stacks: dict[int, list] = {}


def open_spans() -> list[dict]:
    """Every thread's currently-open spans, innermost last, each marked
    ``open: true`` with its age-so-far as ``dur`` (readers must not
    mistake an in-flight span for a completed one)."""
    now = time.perf_counter()
    out = []
    for ident, stack in list(_open_stacks.items()):
        for name, t0, tags in list(stack):
            rec = {
                "name": name,
                "t0": t0,
                "dur": now - t0,
                "open": True,
                "thread": ident,
            }
            if tags:
                rec["args"] = tags
            out.append(rec)
    return out


# -- request context (W3C trace-context shaped) -------------------------------

# Per-thread stacks of the ACTIVE request context: either one request
# (``{"trace_id", "span_id"}``) or the serving round's multi-request form
# (``{"trace_ids": [...]}`` — one pool round advances MANY requests).
# Same GIL-atomic append/pop discipline as `_open_stacks`.
_ctx_stacks: dict[int, list] = {}

#: spans evicted from the bounded ring since the last `reset` — the
#: silent-truncation ledger every `dump_trace` carries (satellite:
#: a quietly-partial request tree must never look complete)
_spans_dropped = 0


def new_trace_id() -> str:
    """A fresh 128-bit W3C trace id (32 lowercase hex chars)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A fresh 64-bit span id (16 lowercase hex chars)."""
    return os.urandom(8).hex()


def parse_traceparent(header: str | None) -> dict | None:
    """Parse a W3C ``traceparent`` header into ``{"trace_id", "span_id"}``.

    Returns None for a missing/malformed header, a forbidden version
    (``ff``) or the all-zero ids the spec reserves — the caller then mints
    a fresh context (the W3C "restart the trace" rule) instead of
    propagating garbage."""
    if not header:
        return None
    parts = str(header).strip().lower().split("-")
    if len(parts) < 4:
        return None
    ver, tid, sid = parts[0], parts[1], parts[2]
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 or ver == "ff":
        return None
    try:
        int(ver, 16), int(tid, 16), int(sid, 16)
    except ValueError:
        return None
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    return {"trace_id": tid, "span_id": sid}


def format_traceparent(ctx: dict) -> str:
    """``ctx`` -> ``00-<trace_id>-<span_id>-01`` (sampled flag set — a
    context this plane carries is by definition one the head kept)."""
    return f"00-{ctx['trace_id']}-{ctx['span_id']}-01"


def should_sample() -> bool:
    """The head-based sampling verdict for MINTING a trace at the door
    (``IGG_TRACE_SAMPLE``, default 1.0 = every request).  Inbound
    contexts are never re-sampled — upstream already decided.  Rate 0
    returns False without touching the RNG (the pinned no-context path)."""
    rate = _config.trace_sample_env()
    if rate is None or rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def current_context() -> dict | None:
    """This thread's innermost request context (None outside any)."""
    stack = _ctx_stacks.get(threading.get_ident())
    return stack[-1] if stack else None


class use_context:
    """Make ``ctx`` the ambient request context for the with-block (this
    thread): spans opened inside resolve it exactly as if they were passed
    ``parent=``.  ``None`` is a no-op, so call sites need no branching."""

    __slots__ = ("ctx",)

    def __init__(self, ctx: dict | None):
        self.ctx = ctx

    def __enter__(self):
        if self.ctx is not None:
            ident = threading.get_ident()
            stack = _ctx_stacks.get(ident)
            if stack is None:
                stack = _ctx_stacks[ident] = []
            stack.append(self.ctx)
        return self.ctx

    def __exit__(self, *exc) -> None:
        if self.ctx is not None:
            ident = threading.get_ident()
            stack = _ctx_stacks.get(ident)
            if stack:
                stack.pop()
                if not stack:
                    _ctx_stacks.pop(ident, None)


def _ring_push(name: str, t0: float, dur: float, tags: dict | None) -> None:
    """Append one closed span, counting evictions: a deque at maxlen
    silently drops its oldest on append, and a silently-truncated ring
    reconstructs into a silently-partial request tree — so the drop count
    rides every dump and readers can refuse to pretend completeness."""
    global _spans_dropped
    ring = _get_ring(_ring_capacity())
    if ring.maxlen is not None and len(ring) >= ring.maxlen:
        _spans_dropped += 1
        _telemetry.counter("trace.spans_dropped_total").inc()
    ring.append((name, t0, dur, tags))


def spans_dropped() -> int:
    """Ring evictions since the last `reset` (the in-process twin of the
    ``trace.spans_dropped_total`` counter; `dump_trace` ships it as the
    per-dump ``dropped`` field)."""
    return _spans_dropped


class _Span:
    """One live span.  Records itself into the ring on exit; re-entrant
    use records one span per enter/exit pair.  While executing it sits on
    this thread's open-span stack (see `open_spans`); a span that resolved
    a request context additionally pushes its own (child) context so
    anything nested chains under it (`current_context`)."""

    __slots__ = ("name", "tags", "t0", "ctx")

    def __init__(self, name: str, tags: dict | None,
                 ctx: dict | None = None):
        self.name = name
        self.tags = tags
        self.ctx = ctx

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter()
        ident = threading.get_ident()
        stack = _open_stacks.get(ident)
        if stack is None:
            stack = _open_stacks[ident] = []
        stack.append((self.name, self.t0, self.tags))
        if self.ctx is not None:
            cstack = _ctx_stacks.get(ident)
            if cstack is None:
                cstack = _ctx_stacks[ident] = []
            cstack.append(self.ctx)
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        ident = threading.get_ident()
        stack = _open_stacks.get(ident)
        if stack:
            stack.pop()
            if not stack:
                _open_stacks.pop(ident, None)  # no thread-lifetime leak
        if self.ctx is not None:
            cstack = _ctx_stacks.get(ident)
            if cstack:
                cstack.pop()
                if not cstack:
                    _ctx_stacks.pop(ident, None)
        _ring_push(self.name, self.t0, t1 - self.t0, self.tags)


class _NoopSpan:
    """Shared disabled-mode singleton (identity-stable, like
    `telemetry.NOOP`): no clock reads, no allocation."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def trace_span(name: str, *, parent: dict | None = None, **tags: Any):
    """A host-side span context manager recording into the process ring.

    Nestable (Chrome-trace ``X`` events on one track render nesting from
    containment); tags become the span's ``args`` in a merged trace.
    Returns the shared `NOOP_SPAN` when tracing is disabled — the
    zero-overhead contract of the rest of the registry.

    Request context: ``parent=`` (a ``{"trace_id", "span_id"}`` dict) or —
    when omitted — the ambient context (`use_context` / an enclosing
    context-carrying span) threads a request's identity into the span.  A
    single context mints this span a fresh ``span_id`` chained under the
    parent and makes it the ambient parent of anything nested; the
    multi-request form (``{"trace_ids": [...]}``, the serving round's
    shape) tags the span with every live ``trace_id`` without inventing
    per-request spans.  With no context in scope the span records exactly
    as before — no ids, no extra allocation.
    """
    if not enabled():
        return NOOP_SPAN
    ctx = parent if parent is not None else current_context()
    if ctx is None:
        return _Span(name, tags or None)
    if "trace_ids" in ctx:
        tags["trace_ids"] = list(ctx["trace_ids"])
        return _Span(name, tags, ctx=ctx)
    sid = new_span_id()
    tags["trace_id"] = ctx["trace_id"]
    tags["span_id"] = sid
    if ctx.get("span_id"):
        tags["parent_id"] = ctx["span_id"]
    return _Span(
        name, tags, ctx={"trace_id": ctx["trace_id"], "span_id": sid}
    )


def record_span(name: str, *, t0: float, dur: float,
                parent: dict | None = None, span_id: str | None = None,
                **tags: Any) -> dict | None:
    """Record one ALREADY-MEASURED span into the ring (no context-manager
    scope): the retroactive shape queue-wait and submit→result spans need
    — their duration is only known at admission/harvest time, long after
    the interval started.  ``t0`` is in this process's ``perf_counter``
    domain (the ring convention).  ``parent=`` chains the span under a
    request context; ``span_id=`` pins the id when the caller already
    broadcast it to peers (the admit span's id rides the control message,
    so every rank's round spans name the SAME parent).  Returns the
    span's own context for further chaining, or None when tracing is
    disabled (nothing recorded — the zero-overhead contract)."""
    if not enabled():
        return None
    if parent is not None:
        sid = span_id or new_span_id()
        tags["trace_id"] = parent["trace_id"]
        tags["span_id"] = sid
        if parent.get("span_id"):
            tags["parent_id"] = parent["span_id"]
        _ring_push(name, float(t0), float(dur), tags)
        return {"trace_id": parent["trace_id"], "span_id": sid}
    _ring_push(name, float(t0), float(dur), tags or None)
    return None


def span_records() -> list[dict]:
    """The current ring as plain dicts (oldest first; test/dump hook)."""
    with _lock:
        items = list(_ring) if _ring else []
    return [
        {"name": n, "t0": t0, "dur": dur, **({"args": tags} if tags else {})}
        for n, t0, dur, tags in items
    ]


def span_summary() -> dict:
    """``{span name: {count, total_s, mean_s, max_s}}`` over the ring —
    the aggregate view `bench.py` ships in its artifact."""
    agg: dict[str, list] = {}
    with _lock:
        items = list(_ring) if _ring else []
    for name, _t0, dur, _tags in items:
        rec = agg.setdefault(name, [0, 0.0, 0.0])
        rec[0] += 1
        rec[1] += dur
        rec[2] = max(rec[2], dur)
    return {
        name: {
            "count": c,
            "total_s": total,
            "mean_s": total / c,
            "max_s": mx,
        }
        for name, (c, total, mx) in sorted(agg.items())
    }


def span_stats(span_lists: Sequence[Sequence[dict]]) -> dict:
    """``{span name: {count, total_s, mean_s, p50_s, p99_s, max_s}}`` over
    any number of span-record lists (the `span_records` / ``trace.p*.json``
    schema) — the aggregation behind ``scripts/igg_trace.py summarize``.
    Quantiles are nearest-rank over ALL matching spans' durations (no
    reservoir: a dump is already bounded by the ring).  Open spans
    (``open: true``) are excluded — their durations are ages, not totals.
    """
    durs: dict[str, list[float]] = {}
    for spans in span_lists:
        for s in spans:
            if s.get("open"):
                continue
            durs.setdefault(s["name"], []).append(float(s["dur"]))
    out = {}
    for name in sorted(durs):
        ds = sorted(durs[name])
        n = len(ds)

        def q(frac: float) -> float:
            return ds[min(n - 1, max(0, round(frac * (n - 1))))]

        out[name] = {
            "count": n,
            "total_s": sum(ds),
            "mean_s": sum(ds) / n,
            "p50_s": q(0.50),
            "p99_s": q(0.99),
            "max_s": ds[-1],
        }
    return out


# -- clock sync ---------------------------------------------------------------

# The barrier-timestamped anchor (set once per grid epoch by
# `record_clock_sync`): {"wall", "perf", "uncertainty_s", "epoch",
# "barrier": bool}.  ``perf`` is this process's perf_counter at barrier
# exit; all ranks' ``perf`` values name (approximately) the same true
# instant, which is what merge alignment uses.
_clock_sync: dict | None = None


def record_clock_sync(barrier_fn=None, *, epoch: int | None = None) -> dict:
    """Take the cross-rank clock-sync sample (called at `init_global_grid`).

    ``barrier_fn`` (multi-process grids): a callable that returns only when
    every process reached it — the ranks' clock samples taken right after
    it anchor one shared instant.  The recorded ``uncertainty_s`` is the
    measured barrier duration: a rank can exit at most one barrier-length
    after the first exiter, so per-rank alignment error is bounded by it
    (document-honest — no ns claims).  Without a barrier (single process)
    the sample is exact by construction (uncertainty 0).
    """
    global _clock_sync
    uncertainty = 0.0
    if barrier_fn is not None:
        tb = time.perf_counter()
        barrier_fn()
        uncertainty = time.perf_counter() - tb
    perf = time.perf_counter()
    wall = time.time()
    _clock_sync = {
        "wall": wall,
        "perf": perf,
        "uncertainty_s": uncertainty,
        "epoch": epoch,
        "barrier": barrier_fn is not None,
    }
    _telemetry.event(
        "clock.sync",
        wall=wall,
        perf=perf,
        uncertainty_s=uncertainty,
        barrier=barrier_fn is not None,
    )
    return _clock_sync


def clock_sync() -> dict:
    """The active sync anchor; synthesized (``barrier: False``) when no
    grid init ran — the merge then aligns by wall clocks only and says so."""
    if _clock_sync is not None:
        return _clock_sync
    return {
        "wall": time.time(),
        "perf": time.perf_counter(),
        "uncertainty_s": None,
        "epoch": None,
        "barrier": False,
    }


# -- per-rank dump + merge ----------------------------------------------------


def trace_filename(rank: int, generation: int | None = None) -> str:
    """``trace.p<rank>.json``, or ``trace.g<gen>.p<rank>.json`` for a
    fenced child (``IGG_GENERATION`` set): a supervised restart's
    generations then coexist in one telemetry dir instead of each
    clobbering its predecessor's dump."""
    if generation is None:
        return f"trace.p{rank}.json"
    return f"trace.g{int(generation)}.p{rank}.json"


def dump_trace(directory: str | os.PathLike | None = None) -> str | None:
    """Write this rank's span file (``trace.p<rank>.json``; generation-
    suffixed under a supervisor, see `trace_filename`) into ``directory``
    (default ``IGG_TELEMETRY_DIR``).  Returns the path, or None when
    telemetry is disabled / no directory resolves.  Exported as
    ``igg.dump_trace``; merge any set of ranks' files with
    ``scripts/igg_trace.py merge`` (or `merge_trace_files`)."""
    if not _telemetry.enabled():
        return None
    directory = os.fspath(directory) if directory else _config.telemetry_dir_env()
    if not directory:
        return None
    rank = _telemetry._proc_index()
    gen = _config.generation_env()
    doc = {
        "schema": TRACE_SCHEMA,
        "rank": rank,
        "pid": os.getpid(),
        "coords": _telemetry._grid_coords(),
        "gen": gen,
        "dropped": spans_dropped(),
        "clock_sync": clock_sync(),
        "spans": span_records(),
    }
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, trace_filename(rank, generation=gen))
    # Atomic publish: periodic dumpers (the fleet drill's pools) race
    # SIGKILL — a torn write must never leave a truncated JSON where a
    # reconstruction will read it.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    return path


def _load_rank_trace(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != TRACE_SCHEMA:
        raise ValueError(
            f"{path}: unsupported trace schema {doc.get('schema')!r} "
            f"(expected {TRACE_SCHEMA})."
        )
    if "rank" not in doc or "spans" not in doc or "clock_sync" not in doc:
        raise ValueError(f"{path}: not a per-rank trace file (missing keys).")
    return doc


#: max wall-clock disagreement (s) between two ranks' barrier-exit samples
#: before the merge refuses to treat them as the SAME barrier.  Same-run
#: samples differ by barrier-exit skew + NTP skew (well under a second);
#: anything bigger means the files come from different runs — the classic
#: stale-dump-in-a-reused-IGG_TELEMETRY_DIR hazard.
BARRIER_WALL_TOL_S = 2.0


#: per-epoch merge: pid stride between generation groups, so every
#: generation's rank tracks form one visually-contiguous band and pids
#: never collide across groups (``pid = gen_index*stride + rank``)
EPOCH_PID_STRIDE = 10000

_ALIGNMENT_NOTE = (
    "per-rank perf_counter timelines are aligned on the barrier "
    "instant recorded at init_global_grid; cross-rank ordering is "
    "trustworthy only beyond each rank's uncertainty_s (the "
    "measured barrier duration) — wall-clock-aligned ranks "
    "(barrier_aligned=false) carry whatever NTP skew the hosts "
    "have."
)


def _check_same_barrier(docs: Sequence[dict]) -> None:
    """Refuse barrier-anchored docs that describe DIFFERENT barriers —
    differing grid epochs or barrier-exit wall samples further apart than
    `BARRIER_WALL_TOL_S` (the stale-dump-in-a-reused-dir hazard)."""
    anchor = docs[0]["clock_sync"]
    for doc in docs[1:]:
        sync = doc["clock_sync"]
        if not (sync.get("barrier") and anchor.get("barrier")):
            continue  # wall-aligned below, flagged — no same-barrier claim
        wall_delta = abs(sync["wall"] - anchor["wall"])
        if (
            sync.get("epoch") != anchor.get("epoch")
            or wall_delta > BARRIER_WALL_TOL_S
        ):
            raise ValueError(
                f"merge_trace_files: rank {doc['rank']}'s barrier anchor "
                f"does not match rank {docs[0]['rank']}'s (epoch "
                f"{sync.get('epoch')} vs {anchor.get('epoch')}, barrier "
                f"wall samples {wall_delta:.1f}s apart > "
                f"{BARRIER_WALL_TOL_S}s) — the files describe different "
                f"runs/barriers and cannot share an aligned clock.  A "
                f"stale trace.p*.json from a previous run in a reused "
                f"telemetry dir looks exactly like this: delete it, or "
                f"merge the current run's files explicitly (or pass "
                f"--per-epoch when the dumps are a supervised restart's "
                f"generations)."
            )


def _aligned_events(
    docs: Sequence[dict],
    *,
    pid_base: int = 0,
    wall_shift: float = 0.0,
    track_suffix: str = "",
) -> tuple[list[dict], dict]:
    """Chrome-trace events for one same-barrier group of docs, plus the
    group's ``per_rank`` alignment metadata.  ``wall_shift`` moves the
    whole group on the merged axis (per-epoch merges place each
    generation at its true wall offset from the earliest group)."""
    anchor = docs[0]["clock_sync"]
    events: list[dict] = []
    per_rank: dict[str, Any] = {}
    for doc in docs:
        sync = doc["clock_sync"]
        barrier_aligned = bool(sync.get("barrier")) and bool(
            anchor.get("barrier")
        )
        if barrier_aligned:
            # span perf t -> seconds since the shared barrier instant.
            offset = -sync["perf"] + wall_shift
        else:
            # No shared barrier: fall back to wall-clock alignment, re-based
            # so the anchor rank's barrier (or sample) instant is still zero.
            offset = (sync["wall"] - anchor["wall"]) - sync["perf"] + wall_shift
        per_rank[str(doc["rank"])] = {
            "barrier_aligned": barrier_aligned,
            "offset_s": offset,
            "uncertainty_s": sync.get("uncertainty_s"),
            "wall_at_sync_unix_s": sync.get("wall"),
        }
        pid = doc["rank"] + pid_base
        coords = doc.get("coords")
        name = (
            f"rank {doc['rank']}"
            + (f" coords {tuple(coords)}" if coords else "")
            + track_suffix
        )
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": name},
            }
        )
        for s in doc["spans"]:
            ev = {
                "ph": "X",
                "name": s["name"],
                "pid": pid,
                "tid": 0,
                "ts": (s["t0"] + offset) * 1e6,
                "dur": s["dur"] * 1e6,
            }
            if s.get("args"):
                ev["args"] = s["args"]
            events.append(ev)
    return events, per_rank


def _finish_trace(events: list[dict], alignment: dict) -> dict:
    # Re-base so the earliest event sits at ts=0 (viewers dislike huge or
    # negative timestamps); the absolute anchor lives in the metadata.
    xs = [e["ts"] for e in events if e["ph"] == "X"]
    base = min(xs) if xs else 0.0
    for e in events:
        if e["ph"] == "X":
            e["ts"] -= base
    alignment["ts_zero_offset_s"] = base / 1e6
    events.sort(key=lambda e: (e["pid"], e.get("ts", -1.0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"clock_alignment": alignment},
    }


def _merge_per_epoch(docs: list[dict]) -> dict:
    """Per-epoch merge: group dumps by (generation, grid epoch), align each
    group on its OWN barrier, and place the groups on one shared wall-clock
    axis — the shape a supervised restart leaves in a telemetry dir, where
    the flat merge correctly refuses to pretend one barrier."""
    groups: dict[tuple, list[dict]] = {}
    for doc in docs:
        key = (doc.get("gen"), doc["clock_sync"].get("epoch"))
        groups.setdefault(key, []).append(doc)
    ordered = sorted(
        groups.items(),
        key=lambda kv: min(d["clock_sync"]["wall"] for d in kv[1]),
    )
    base_wall = min(d["clock_sync"]["wall"] for d in docs)
    events: list[dict] = []
    alignment: dict[str, Any] = {
        "per_epoch": True,
        "note": (
            _ALIGNMENT_NOTE
            + "  Groups (generations/epochs) are aligned on their own "
            "barriers and placed relative to each other by wall clock "
            "only — cross-group ordering carries NTP-grade skew."
        ),
        "groups": [],
    }
    for gi, ((gen, epoch), group) in enumerate(ordered):
        group = sorted(group, key=lambda d: d["rank"])
        ranks = [d["rank"] for d in group]
        if len(set(ranks)) != len(ranks):
            raise ValueError(
                f"merge_trace_files: duplicate rank(s) within generation "
                f"{gen!r} / epoch {epoch!r} ({ranks}) — each rank "
                f"contributes exactly one file per generation."
            )
        _check_same_barrier(group)
        anchor = group[0]["clock_sync"]
        suffix = f" gen {gen}" if gen is not None else f" epoch {epoch}"
        evs, per_rank = _aligned_events(
            group,
            pid_base=gi * EPOCH_PID_STRIDE,
            wall_shift=anchor["wall"] - base_wall,
            track_suffix=suffix,
        )
        events.extend(evs)
        alignment["groups"].append(
            {
                "gen": gen,
                "epoch": epoch,
                "anchor_rank": group[0]["rank"],
                "anchor_wall_unix_s": anchor["wall"],
                "pid_base": gi * EPOCH_PID_STRIDE,
                "per_rank": per_rank,
            }
        )
    return _finish_trace(events, alignment)


def merge_trace_files(
    paths: Sequence[str | os.PathLike], *, per_epoch: bool = False
) -> dict:
    """Join per-rank span files into one Chrome-trace/Perfetto JSON object.

    One track (pid) per rank; ``X`` (complete) events carry the span tags
    as ``args``.  Alignment: the lowest rank is the anchor — its
    barrier-exit wall time defines the absolute axis, and every rank's
    spans shift by ``(own perf at barrier exit)`` so all tracks share the
    barrier instant as time zero.  The per-rank offset and its uncertainty
    (the measured barrier duration — the honesty bound on cross-rank
    ordering) land in ``otherData.clock_alignment``; a rank whose sync was
    not barrier-anchored (``barrier: false``) is aligned by wall clock
    instead and flagged, since nothing ties its perf counter to the
    others'.  Events are sorted by (pid, ts), so each track's timestamps
    are monotonic — the tier-1 validity pin.

    Barrier-anchored inputs must describe the SAME barrier, or the merged
    "aligned" clock is a lie: the merge refuses files whose grid epochs
    differ or whose barrier-exit wall samples disagree by more than
    `BARRIER_WALL_TOL_S` (a stale ``trace.p*.json`` from a previous run
    left in a reused telemetry dir is exactly this shape — delete it, or
    pass the current run's files explicitly).  A supervised restart
    legitimately leaves MULTIPLE generations' dumps in one dir; pass
    ``per_epoch=True`` (CLI ``--per-epoch``) to merge each (generation,
    epoch) group under its own alignment — one pid band per group, groups
    placed relative to each other by wall clock — instead of refusing the
    set.
    """
    docs = sorted(
        (_load_rank_trace(os.fspath(p)) for p in paths),
        key=lambda d: d["rank"],
    )
    if not docs:
        raise ValueError("merge_trace_files: no per-rank trace files given.")
    if per_epoch:
        return _merge_per_epoch(docs)
    ranks = [d["rank"] for d in docs]
    if len(set(ranks)) != len(ranks):
        hint = ""
        if len({d.get("gen") for d in docs}) > 1:
            # the supervised-restart shape: each generation re-dumps the
            # same rank set — the remedy is the per-epoch merge, not
            # deleting files
            hint = (
                "  The dumps span multiple generations "
                "(trace.g<gen>.p<rank>.json): pass --per-epoch to merge "
                "each generation under its own alignment."
            )
        raise ValueError(
            f"merge_trace_files: duplicate rank(s) in inputs ({ranks}) — "
            f"each rank contributes exactly one file.{hint}"
        )
    _check_same_barrier(docs)
    anchor = docs[0]["clock_sync"]
    events, per_rank = _aligned_events(docs)
    alignment: dict[str, Any] = {
        "anchor_rank": docs[0]["rank"],
        "anchor_wall_unix_s": anchor["wall"],
        "note": _ALIGNMENT_NOTE,
        "per_rank": per_rank,
    }
    return _finish_trace(events, alignment)


def validate_chrome_trace(doc: dict) -> list[str]:
    """Problems with a merged trace (empty list = valid): the tier-1 /
    soak check that the artifact really is loadable Chrome-trace JSON with
    per-track monotonic timestamps and alignment metadata.  NaN/inf
    timestamps are rejected explicitly — Python's json writes them but
    strict parsers (and the trace viewers) refuse the artifact, and a NaN
    would additionally sail through the monotonicity comparison."""
    import math

    problems: list[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is missing or not a list"]
    last_ts: dict[Any, float] = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e or "pid" not in e:
            problems.append(f"event {i} malformed: {e!r}")
            continue
        if e["ph"] != "X":
            continue
        for key in ("name", "ts", "dur"):
            if key not in e:
                problems.append(f"event {i} missing {key!r}")
        ts = e.get("ts")
        if (
            not isinstance(ts, (int, float))
            or not math.isfinite(ts)
            or ts < 0
        ):
            problems.append(f"event {i} has non-finite/negative ts {ts!r}")
            continue
        dur = e.get("dur")
        if isinstance(dur, (int, float)) and (
            not math.isfinite(dur) or dur < 0
        ):
            problems.append(f"event {i} has non-finite/negative dur {dur!r}")
        if ts < last_ts.get(e["pid"], float("-inf")):
            problems.append(
                f"event {i} breaks track pid={e['pid']} monotonicity "
                f"({ts} after {last_ts[e['pid']]})"
            )
        last_ts[e["pid"]] = ts
    if "clock_alignment" not in doc.get("otherData", {}):
        problems.append("otherData.clock_alignment metadata missing")
    return problems


# -- request-tree reconstruction + critical path ------------------------------


def _trace_match(args: dict | None, trace_id: str) -> tuple[bool, str | None]:
    """Does a span's ``args`` belong to ``trace_id``?  Returns
    ``(matched, member_parent_span_id)`` — the second element is set when
    the match came through a serving-round ``members`` entry, whose
    embedded context names the request-side parent span directly."""
    if not args:
        return False, None
    matched = False
    member_parent = None
    # A round span tags BOTH ``trace_ids`` and ``members``: the member
    # entry must still yield the parent edge, so look for it first.
    for m in args.get("members") or ():
        if isinstance(m, dict):
            ctx = m.get("trace")
            if isinstance(ctx, dict) and ctx.get("trace_id") == trace_id:
                matched = True
                member_parent = ctx.get("span_id")
                break
    if args.get("trace_id") == trace_id:
        matched = True
    ids = args.get("trace_ids")
    if not matched and ids and trace_id in ids:
        matched = True
    return matched, member_parent


def _span_wall(doc: dict, t0: float) -> float:
    """A span's start instant on the wall clock, anchored by its dump's
    clock sync — the one axis per-pool/per-generation dumps share."""
    sync = doc["clock_sync"]
    return sync["wall"] + (float(t0) - sync["perf"])


def request_tree(docs: Sequence[dict], trace_id: str) -> dict:
    """Reconstruct ONE request's causal tree from any set of per-rank
    trace docs (the `dump_trace` schema) — across pools, generations and
    re-routes, since span/parent ids are globally unique and every dump
    carries its own wall anchor.

    Parenting: an explicit ``parent_id`` tag wins (cross-dump — ids are
    global); a tagged-but-unparented span nests under the smallest
    enclosing matching span of its OWN dump (time containment — the
    round-span case, where many requests share one span); anything else
    is a root.  Returns ``{"trace_id", "roots", "spans", "ranks",
    "gens", "dropped", "incomplete"}`` — ``incomplete`` is True when any
    contributing dump reported ring evictions, because a truncated ring
    reconstructs into a silently-partial tree and the reader must know.
    """
    nodes: list[dict] = []
    for di, doc in enumerate(docs):
        for s in doc.get("spans", ()):
            args = s.get("args")
            matched, member_parent = _trace_match(args, trace_id)
            if not matched:
                continue
            args = args or {}
            nodes.append(
                {
                    "name": s["name"],
                    "rank": doc.get("rank"),
                    "gen": doc.get("gen"),
                    "t0_unix_s": _span_wall(doc, s["t0"]),
                    "dur_s": float(s["dur"]),
                    "args": args,
                    "span_id": args.get("span_id"),
                    "parent_id": args.get("parent_id") or member_parent,
                    "children": [],
                    "_doc": di,
                }
            )
    by_span_id = {n["span_id"]: n for n in nodes if n["span_id"]}
    roots: list[dict] = []
    for n in nodes:
        parent = by_span_id.get(n["parent_id"]) if n["parent_id"] else None
        if parent is None and not n["parent_id"]:
            # No explicit link: nest under the smallest enclosing matching
            # span of the same dump (perf clocks only compare in-process).
            t0, t1 = n["t0_unix_s"], n["t0_unix_s"] + n["dur_s"]
            best = None
            for c in nodes:
                if c is n or c["_doc"] != n["_doc"]:
                    continue
                c0, c1 = c["t0_unix_s"], c["t0_unix_s"] + c["dur_s"]
                if c0 <= t0 + 1e-9 and t1 <= c1 + 1e-9 and c["dur_s"] >= n["dur_s"]:
                    if best is None or c["dur_s"] < best["dur_s"]:
                        best = c
            parent = best
        if parent is not None and parent is not n:
            parent["children"].append(n)
        else:
            roots.append(n)

    def _order(ns: list[dict]) -> None:
        ns.sort(key=lambda x: x["t0_unix_s"])
        for x in ns:
            x.pop("_doc", None)
            _order(x["children"])

    _order(roots)
    dropped = sum(int(doc.get("dropped") or 0) for doc in docs)
    return {
        "trace_id": trace_id,
        "roots": roots,
        "spans": len(nodes),
        "ranks": sorted({n["rank"] for n in nodes if n["rank"] is not None}),
        "gens": sorted({n["gen"] for n in nodes if n["gen"] is not None}),
        "dropped": dropped,
        "incomplete": dropped > 0,
    }


#: latency-attribution segments, first match wins per span name: the
#: request's wall time decomposes into queue-wait / admission / re-route /
#: checkpoint / exchange / rounds (residual round time net of nested
#: exchange+checkpoint), with anything uncovered landing in ``other``.
_SEGMENT_OF = (
    ("queue_wait", ("igg.frontdoor.admit",)),
    ("admission", ("igg.serving.admission",)),
    ("reroute", ("igg.fleet.reroute", "igg.fleet.detect")),
    (
        "checkpoint",
        (
            "igg.checkpoint.save",
            "igg.checkpoint.restore",
            "igg.frontdoor.resize",
        ),
    ),
    (
        "exchange",
        (
            "igg_halo_exchange",
            "igg_slab_exchange_begin",
            "igg_slab_exchange_finish",
        ),
    ),
    ("rounds", ("igg.serving.round",)),
)


def _union(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Merge overlapping intervals (duplicate round spans from N ranks
    must count the wall-clock once, not N times)."""
    out: list[tuple[float, float]] = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _measure(intervals: list[tuple[float, float]]) -> float:
    return sum(b - a for a, b in intervals)


def _subtract(
    intervals: list[tuple[float, float]],
    minus: list[tuple[float, float]],
) -> list[tuple[float, float]]:
    """Interval-set difference (both inputs already unioned/sorted)."""
    out: list[tuple[float, float]] = []
    for a, b in intervals:
        cur = a
        for ma, mb in minus:
            if mb <= cur or ma >= b:
                continue
            if ma > cur:
                out.append((cur, ma))
            cur = max(cur, mb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def critical_path(tree: dict) -> dict:
    """Attribute a request's latency to segments (`_SEGMENT_OF`), walking
    the `request_tree` output on the shared wall axis.

    Each segment's time is the UNION of its spans' wall intervals (N
    ranks' identical round spans count once); nested double-counting is
    removed (exchange/checkpoint time inside a round is charged to
    exchange/checkpoint, not rounds; admission inside queue-wait to
    admission).  ``total_s`` is the door's submit→result span when present
    (``igg.frontdoor.request``), else the tree's wall extent; the
    uncovered remainder is ``other``.  Returns ``{"total_s", "segments":
    {seg: {"s", "share"}}}``."""
    flat: list[dict] = []

    def _walk(ns) -> None:
        for n in ns:
            flat.append(n)
            _walk(n["children"])

    _walk(tree.get("roots", ()))
    seg_iv: dict[str, list[tuple[float, float]]] = {
        seg: [] for seg, _names in _SEGMENT_OF
    }
    name_to_seg = {
        name: seg for seg, names in _SEGMENT_OF for name in names
    }
    request_spans: list[dict] = []
    for n in flat:
        if n["name"] == "igg.frontdoor.request":
            request_spans.append(n)
        seg = name_to_seg.get(n["name"])
        if seg is not None:
            seg_iv[seg].append(
                (n["t0_unix_s"], n["t0_unix_s"] + n["dur_s"])
            )
    iv = {seg: _union(v) for seg, v in seg_iv.items()}
    # Charge nested time to the inner segment, once.
    iv["rounds"] = _subtract(
        _subtract(iv["rounds"], iv["exchange"]), iv["checkpoint"]
    )
    iv["queue_wait"] = _subtract(iv["queue_wait"], iv["admission"])
    if request_spans:
        total = max(n["dur_s"] for n in request_spans)
    elif flat:
        t0 = min(n["t0_unix_s"] for n in flat)
        t1 = max(n["t0_unix_s"] + n["dur_s"] for n in flat)
        total = t1 - t0
    else:
        total = 0.0
    segments: dict[str, dict] = {}
    covered = 0.0
    for seg, _names in _SEGMENT_OF:
        s = _measure(iv[seg])
        covered += s
        segments[seg] = {
            "s": s,
            "share": (s / total) if total > 0 else 0.0,
        }
    other = max(0.0, total - covered)
    segments["other"] = {
        "s": other,
        "share": (other / total) if total > 0 else 0.0,
    }
    return {"total_s": total, "segments": segments}


#: span-name prefixes highlighted in the `request_chrome_trace` view —
#: the request's control-plane skeleton (door hops, fleet routing,
#: supervised restarts), colored apart from the compute spans they enclose
_REQUEST_SKELETON_PREFIXES = ("igg.frontdoor.", "igg.fleet.", "igg.supervisor.")


def request_chrome_trace(tree: dict) -> dict:
    """One request's causal tree (`request_tree` output) as a Chrome-trace/
    Perfetto JSON object: one track per (generation, rank) the request
    touched, every span placed on the ABSOLUTE wall axis (each dump's own
    clock sync anchors it — the only axis that exists across pools and
    generations), control-plane skeleton spans highlighted via ``cname``.
    The alignment honesty note lands in ``otherData.clock_alignment``;
    the tree's incompleteness verdict rides ``otherData.request``.
    """
    flat: list[dict] = []

    def _walk(ns) -> None:
        for n in ns:
            flat.append(n)
            _walk(n["children"])

    _walk(tree.get("roots", ()))
    if not flat:
        raise ValueError(
            f"request_chrome_trace: no spans for trace "
            f"{tree.get('trace_id')!r}."
        )
    t_zero = min(n["t0_unix_s"] for n in flat)

    def _band(n: dict) -> tuple:
        return (
            n["gen"] if n["gen"] is not None else -1,
            n["rank"] if n["rank"] is not None else -1,
        )

    bands = sorted({_band(n) for n in flat})
    pid_of = {band: i for i, band in enumerate(bands)}
    events: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid_of[(gen, rank)],
            "args": {
                "name": f"rank {rank}"
                + (f" (gen {gen})" if gen >= 0 else "")
            },
        }
        for gen, rank in bands
    ]
    spans = []
    for n in flat:
        ev = {
            "name": n["name"],
            "ph": "X",
            "pid": pid_of[_band(n)],
            "tid": 0,
            "ts": (n["t0_unix_s"] - t_zero) * 1e6,
            "dur": n["dur_s"] * 1e6,
            "cat": "igg",
            "args": n["args"],
        }
        if n["name"].startswith(_REQUEST_SKELETON_PREFIXES):
            ev["cname"] = "thread_state_running"  # the highlighted skeleton
        spans.append(ev)
    spans.sort(key=lambda e: (e["pid"], e["ts"]))
    events.extend(spans)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock_alignment": {
                "mode": "wall",
                "note": (
                    "request view: every dump's spans are placed on the "
                    "absolute wall axis via its own clock sync — "
                    "cross-process ordering carries whatever NTP skew "
                    "the hosts have."
                ),
            },
            "request": {
                "trace_id": tree.get("trace_id"),
                "ranks": tree.get("ranks"),
                "gens": tree.get("gens"),
                "dropped": tree.get("dropped"),
                "incomplete": tree.get("incomplete"),
            },
        },
    }


# -- OTLP/JSON export ---------------------------------------------------------

#: span names exported with OTLP ``kind`` SERVER (2) — the ingress edges;
#: everything else is INTERNAL (1)
_OTLP_SERVER_SPANS = frozenset({"igg.frontdoor.request", "igg.fleet.route"})


def _otlp_value(v: Any) -> dict:
    """One OTLP AnyValue.  Deterministic: compound values serialize as
    sorted-key JSON strings, so the export is byte-stable for a fixed
    input (the golden-pin contract)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, str):
        return {"stringValue": v}
    return {"stringValue": json.dumps(v, sort_keys=True, default=str)}


def otlp_trace(docs: Sequence[dict], *, trace_id: str | None = None) -> dict:
    """Export per-rank trace docs as OTLP/JSON (``resourceSpans`` —
    the Jaeger/Tempo ingest shape; one resource per dump, resource
    attributes ``service.name=igg`` / ``igg.rank`` / ``igg.gen``).

    ``trace_id=`` exports one request's spans only; otherwise every
    closed span ships, with untagged spans grouped under a deterministic
    per-dump trace id (content-addressed — same dump, same export).
    Timestamps are wall-anchored via each dump's clock sync.  Output is
    deterministic for fixed input: docs sort by (gen, rank), spans by
    (t0, name), attributes by key — serialize with ``sort_keys`` for a
    byte-stable artifact."""
    import hashlib

    resource_spans: list[dict] = []
    for doc in sorted(
        docs, key=lambda d: (d.get("gen") or 0, d.get("rank") or 0)
    ):
        rank = doc.get("rank")
        gen = doc.get("gen")
        local_tid = hashlib.sha256(
            f"igg:{rank}:{gen}".encode()
        ).hexdigest()[:32]
        spans = sorted(
            (s for s in doc.get("spans", ()) if not s.get("open")),
            key=lambda s: (float(s["t0"]), s["name"]),
        )
        out_spans: list[dict] = []
        for i, s in enumerate(spans):
            args = s.get("args") or {}
            member_parent = None
            if trace_id is not None:
                matched, member_parent = _trace_match(args, trace_id)
                if not matched:
                    continue
            tid = args.get("trace_id") or trace_id or local_tid
            sid = args.get("span_id") or hashlib.sha256(
                f"{rank}:{gen}:{s['name']}:{float(s['t0']):.9f}:"
                f"{float(s['dur']):.9f}:{i}".encode()
            ).hexdigest()[:16]
            start = _span_wall(doc, s["t0"])
            end = start + float(s["dur"])
            span_doc: dict[str, Any] = {
                "traceId": tid,
                "spanId": sid,
                "name": s["name"],
                "kind": 2 if s["name"] in _OTLP_SERVER_SPANS else 1,
                "startTimeUnixNano": str(int(round(start * 1e9))),
                "endTimeUnixNano": str(int(round(end * 1e9))),
            }
            parent = args.get("parent_id") or member_parent
            if parent:
                span_doc["parentSpanId"] = parent
            attrs = [
                {"key": k, "value": _otlp_value(v)}
                for k, v in sorted(args.items())
                if k not in ("trace_id", "span_id", "parent_id")
            ]
            if attrs:
                span_doc["attributes"] = attrs
            out_spans.append(span_doc)
        if not out_spans:
            continue
        res_attrs = [
            {"key": "service.name", "value": {"stringValue": "igg"}},
        ]
        if rank is not None:
            res_attrs.append(
                {"key": "igg.rank", "value": {"intValue": str(int(rank))}}
            )
        if gen is not None:
            res_attrs.append(
                {"key": "igg.gen", "value": {"intValue": str(int(gen))}}
            )
        resource_spans.append(
            {
                "resource": {"attributes": res_attrs},
                "scopeSpans": [
                    {
                        "scope": {
                            "name": "igg.tracing",
                            "version": str(TRACE_SCHEMA),
                        },
                        "spans": out_spans,
                    }
                ],
            }
        )
    return {"resourceSpans": resource_spans}


def _hexid(v: Any, width: int) -> bool:
    if not isinstance(v, str) or len(v) != width:
        return False
    try:
        int(v, 16)
        return True
    except ValueError:
        return False


def validate_otlp(doc: dict) -> list[str]:
    """Problems with an OTLP/JSON export (empty list = valid): the schema
    check behind the golden pin — id widths, nano-timestamp strings with
    end >= start, attribute shape, the resourceSpans nesting a collector
    actually accepts."""
    problems: list[str] = []
    rss = doc.get("resourceSpans")
    if not isinstance(rss, list):
        return ["resourceSpans is missing or not a list"]

    def _check_attrs(attrs: Any, where: str) -> None:
        if attrs is None:
            return
        if not isinstance(attrs, list):
            problems.append(f"{where}: attributes not a list")
            return
        for a in attrs:
            if (
                not isinstance(a, dict)
                or not isinstance(a.get("key"), str)
                or not isinstance(a.get("value"), dict)
            ):
                problems.append(f"{where}: malformed attribute {a!r}")

    for ri, rs in enumerate(rss):
        if not isinstance(rs, dict):
            problems.append(f"resourceSpans[{ri}] not an object")
            continue
        _check_attrs(
            rs.get("resource", {}).get("attributes"),
            f"resourceSpans[{ri}].resource",
        )
        sss = rs.get("scopeSpans")
        if not isinstance(sss, list):
            problems.append(f"resourceSpans[{ri}].scopeSpans not a list")
            continue
        for si, ss in enumerate(sss):
            spans = ss.get("spans") if isinstance(ss, dict) else None
            if not isinstance(spans, list):
                problems.append(
                    f"resourceSpans[{ri}].scopeSpans[{si}].spans not a list"
                )
                continue
            for pi, sp in enumerate(spans):
                where = (
                    f"resourceSpans[{ri}].scopeSpans[{si}].spans[{pi}]"
                )
                if not isinstance(sp, dict):
                    problems.append(f"{where} not an object")
                    continue
                if not _hexid(sp.get("traceId"), 32):
                    problems.append(f"{where}: bad traceId")
                if not _hexid(sp.get("spanId"), 16):
                    problems.append(f"{where}: bad spanId")
                if "parentSpanId" in sp and not _hexid(
                    sp["parentSpanId"], 16
                ):
                    problems.append(f"{where}: bad parentSpanId")
                if not sp.get("name"):
                    problems.append(f"{where}: empty name")
                if not isinstance(sp.get("kind"), int):
                    problems.append(f"{where}: kind not an int")
                try:
                    t0 = int(sp.get("startTimeUnixNano"))
                    t1 = int(sp.get("endTimeUnixNano"))
                    if t1 < t0:
                        problems.append(f"{where}: end before start")
                except (TypeError, ValueError):
                    problems.append(f"{where}: non-integer timestamps")
                _check_attrs(sp.get("attributes"), where)
    return problems


# -- straggler detection ------------------------------------------------------

#: default ``IGG_SKEW_WARN`` threshold on max/min per-rank step seconds
SKEW_WARN_DEFAULT = 2.0

_skew_cache: dict = {}


def _clear_caches() -> None:
    _skew_cache.clear()


def _skew_fn(gg):
    """The jitted all-ranks share of one host scalar per block: the same
    scatter-into-one-hot + all-axes psum shape as `resilience.check_fields`
    and the chunked gather's block fetch (`ops.gather._block_fetch_fn`) —
    the one collective pattern proven on every supported transport.  The
    result is a tiny replicated ``dims``-shaped array every process reads
    host-side."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES, NDIMS
    from .compat import shard_map

    key = gg.epoch
    fn = _skew_cache.get(key)
    if fn is not None:
        return fn

    def per_block(x):
        onehot = jnp.zeros(tuple(gg.dims), jnp.float32)
        coords = tuple(
            lax.axis_index(AXIS_NAMES[d]) if gg.dims[d] > 1 else jnp.int32(0)
            for d in range(NDIMS)
        )
        onehot = lax.dynamic_update_slice(
            onehot, x.astype(jnp.float32).reshape((1, 1, 1)), coords
        )
        return lax.psum(onehot, AXIS_NAMES)

    mapped = shard_map(
        per_block,
        mesh=gg.mesh,
        in_specs=P(*AXIS_NAMES),
        out_specs=P(),
        check_vma=False,
    )
    fn = jax.jit(mapped)
    _skew_cache[key] = fn
    return fn


#: one-shot latency armed on the next host control collective (the
#: ``net_delay`` fault kind, `utils.resilience`): seconds slept before this
#: process dispatches into `all_ranks_value` — its peers block with it,
#: which is exactly the transient network fault the chaos plane models.
_collective_delay = 0.0


def arm_collective_delay(seconds: float) -> None:
    """Arm one-shot latency on this process's next host control collective
    (consumed by `all_ranks_value` — the skew-probe / `broadcast_control`
    transport).  The fault-injection hook of ``net_delay``."""
    global _collective_delay
    _collective_delay = max(0.0, float(seconds))


def _consume_collective_delay() -> None:
    global _collective_delay
    delay, _collective_delay = _collective_delay, 0.0
    if delay:
        time.sleep(delay)


def all_ranks_value(value: float):
    """Share one host scalar per process with every process.

    Returns the replicated ``dims``-shaped numpy array (one entry per
    block; every block a process owns carries that process's value), or
    None on single-process grids — the probe is strictly a cross-process
    diagnostic.  COLLECTIVE: every process must call it together.
    """
    import jax

    from ..parallel import grid as _grid

    if not _grid.grid_is_initialized() or jax.process_count() == 1:
        return None
    _consume_collective_delay()
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES

    gg = _grid.global_grid()
    sharding = NamedSharding(gg.mesh, P(*AXIS_NAMES))
    arr = jax.make_array_from_callback(
        tuple(gg.dims),
        sharding,
        lambda idx: np.full((1, 1, 1), value, np.float32),
    )
    return np.asarray(_skew_fn(gg)(arr))


def skew_probe(step_seconds: float, *, warn: float | None = None) -> dict | None:
    """One all-ranks skew probe over the last window's step wall time.

    Publishes the ``skew.step_seconds_max_over_min`` and
    ``skew.slowest_rank`` gauges on every rank, fires a rank-tagged
    ``skew.straggler`` event (plus the ``skew.straggler_total`` counter)
    when the ratio exceeds ``warn`` (default ``IGG_SKEW_WARN``, built-in
    `SKEW_WARN_DEFAULT`; 0 disables the event).  Returns the probe result
    dict, or None on single-process grids (skipped entirely — no
    collective, no gauges).  Collective; call at a deterministic cadence
    on every process (the heartbeat cadence of the instrumented loops).
    """
    vals = all_ranks_value(float(step_seconds))
    if vals is None:
        return None
    import numpy as np

    from ..parallel import grid as _grid

    gg = _grid.global_grid()
    vmax = float(np.max(vals))
    vmin = float(np.min(vals))
    ratio = vmax / vmin if vmin > 0 else float("inf") if vmax > 0 else 1.0
    slow_coords = tuple(
        int(c) for c in np.unravel_index(int(np.argmax(vals)), vals.shape)
    )
    slowest_rank = int(gg.mesh.devices[slow_coords].process_index)
    _telemetry.gauge("skew.step_seconds_max_over_min").set(ratio)
    _telemetry.gauge("skew.slowest_rank").set(slowest_rank)
    if warn is None:
        env = _config.skew_warn_env()
        warn = SKEW_WARN_DEFAULT if env is None else env
    result = {
        "ratio": ratio,
        "slowest_rank": slowest_rank,
        "slowest_coords": list(slow_coords),
        "max_s": vmax,
        "min_s": vmin,
        "mine_s": float(step_seconds),
    }
    if warn and ratio > warn:
        _telemetry.counter("skew.straggler_total").inc()
        _telemetry.event("skew.straggler", warn=warn, **result)
    return result


# -- flight recorder ----------------------------------------------------------


def flight_filename(rank: int) -> str:
    return f"flight_{rank}.json"


def _active_config() -> dict:
    """The run's active configuration for a flight bundle: every ``IGG_*``
    env var plus the live grid's identity (when one is up)."""
    cfg: dict[str, Any] = {
        "env": {k: v for k, v in os.environ.items() if k.startswith("IGG_")},
    }
    try:
        from ..parallel import grid as _grid

        if _grid.grid_is_initialized():
            gg = _grid.global_grid()
            cfg["grid"] = {
                "nxyz_g": list(gg.nxyz_g),
                "nxyz": list(gg.nxyz),
                "dims": list(gg.dims),
                "coords": list(gg.coords),
                "periods": list(gg.periods),
                "overlaps": list(gg.overlaps),
                "nprocs": gg.nprocs,
                "me": gg.me,
                "epoch": gg.epoch,
            }
    except Exception:  # the recorder must never raise out of a crash path
        pass
    return cfg


def dump_flight_recorder(reason: str, **info: Any) -> str | None:
    """Dump the crash flight-recorder bundle for this rank.

    One JSON object — ``{ts, reason, rank, pid, coords, info, config,
    metrics, spans}`` — appended as a single ``O_APPEND`` line to
    ``flight_<rank>.json`` under ``IGG_TELEMETRY_DIR`` (several trips
    append several lines; the last line is the newest bundle).  Crash-safe
    by the event-log discipline: the write is one ``os.write`` of a
    complete line, so a hard ``os._exit`` immediately after loses nothing.
    Returns the path, or None when telemetry is off / no directory is set.
    Never raises: a failing recorder must not mask the fault it records.
    """
    try:
        if not _telemetry.enabled():
            return None
        directory = _config.telemetry_dir_env()
        if not directory:
            return None
        rank = _telemetry._proc_index()
        bundle = {
            "ts": time.time(),
            "reason": reason,
            "rank": rank,
            "pid": os.getpid(),
            "coords": _telemetry._grid_coords(),
            "info": info,
            "config": _active_config(),
            "metrics": _telemetry.snapshot(),
            # Closed ring PLUS the spans currently executing (``open:
            # true``, every thread): the span you most want at crash time
            # is the one that was in flight when the run died.
            "spans": span_records() + open_spans(),
        }
        try:
            # An in-flight device capture (utils.profiling): a crash
            # mid-window is explained by its dir/window/step — and the
            # post-mortem knows a partial profiler dir is expected.
            from . import profiling as _profiling

            cap = _profiling.active_capture()
            if cap is not None:
                bundle["profile"] = cap
        except Exception:
            pass
        try:
            line = json.dumps(bundle, default=str) + "\n"
        except (TypeError, ValueError):
            line = json.dumps(
                {k: str(v) for k, v in bundle.items()}
            ) + "\n"
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, flight_filename(rank))
        fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)
        _telemetry.counter("resilience.flight_dumps").inc()
        return path
    except Exception:
        return None


def read_flight_bundles(path: str | os.PathLike) -> list[dict]:
    """Parse one ``flight_<rank>.json`` (one bundle per line, torn trailing
    line skipped — the `telemetry.read_events` contract)."""
    return _telemetry.read_events(path)


def reset() -> None:
    """Drop the span ring, open/context stacks, drop counter, clock sync
    and probe caches (test hook)."""
    global _ring, _ring_cap, _clock_sync, _collective_delay, _spans_dropped
    with _lock:
        _ring = None
        _ring_cap = 0
    _open_stacks.clear()
    _ctx_stacks.clear()
    _spans_dropped = 0
    _clock_sync = None
    _collective_delay = 0.0
    _skew_cache.clear()
