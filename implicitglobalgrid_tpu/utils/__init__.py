"""Index math, field constructors and configuration."""
