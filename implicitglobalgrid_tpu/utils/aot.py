"""Synthetic-GlobalGrid AOT scaffolding.

Multi-chip TPU hardware is not attached in the build environment, but the
runtime CAN compile for detached topologies
(`jax.experimental.topologies.get_topology_desc`) — the basis of every
multi-chip structural check (`scripts/verify_tpu.py` checks 6/9/10/11, the
`benchmarks/run.py::aot_weak_proxy` north-star record).  They all need the
same scaffold: resolve a topology description, build a ``dims`` mesh over
its devices, and install a synthetic `GlobalGrid` carrying that mesh so the
per-block program builders (models, halo ops) trace against the multi-chip
topology.  One implementation here, so a change to the swap/restore
protocol (or a new `GlobalGrid` field) cannot drift between the four users.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math

#: Topology-name candidates per chip count, tried in order (the leading
#: ``{kind}`` entries resolve on the attached generation; the literal ones
#: are fallbacks for runtimes whose device_kind string differs).
_TOPOLOGY_NAMES = {
    8: ("{kind}:2x2x2", "{kind}:2x4", "v5e:2x4"),
    16: ("{kind}:4x4", "v5e:4x4", "v5litepod-16"),
    256: ("{kind}:16x16", "v5e:16x16", "v5litepod-256"),
}


def _derived_topology_names(nchips: int) -> tuple[str, ...]:
    """``{kind}:AxB`` candidates derived for chip counts not in the table.

    Square-ish 2-D factorization, largest divisor ``a <= sqrt(nchips)``
    first — the same shapes the curated `_TOPOLOGY_NAMES` entries use
    (8 -> 2x4, 16 -> 4x4, 256 -> 16x16), so an uncurated count (e.g. 64)
    still gets a plausible slice name instead of an immediate failure.
    """
    a = max(d for d in range(1, int(math.isqrt(nchips)) + 1) if nchips % d == 0)
    b = nchips // a
    names = [f"{{kind}}:{a}x{b}"]
    if a != b:
        names.append(f"{{kind}}:{b}x{a}")
    return tuple(names)


def topology_mesh(dims):
    """An ``("x","y","z")`` `Mesh` of ``prod(dims)`` detached-topology devices.

    Raises ``RuntimeError`` when no topology description resolves — the one
    legitimate skip reason for AOT checks.  The error carries every
    candidate's own failure (ADVICE r5 low #2): a misconfigured runtime
    used to surface as a bare "no topology available" with the per-name
    exceptions swallowed.
    """
    import numpy as np

    import jax
    from jax.experimental import topologies
    from jax.sharding import Mesh

    nchips = math.prod(dims)
    kind = jax.devices()[0].device_kind
    names = _TOPOLOGY_NAMES.get(nchips) or _derived_topology_names(nchips)
    topo = None
    failures: list[str] = []
    for name in names:
        resolved = name.format(kind=kind)
        try:
            topo = topologies.get_topology_desc(
                platform="tpu", topology_name=resolved
            )
            break
        except Exception as e:
            failures.append(f"{resolved}: {type(e).__name__}: {e}")
            continue
    if topo is None:
        detail = "; ".join(failures) if failures else "no candidates tried"
        raise RuntimeError(
            f"no AOT topology description available for {nchips} chips "
            f"(dims={tuple(dims)}); candidates failed with: {detail}"
        )
    devs = np.asarray(topo.devices)[:nchips].reshape(dims)
    return Mesh(devs, ("x", "y", "z"))


@contextlib.contextmanager
def synthetic_topology_grid(dims, nloc, overlaps=(2, 2, 2)):
    """Install a synthetic multi-chip `GlobalGrid` for AOT lowering.

    Initializes a real 1-device grid with local shape ``nloc`` and
    ``overlaps`` (so every derived quantity — implicit global size, halo
    widths, timing functions — is built by the public path), then swaps in
    a copy carrying the detached-topology ``dims`` mesh.  Yields
    ``(gg, mesh)``; the grid is restored and finalized on exit.  Refuses to
    run with a live caller grid rather than silently destroying it.
    """
    import jax

    from ..parallel import grid as _grid

    if _grid.grid_is_initialized():
        raise RuntimeError(
            "synthetic_topology_grid needs a clean slate: finalize the "
            "current global grid first."
        )
    mesh = topology_mesh(dims)  # before init: a topology failure must skip cleanly
    nx, ny, nz = nloc
    ox, oy, oz = overlaps
    _grid.init_global_grid(
        nx, ny, nz, overlapx=ox, overlapy=oy, overlapz=oz, quiet=True,
        devices=list(jax.devices())[:1],
    )
    gg0 = _grid.get_global_grid()
    gg = dataclasses.replace(
        gg0, mesh=mesh, dims=tuple(dims), nprocs=math.prod(dims), coords=(0, 0, 0)
    )
    _grid.set_global_grid(gg)
    try:
        yield gg, mesh
    finally:
        _grid.set_global_grid(gg0)
        _grid.finalize_global_grid()
