"""Optimized-HLO dataflow analysis for the overlap-evidence checks.

Shared by `tests/test_stencil_overlap.py` (8-device CPU mesh, differential
control) and `scripts/verify_tpu.py` (AOT TPU topology program): one parser,
one transitive-closure walk, one fusion-size heuristic — so a fix to the
analyzer cannot drift between the test and the hardware check.

The schedulability criterion: a collective-permute whose transitive operand
closure contains the full-block interior fusion can only start AFTER the
interior finishes (a barrier); one whose closure holds only slab-sized ops is
free to fly while the interior computes — the structural freedom
`hide_communication` exists to create (the reference's analogous mechanism is
its max-priority streams, `/root/reference/src/update_halo.jl:424`).
"""

from __future__ import annotations

import re

# Instruction name + everything after '='.  The type is NOT captured as one
# token: TPU HLO tuple types contain spaces and nested parens
# (`(f32[1,16,16]{1,0,2:T(1,128)S(1)}, u32[]{:S(2)})`), so the op kind and
# operand refs are extracted from the remainder instead.
_INST_RE = re.compile(r"\s*(?:ROOT\s+)?%?([\w\.\-]+)\s*=\s*(.*)$")


def parse_computations(txt: str) -> dict[str, list[str]]:
    """Split optimized HLO text into {computation_name: [instruction lines]}."""
    comps: dict[str, list[str]] = {}
    cur = None
    for line in txt.splitlines():
        if line.rstrip().endswith("{") and "(" in line:
            cur = line.split("(")[0].strip()
            cur = cur[len("ENTRY "):] if cur.startswith("ENTRY ") else cur
            cur = cur.lstrip("%")
            comps[cur] = []
        elif line.strip() == "}":
            cur = None
        elif cur is not None:
            comps[cur].append(line)
    return comps


def _out_elems(typ: str) -> int:
    """Largest array size in an HLO type string (handles tuple types)."""
    best = 0
    for shp in re.findall(r"\[([\d,]*)\]", typ):
        if shp:
            p = 1
            for x in shp.split(","):
                p *= int(x)
            best = max(best, p)
    return best


def _op_kind(rest: str) -> str:
    """Classify the instruction from the text after '='."""
    if "collective-permute-start(" in rest:
        return "collective-permute-start"
    if "collective-permute-done(" in rest:
        return "collective-permute-done"
    if re.search(r"\bcollective-permute\(", rest):
        return "collective-permute"
    if re.search(r"\bfusion\(", rest):
        return "fusion"
    if re.search(r"\bcustom-call\(", rest):
        return "custom-call"
    return "other"


_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "u64": 8, "s64": 8, "u32": 4, "s32": 4, "u16": 2, "s16": 2,
    "u8": 1, "s8": 1, "pred": 1,
}
_ARRAY_RE = re.compile(r"(f64|f32|bf16|f16|u64|s64|u32|s32|u16|s16|u8|s8|pred)\[([\d,]*)\]")


def collective_payloads(txt: str) -> list[dict]:
    """Per-hop payloads of every collective-permute in an optimized program.

    Returns one record per ``collective-permute``/``collective-permute-start``
    instruction: ``{"shape", "dtype", "bytes"}`` — every non-scalar array in
    the instruction's (possibly tuple) type is payload (a combined /
    multi-operand permute moves all of them in one hop; scalars are the
    async-start ops' u32 context, not payload).  The per-hop *byte* count is
    what a weak-scaling budget needs: payload ÷ link bandwidth + hop latency
    vs the measured step time.

    Async-start tuples list each moved buffer TWICE — ``(aliased
    operands..., results..., contexts...)`` — so the RESULT half is counted,
    verified by matching the two halves elementwise (ADVICE r5 low #3: the
    old blind ``total //= 2`` could silently skew the budget's per-hop
    bytes on any tuple shape drift).  A start op whose array list does not
    split into two identical halves falls back to the raw sum and flags it
    (``"payload_fallback": "raw-sum"``) so a budget consumer can see the
    number is an upper bound, not silently half-wrong.
    """
    out = []
    for lines in parse_computations(txt).values():
        for l in lines:
            m = _INST_RE.match(l)
            if not m:
                continue
            _, rest = m.groups()
            kind = _op_kind(rest)
            if kind not in ("collective-permute", "collective-permute-start"):
                continue
            head = rest.split("collective-permute")[0]
            arrays = []  # (type string, bytes) per non-scalar array
            for dt, shp in _ARRAY_RE.findall(head):
                if not shp:
                    continue
                elems = 1
                for x in shp.split(","):
                    elems *= int(x)
                arrays.append((f"{dt}[{shp}]", elems * _DTYPE_BYTES[dt]))
            if not arrays:
                continue
            fallback = None
            if kind == "collective-permute-start":
                half = len(arrays) // 2
                if len(arrays) % 2 == 0 and arrays[:half] == arrays[half:]:
                    arrays = arrays[half:]  # the results half
                else:
                    fallback = "raw-sum"
            rec = {
                "shape": ",".join(a[0] for a in arrays),
                "dtype": arrays[0][0].split("[")[0],
                "bytes": sum(a[1] for a in arrays),
            }
            if fallback:
                rec["payload_fallback"] = fallback
            out.append(rec)
    return out


def pipelined_overlap_evidence(txt: str) -> dict:
    """Structural evidence that a program schedules kernel launches across
    its collectives — the pipelined group schedule's HLO check.

    For every computation holding both collective-permutes and
    custom-calls (the Pallas kernel launches are ``custom-call``s in the
    optimized program), count the (collective, custom-call) pairs with NO
    transitive dependency in either direction: XLA's scheduler is free to
    run such a pair concurrently.  The serialized schedule has none (every
    kernel launch feeds or consumes every group-boundary exchange); the
    pipelined schedule's interior passes are exactly the launches built to
    be independent of the in-flight permutes.

    Returns ``{"n_collectives", "n_custom_calls", "independent_pairs",
    "overlappable_collectives"}`` (the last: collectives with at least one
    independent kernel launch).
    """
    n_cp, n_cc, pairs, overlappable = 0, 0, 0, 0
    for lines in parse_computations(txt).values():
        if not any("collective-permute" in l for l in lines):
            continue
        insts: dict[str, tuple[str, str, list[str]]] = {}
        for l in lines:
            m = _INST_RE.match(l)
            if m:
                name, rest = m.groups()
                insts[name] = (_op_kind(rest), rest, re.findall(r"%([\w\.\-]+)", rest))

        def closure(n):
            seen: set = set()
            stack = [n]
            while stack:
                for o in insts.get(stack.pop(), (None, None, []))[2]:
                    if o not in seen:
                        seen.add(o)
                        stack.append(o)
            return seen

        cps = [
            n
            for n, (op, _, _) in insts.items()
            if op in ("collective-permute", "collective-permute-start")
        ]
        ccs = [n for n, (op, _, _) in insts.items() if op == "custom-call"]
        if not ccs:
            continue
        n_cp += len(cps)
        n_cc += len(ccs)
        cc_closures = {c: closure(c) for c in ccs}
        # An async collective is "independent" of a launch when neither its
        # start nor its done reaches the launch (and vice versa); dones are
        # found as consumers via the start's name appearing in closures.
        for cp in cps:
            cp_clo = closure(cp)
            free = [
                cc
                for cc in ccs
                if cc not in cp_clo and cp not in cc_closures[cc]
            ]
            pairs += len(free)
            if free:
                overlappable += 1
    return {
        "n_collectives": n_cp,
        "n_custom_calls": n_cc,
        "independent_pairs": pairs,
        "overlappable_collectives": overlappable,
    }


#: HLO op-NAME vocabulary for device-timeline classification
#: (`utils.profiling`): profiler trace events carry instruction NAMES
#: (``collective-permute-start.3``, ``pad_add_fusion.1``, ``copy.17``), not
#: instruction text, so this is the name-based sibling of `_op_kind` — one
#: blessed vocabulary for both the HLO-text analyzers and the trace parser.
#: "collective" moves bytes over the fabric; "kernel" is real compute
#: (fusions, custom-calls — the Pallas launches — and the standalone
#: heavyweights); everything else is "glue": copies, slices, control flow,
#: layout shuffling — the cadence overhead per-op attribution exists to
#: localize.
COLLECTIVE_OP_TOKENS = (
    "collective-permute",
    "all-reduce",
    "all-gather",
    "all-to-all",
    "reduce-scatter",
    "collective-broadcast",
)

KERNEL_OP_TOKENS = ("fusion", "custom-call", "convolution", "dot")


def classify_op_name(name: str) -> str:
    """Classify one HLO instruction NAME as ``collective`` | ``kernel`` |
    ``glue``.

    Matches the vocabulary tokens as substrings of the name with any
    trailing ``.N`` suffix intact (XLA embeds the op kind in generated
    names: ``select_dynamic-update-slice_fusion.1`` is a fusion,
    ``collective-permute-start.3`` a collective).  A name holding both a
    collective and a kernel token classifies as collective — a fused
    collective still occupies the fabric.
    """
    low = name.lower()
    for tok in COLLECTIVE_OP_TOKENS:
        if tok in low:
            return "collective"
    for tok in KERNEL_OP_TOKENS:
        if tok in low:
            return "kernel"
    return "glue"


def collective_waits(txt: str, big_elems: int) -> tuple[int, list[bool], int]:
    """Analyze every HLO computation holding collective-permutes.

    Returns ``(n_collectives, waits, n_async)`` where ``waits[i]`` says
    whether collective ``i`` (sync ``collective-permute`` or async
    ``collective-permute-start``) transitively depends on a fusion with
    >= ``big_elems`` output elements, and ``n_async`` counts the async
    start ops (TPU backend; the CPU backend emits sync collectives only).
    Closures are computed within each computation — the collectives and the
    interior fusion always share one (the SPMD entry or a loop body).
    """
    n_total, waits_all, n_async = 0, [], 0
    for lines in parse_computations(txt).values():
        if not any("collective-permute" in l for l in lines):
            continue
        insts: dict[str, tuple[str, str, list[str]]] = {}
        for l in lines:
            m = _INST_RE.match(l)
            if m:
                name, rest = m.groups()
                insts[name] = (_op_kind(rest), rest, re.findall(r"%([\w\.\-]+)", rest))

        big = {
            n
            for n, (op, rest, _) in insts.items()
            if op == "fusion" and _out_elems(rest) >= big_elems
        }

        def closure(n, seen):
            stack = [n]
            while stack:  # iterative: deep programs exceed the recursion limit
                for o in insts.get(stack.pop(), (None, None, []))[2]:
                    if o not in seen:
                        seen.add(o)
                        stack.append(o)
            return seen

        cps = [
            n
            for n, (op, _, _) in insts.items()
            if op in ("collective-permute", "collective-permute-start")
        ]
        n_total += len(cps)
        waits_all += [bool(closure(c, set()) & big) for c in cps]
        n_async += sum(
            1 for op, _, _ in insts.values() if op == "collective-permute-start"
        )
    return n_total, waits_all, n_async
