"""Optimized-HLO dataflow analysis for the overlap-evidence checks.

Shared by `tests/test_stencil_overlap.py` (8-device CPU mesh, differential
control) and `scripts/verify_tpu.py` (AOT TPU topology program): one parser,
one transitive-closure walk, one fusion-size heuristic — so a fix to the
analyzer cannot drift between the test and the hardware check.

The schedulability criterion: a collective-permute whose transitive operand
closure contains the full-block interior fusion can only start AFTER the
interior finishes (a barrier); one whose closure holds only slab-sized ops is
free to fly while the interior computes — the structural freedom
`hide_communication` exists to create (the reference's analogous mechanism is
its max-priority streams, `/root/reference/src/update_halo.jl:424`).
"""

from __future__ import annotations

import re

# Instruction name + everything after '='.  The type is NOT captured as one
# token: TPU HLO tuple types contain spaces and nested parens
# (`(f32[1,16,16]{1,0,2:T(1,128)S(1)}, u32[]{:S(2)})`), so the op kind and
# operand refs are extracted from the remainder instead.
_INST_RE = re.compile(r"\s*(?:ROOT\s+)?%?([\w\.\-]+)\s*=\s*(.*)$")


def parse_computations(txt: str) -> dict[str, list[str]]:
    """Split optimized HLO text into {computation_name: [instruction lines]}."""
    comps: dict[str, list[str]] = {}
    cur = None
    for line in txt.splitlines():
        if line.rstrip().endswith("{") and "(" in line:
            cur = line.split("(")[0].strip()
            cur = cur[len("ENTRY "):] if cur.startswith("ENTRY ") else cur
            cur = cur.lstrip("%")
            comps[cur] = []
        elif line.strip() == "}":
            cur = None
        elif cur is not None:
            comps[cur].append(line)
    return comps


def _out_elems(typ: str) -> int:
    """Largest array size in an HLO type string (handles tuple types)."""
    best = 0
    for shp in re.findall(r"\[([\d,]*)\]", typ):
        if shp:
            p = 1
            for x in shp.split(","):
                p *= int(x)
            best = max(best, p)
    return best


def _op_kind(rest: str) -> str:
    """Classify the instruction from the text after '='."""
    if "collective-permute-start(" in rest:
        return "collective-permute-start"
    if "collective-permute-done(" in rest:
        return "collective-permute-done"
    if re.search(r"\bcollective-permute\(", rest):
        return "collective-permute"
    if re.search(r"\bfusion\(", rest):
        return "fusion"
    return "other"


def collective_waits(txt: str, big_elems: int) -> tuple[int, list[bool], int]:
    """Analyze every HLO computation holding collective-permutes.

    Returns ``(n_collectives, waits, n_async)`` where ``waits[i]`` says
    whether collective ``i`` (sync ``collective-permute`` or async
    ``collective-permute-start``) transitively depends on a fusion with
    >= ``big_elems`` output elements, and ``n_async`` counts the async
    start ops (TPU backend; the CPU backend emits sync collectives only).
    Closures are computed within each computation — the collectives and the
    interior fusion always share one (the SPMD entry or a loop body).
    """
    n_total, waits_all, n_async = 0, [], 0
    for lines in parse_computations(txt).values():
        if not any("collective-permute" in l for l in lines):
            continue
        insts: dict[str, tuple[str, str, list[str]]] = {}
        for l in lines:
            m = _INST_RE.match(l)
            if m:
                name, rest = m.groups()
                insts[name] = (_op_kind(rest), rest, re.findall(r"%([\w\.\-]+)", rest))

        big = {
            n
            for n, (op, rest, _) in insts.items()
            if op == "fusion" and _out_elems(rest) >= big_elems
        }

        def closure(n, seen):
            stack = [n]
            while stack:  # iterative: deep programs exceed the recursion limit
                for o in insts.get(stack.pop(), (None, None, []))[2]:
                    if o not in seen:
                        seen.add(o)
                        stack.append(o)
            return seen

        cps = [
            n
            for n, (op, _, _) in insts.items()
            if op in ("collective-permute", "collective-permute-start")
        ]
        n_total += len(cps)
        waits_all += [bool(closure(c, set()) & big) for c in cps]
        n_async += sum(
            1 for op, _, _ in insts.values() if op == "collective-permute-start"
        )
    return n_total, waits_all, n_async
