"""Live telemetry plane: per-rank scrape endpoints + in-flight anomaly rules.

PR 9 made the repo diagnosable *after the fact* — trace dumps, merged
timelines, crash flight bundles — but every signal was pull-from-disk and
every quantile a run-lifetime reservoir.  This module is the live half of
the observability stack (docs/observability.md, "live plane" tier): the
machine-readable, continuously-scraped equivalent of the reference's human
watching ``tic``/``toc`` lines scroll by.

* **Per-rank endpoints** — `ensure_server` starts ONE daemon-thread HTTP
  server per process when ``IGG_METRICS_PORT`` is set (port 0 = ephemeral;
  the bound port is published via the ``liveplane.port`` gauge, the rank-0
  heartbeat event and a ``liveplane.p<rank>.json`` endpoint file under
  ``IGG_TELEMETRY_DIR`` — the discovery surface ``scripts/igg_top.py``
  scrapes).  Endpoints, all read-only snapshots taken under the registry
  lock, ZERO collectives:

  - ``/metrics`` — the existing `telemetry.prometheus_text` exposition,
    byte-identical to what `telemetry.dump_metrics` writes for the same
    snapshot;
  - ``/healthz`` — rank, grid identity/coords, uptime, last-step age,
    guard/watchdog counters from `utils.resilience`, the current skew
    verdict, the rolling ``slo`` quantiles and a bounded ``alerts``
    section (`health_snapshot`);
  - ``/spans`` — the `utils.tracing` ring (plus currently-open spans) as
    JSON; ``?name=<substring>`` and ``?request=<trace_id>`` narrow the
    view to one span family or one request's causal slice.

  With ``IGG_TELEMETRY=0`` the server never starts — the PR-4
  no-op-singleton contract extends to the whole plane.

* **Rolling SLO windows** — `publish_slo_gauges` turns every histogram's
  sliding-window view (`telemetry.Histogram.window_summary`, window length
  ``IGG_SLO_WINDOW_S``) into the ``slo.<metric>.p50/p90/p99`` gauge family
  for ``step_seconds``, ``t_eff_gbs`` and the serving round/member
  latencies — live quantiles over the last `telemetry.SLO_WINDOWS`
  windows, not since process start.

* **In-flight anomaly detection** — a pluggable `RuleEngine` evaluated at
  heartbeat cadence on each rank (`heartbeat_tick`, wired into the models'
  instrumented loops and `ServingLoop`) AND at ``/healthz`` scrape time
  (the only vantage that can see a stalled loop from outside it).  Each
  rule transition fires ONE structured ``alert.<rule>`` event
  (rank/severity/evidence-tagged, riding the PR-4 event log) and lands in
  the bounded ``alerts`` ring `health_snapshot` exposes; subscribers
  (`subscribe` — `resilience.guarded_time_loop` and
  `serving.ServingLoop`) escalate critical alerts into the existing
  guard/evict machinery instead of leaving them log lines nobody reads.

Layering: imports `config`, `telemetry` and `tracing` only; jax and the
grid are never touched (the plane must serve while the accelerator side is
wedged — that is exactly when an operator scrapes it).
"""

from __future__ import annotations

import collections
import http.server
import json
import os
import socket
import threading
import time
import urllib.parse as _urlparse
from typing import Any, Callable

from . import config as _config
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = [
    "enabled",
    "ensure_server",
    "start_server",
    "stop_server",
    "server_port",
    "endpoint_filename",
    "health_snapshot",
    "slo_view",
    "publish_slo_gauges",
    "heartbeat_tick",
    "Rule",
    "RuleEngine",
    "get_engine",
    "register_rule",
    "subscribe",
    "unsubscribe",
    "alerts_since",
    "set_teff_expectation",
    "teff_expectation",
    "reset",
]

#: wall-clock at module import: the uptime baseline ``/healthz`` reports
_T0 = time.time()

#: bound on the recent-alerts ring (`RuleEngine`) and the ``alerts``
#: section of ``/healthz`` — however long the run, however noisy the rules
ALERTS_KEEP = 32


def enabled() -> bool:
    """The live plane can start: telemetry on AND ``IGG_METRICS_PORT`` set."""
    return _telemetry.enabled() and _config.metrics_port_env() is not None


# -- health snapshot ----------------------------------------------------------


def _grid_identity() -> dict | None:
    try:
        from ..parallel import grid as _grid

        if _grid.grid_is_initialized():
            gg = _grid.global_grid()
            return {
                "nxyz_g": list(gg.nxyz_g),
                "nxyz": list(gg.nxyz),
                "dims": list(gg.dims),
                "coords": list(gg.coords),
                "nprocs": gg.nprocs,
                "me": gg.me,
                "epoch": gg.epoch,
            }
    except Exception:  # the health view must never raise out of a scrape
        pass
    return None


def health_snapshot(snap: dict | None = None) -> dict:
    """The ``/healthz`` document: one JSON-serializable dict per scrape.

    ``ok`` is False while any CRITICAL alert is active.  ``slo`` carries
    each histogram's rolling-window quantiles (absent until something
    recorded into a window); ``skew``/``serving`` appear only when their
    gauges were published — absence is meaningful, never zero-filled (the
    heartbeat-event convention).  ``snap`` shares the caller's registry
    snapshot (the scrape handler takes exactly one per request).
    """
    if snap is None:
        snap = _telemetry.snapshot()
    eng = get_engine()
    active = eng.active_alerts()
    doc: dict[str, Any] = {
        "ok": not any(a["severity"] == "critical" for a in active),
        "ts": snap["ts"],
        "rank": snap["rank"],
        "pid": snap["pid"],
        "coords": snap["coords"],
        "uptime_s": time.time() - _T0,
        "telemetry_enabled": snap["enabled"],
    }
    grid = _grid_identity()
    if grid is not None:
        doc["grid"] = grid
    prog = _telemetry.last_progress()
    if prog is not None:
        doc["last_step"] = prog
    doc.update(_health_tail(snap, eng, active))
    return doc


def slo_view(snap: dict) -> dict:
    """``{histogram name: rolling-window summary}`` of a registry snapshot
    — the live-quantile view ``/healthz``'s ``slo`` section serves and
    ``bench.py`` ships as ``extras.telemetry.slo_windows`` (one helper so
    the two can never drift apart)."""
    return {
        name: s["window"]
        for name, s in snap.get("histograms", {}).items()
        if "window" in s
    }


def _health_tail(snap: dict, eng: "RuleEngine", active: list[dict]) -> dict:
    """The registry-derived sections of the health document (guard/skew/
    serving/slo/liveplane/alerts)."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    doc: dict[str, Any] = {}
    doc["guard"] = {
        "trips": counters.get("resilience.guard_trips", 0),
        "rollbacks": counters.get("resilience.rollbacks", 0),
        "watchdog_deadline_exceeded": counters.get(
            "resilience.watchdog_deadline_exceeded", 0
        ),
        "retries": counters.get("resilience.retries", 0),
        "flight_dumps": counters.get("resilience.flight_dumps", 0),
    }
    ratio = gauges.get("skew.step_seconds_max_over_min")
    if ratio is not None:
        doc["skew"] = {
            "step_seconds_max_over_min": ratio,
            "slowest_rank": gauges.get("skew.slowest_rank"),
            "straggler_total": counters.get("skew.straggler_total", 0),
        }
    if "serving.active_members" in gauges:
        doc["serving"] = {
            "active_members": gauges["serving.active_members"],
            "queue_depth": gauges.get("serving.queue_depth"),
            "capacity": gauges.get("serving.capacity"),
        }
        # Worst in-flight request age, computed at scrape time from the
        # front door's oldest-submit gauge (a precomputed age would go
        # stale between scrapes; a timestamp cannot).
        oldest = gauges.get("frontdoor.oldest_submitted_ts")
        if oldest:
            doc["serving"]["oldest_request_age_s"] = round(
                max(0.0, time.time() - oldest), 3
            )
    if "frontdoor.port" in gauges or "frontdoor.requests_total" in counters:
        # The network-facing plane (serving.frontdoor, docs/serving.md):
        # admission totals + per-reason rejects + per-tenant counters, so
        # one /healthz scrape answers "who is being turned away and why".
        rejected = {
            name[len("frontdoor.rejected."):]: v
            for name, v in counters.items()
            if name.startswith("frontdoor.rejected.")
        }
        tenants: dict[str, dict] = {}
        for name, v in counters.items():
            if not name.startswith("frontdoor.tenant."):
                continue
            tenant, _, kind = name[len("frontdoor.tenant."):].rpartition(".")
            if tenant:
                tenants.setdefault(tenant, {})[kind] = v
        doc["frontdoor"] = {
            "port": gauges.get("frontdoor.port"),
            "pending": gauges.get("frontdoor.pending"),
            "backpressure": gauges.get("frontdoor.backpressure"),
            "requests_total": counters.get("frontdoor.requests_total", 0),
            "admitted_total": counters.get("frontdoor.admitted_total", 0),
            "rejected_total": counters.get("frontdoor.rejected_total", 0),
            "rejected": rejected,
            "tenants": tenants,
        }
    slo = slo_view(snap)
    if slo:
        doc["slo"] = slo
    port = gauges.get("liveplane.port")
    if port is not None:
        doc["liveplane"] = {"port": int(port)}
    doc["alerts"] = {
        "active": active,
        "recent": eng.recent_alerts(),
        # from the engine, not the counter snapshot: alerts fired by THIS
        # scrape's rule evaluation must already be visible in its response
        "fired_total": eng.fired_total(),
    }
    return doc


# -- rolling SLO gauges -------------------------------------------------------

#: histogram-name suffixes promoted into the ``slo.*`` gauge family — the
#: step-latency, throughput, serving-round and front-door request-latency
#: families ROADMAP item 3 keys admission control on
_SLO_SUFFIXES = ("step_seconds", "t_eff_gbs", "round_seconds",
                 "request_seconds")


def publish_slo_gauges(snap: dict | None = None) -> dict:
    """Publish ``slo.<metric>.p50/p90/p99`` gauges from the rolling windows.

    Returns ``{metric: window summary}`` for the histograms that had live
    window data.  No-op (empty dict) when telemetry is disabled.
    """
    if not _telemetry.enabled():
        return {}
    if snap is None:
        snap = _telemetry.snapshot()
    out = {}
    for name, s in snap.get("histograms", {}).items():
        win = s.get("window")
        if not win or not name.endswith(_SLO_SUFFIXES):
            continue
        out[name] = win
        for q in ("p50", "p90", "p99"):
            v = win.get(q)
            if v is not None:
                _telemetry.gauge(f"slo.{name}.{q}").set(v)
    return out


# -- anomaly rules ------------------------------------------------------------

# Explicit T_eff expectations (GB/s) per model — the reconcile-derived
# prior: `analysis/reconcile.py`'s bytes model converts a roofline (or a
# bench-record) figure into the T_eff the convention should sustain
# (``modeled_actual_gbs * achieved_fraction``); whoever holds that number
# (bench harness, deployment config) stages it here and `TeffDropRule`
# checks live windows against it.  Without one, the rule self-calibrates
# on the run's own lifetime p90 — a regression-from-own-baseline alarm.
_teff_expectations: dict[str, float] = {}


def set_teff_expectation(model: str, gbs: float | None) -> None:
    """Stage (or clear, with None) the expected T_eff for ``model``."""
    if gbs is None:
        _teff_expectations.pop(model, None)
    else:
        _teff_expectations[model] = float(gbs)


def teff_expectation(model: str) -> float | None:
    return _teff_expectations.get(model)


class Rule:
    """One anomaly rule: ``check(ctx)`` returns an evidence dict while the
    anomalous condition holds, else None.  ``ctx`` carries ``now``,
    ``source`` (``"heartbeat"`` | ``"scrape"``), ``snapshot`` (the registry),
    ``progress`` (`telemetry.last_progress`) and ``rss``.  The engine
    latches per rule: ONE ``alert.<name>`` event per continuous episode
    (re-arming when the condition clears).  Rules must be cheap and local
    — they run inside the step loop's heartbeat and the scrape handler."""

    name = "rule"
    severity = "warn"

    def check(self, ctx: dict) -> dict | None:  # pragma: no cover - abstract
        raise NotImplementedError


class TeffDropRule(Rule):
    """Windowed T_eff p50 below a fraction of the expectation.

    Expectation: the staged reconcile-derived prior (`set_teff_expectation`)
    when one exists, else the run's own lifetime p90 (self-calibrating).
    Warm-up guarded: needs ``min_total`` lifetime samples and
    ``min_window`` samples in the live window before judging.
    """

    name = "teff_drop"
    severity = "warn"

    def __init__(self, fraction: float = 0.5, *, min_window: int = 4,
                 min_total: int = 20):
        self.fraction = fraction
        self.min_window = min_window
        self.min_total = min_total

    def check(self, ctx: dict) -> dict | None:
        for name, s in ctx["snapshot"].get("histograms", {}).items():
            if not name.endswith(".t_eff_gbs"):
                continue
            win = s.get("window")
            if (
                not win
                or win["count"] < self.min_window
                or s["count"] < self.min_total
            ):
                continue
            model = name[: -len(".t_eff_gbs")]
            expect = teff_expectation(model)
            source = "reconcile" if expect is not None else "lifetime_p90"
            if expect is None:
                expect = s.get("p90")
            if not expect:
                continue
            if win["p50"] < self.fraction * expect:
                return {
                    "metric": name,
                    "window_p50_gbs": win["p50"],
                    "expected_gbs": expect,
                    "expectation_source": source,
                    "fraction": self.fraction,
                }
        return None


class SkewSustainedRule(Rule):
    """Skew ratio past ``IGG_SKEW_WARN`` for ``k`` consecutive heartbeat
    windows, fired ONLY on the rank the probe named slowest — every rank
    sees the same gauges, so firing everywhere would be noise without
    attribution."""

    name = "skew_sustained"
    severity = "warn"

    def __init__(self, k: int = 3):
        self.k = k
        self._streak = 0
        self._ev: dict | None = None

    def check(self, ctx: dict) -> dict | None:
        if ctx["source"] != "heartbeat":
            return self._ev  # gauges only move at heartbeat cadence
        gauges = ctx["snapshot"].get("gauges", {})
        ratio = gauges.get("skew.step_seconds_max_over_min")
        slowest = gauges.get("skew.slowest_rank")
        warn = _config.skew_warn_env()
        if warn is None:
            warn = _tracing.SKEW_WARN_DEFAULT
        if (
            ratio is not None
            and warn
            and ratio > warn
            and slowest == ctx["snapshot"]["rank"]
        ):
            self._streak += 1
        else:
            self._streak = 0
            self._ev = None
            return None
        if self._streak >= self.k:
            self._ev = {
                "ratio": ratio,
                "warn": warn,
                "windows": self._streak,
                "slowest_rank": slowest,
            }
        return self._ev


class ConvergenceStallRule(Rule):
    """A watched residual gauge not improving over ``k`` heartbeat windows.

    Defaults to ``serving.pt_residual_min`` (the porous PT residual
    `serving.ServingLoop` publishes each convergence sweep); quiet when
    the gauge does not exist, or when the companion ``<gauge cut to
    prefix>_watched`` population gauge says nothing is being driven
    toward a tolerance (a retired member's frozen residual is not a
    stall).  "Improving" = dropping by at least ``rel_improve`` relative
    to the best value seen this episode; a JUMP past ``1 + jump`` of the
    best resets the episode instead of counting as stagnation — the
    watched population changed (a fresh member starts at a higher
    residual), it did not stall.
    """

    name = "convergence_stall"
    severity = "warn"

    def __init__(self, k: int = 3, *, gauge: str = "serving.pt_residual_min",
                 rel_improve: float = 1e-3, jump: float = 0.5):
        self.k = k
        self.gauge = gauge
        self.watched_gauge = gauge.rsplit("_min", 1)[0] + "_watched"
        self.rel_improve = rel_improve
        self.jump = jump
        self._best: float | None = None
        self._streak = 0
        self._ev: dict | None = None

    def _reset(self, best: float | None = None) -> None:
        self._best, self._streak, self._ev = best, 0, None

    def check(self, ctx: dict) -> dict | None:
        if ctx["source"] != "heartbeat":
            return self._ev
        gauges = ctx["snapshot"].get("gauges", {})
        cur = gauges.get(self.gauge)
        if cur is None or gauges.get(self.watched_gauge) == 0:
            self._reset()
            return None
        if self._best is None or cur < self._best * (1.0 - self.rel_improve):
            self._reset(cur)
            return None
        if cur > self._best * (1.0 + self.jump):
            # population change, not a stall: restart the episode here
            self._reset(cur)
            return None
        self._streak += 1
        if self._streak >= self.k:
            self._ev = {
                "gauge": self.gauge,
                "residual": cur,
                "best": self._best,
                "windows": self._streak,
            }
        return self._ev


class StepStallRule(Rule):
    """Last-step age past the stall deadline — the rule that catches a hung
    loop, which is precisely why it ALSO evaluates at scrape time: a
    stalled loop never reaches its own heartbeat, but the scrape thread
    stays alive and sees the age grow.

    Deadline: ``IGG_WATCHDOG_S`` when set (> 0), else
    ``max(floor_s, factor * p50 step latency)`` from the rolling window
    (falling back to the lifetime p50).  The MEDIAN deliberately, not p99:
    the first step's compile time is a legitimate tail outlier that would
    stretch a p99-based deadline past any real stall.  Quiet before the
    first completed step (bring-up + first compile are not stalls) and
    after a completed run (the server outlives the loop).
    """

    name = "step_stall"
    severity = "critical"

    def __init__(self, *, floor_s: float = 1.0, factor: float = 20.0):
        self.floor_s = floor_s
        self.factor = factor

    def _deadline(self, ctx: dict, kind: str) -> float:
        wd = _config.watchdog_env()
        if wd:
            return wd
        hist = (
            "serving.round_seconds"
            if kind == "serving.round"
            else f"{kind}.step_seconds"
        )
        s = ctx["snapshot"].get("histograms", {}).get(hist, {})
        p50 = s.get("window", {}).get("p50") or s.get("p50")
        return max(self.floor_s, self.factor * p50) if p50 else self.floor_s

    def check(self, ctx: dict) -> dict | None:
        p = ctx.get("progress")
        if not p or p.get("init") or p.get("done"):
            return None
        deadline = self._deadline(ctx, p["kind"])
        if p["age_s"] > deadline:
            return {
                "kind": p["kind"],
                "step": p["step"],
                "age_s": round(p["age_s"], 3),
                "deadline_s": round(deadline, 3),
            }
        return None


class RssGrowthRule(Rule):
    """Process RSS grown past ``factor`` x the first observation (and by at
    least ``min_bytes`` absolute — small processes breathe).  The leak
    tripwire the ``proc.rss_bytes`` heartbeat gauge exists for."""

    name = "rss_growth"
    severity = "warn"

    def __init__(self, factor: float = 1.5, *, min_bytes: int = 256 << 20):
        self.factor = factor
        self.min_bytes = min_bytes
        self._baseline: int | None = None

    def check(self, ctx: dict) -> dict | None:
        rss = ctx.get("rss")
        if rss is None:
            return None
        if self._baseline is None:
            if ctx["source"] == "heartbeat":
                self._baseline = rss  # first heartbeat = steady-state-ish
            return None
        if (
            rss > self.factor * self._baseline
            and rss - self._baseline > self.min_bytes
        ):
            return {
                "rss_bytes": rss,
                "baseline_bytes": self._baseline,
                "growth": round(rss / self._baseline, 3),
            }
        return None


def default_rules() -> list[Rule]:
    return [
        TeffDropRule(),
        SkewSustainedRule(),
        ConvergenceStallRule(),
        StepStallRule(),
        RssGrowthRule(),
    ]


class RuleEngine:
    """Evaluates the rule set, latches per-rule episodes, fans alerts out.

    Thread-safe: ticks arrive from the step loop (heartbeat) AND the
    scrape handler's thread.  Per alert transition: ONE structured
    ``alert.<rule>`` event (rank-tagged via the event log), the
    ``alerts.fired_total`` counter, a slot in the bounded recent ring, and
    one callback per subscriber (exceptions swallowed — an alert consumer
    must never take down the loop that feeds it).
    """

    def __init__(self, rules: list[Rule] | None = None):
        self.rules: list[Rule] = (
            list(rules) if rules is not None else default_rules()
        )
        self._lock = threading.Lock()
        self._active: dict[str, dict] = {}  # rule name -> active alert
        self._recent: collections.deque = collections.deque(maxlen=ALERTS_KEEP)
        self._subscribers: list[Callable[[dict], None]] = []
        self._seq = 0

    # - wiring -

    def register(self, rule: Rule) -> None:
        with self._lock:
            self.rules.append(rule)

    def subscribe(self, fn: Callable[[dict], None]) -> Callable[[dict], None]:
        with self._lock:
            if fn not in self._subscribers:
                self._subscribers.append(fn)
        return fn

    def unsubscribe(self, fn) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    # - evaluation -

    def tick(self, source: str = "heartbeat", model: str | None = None,
             snap: dict | None = None) -> list[dict]:
        """One evaluation pass; returns the alerts that FIRED this tick.
        ``snap`` lets the caller share one registry snapshot across the
        tick and its own rendering (snapshots sort every reservoir under
        the registry lock — one per scrape is enough)."""
        if not _telemetry.enabled():
            return []
        ctx = {
            "now": time.time(),
            "source": source,
            "model": model,
            "snapshot": snap if snap is not None else _telemetry.snapshot(),
            "progress": _telemetry.last_progress(),
            "rss": _telemetry.proc_rss_bytes(),
        }
        fired: list[dict] = []
        with self._lock:
            rules = list(self.rules)
            subscribers = list(self._subscribers)
        for rule in rules:
            try:
                ev = rule.check(ctx)
            except Exception:  # a broken rule must not break the loop/scrape
                continue
            with self._lock:
                was_active = rule.name in self._active
                if ev is None:
                    self._active.pop(rule.name, None)  # episode over: re-arm
                    continue
                if was_active:
                    self._active[rule.name]["evidence"] = ev
                    continue
                self._seq += 1
                alert = {
                    "seq": self._seq,
                    "ts": ctx["now"],
                    "rule": rule.name,
                    "severity": rule.severity,
                    "rank": ctx["snapshot"]["rank"],
                    "source": source,
                    "evidence": ev,
                }
                self._active[rule.name] = alert
                self._recent.append(alert)
            fired.append(alert)
        for alert in fired:
            _telemetry.counter("alerts.fired_total").inc()
            _telemetry.event(
                f"alert.{alert['rule']}",
                severity=alert["severity"],
                source=alert["source"],
                evidence=alert["evidence"],
            )
            for fn in subscribers:
                try:
                    fn(alert)
                except Exception:
                    pass
        return fired

    # - views -

    def active_alerts(self) -> list[dict]:
        with self._lock:
            return [dict(a) for a in self._active.values()]

    def recent_alerts(self) -> list[dict]:
        with self._lock:
            return [dict(a) for a in self._recent]

    def alerts_since(self, seq: int | float) -> tuple[int, list[dict]]:
        """Alerts with ``seq`` greater than the given cursor (the polling
        surface `serving.ServingLoop` uses) and the new cursor."""
        with self._lock:
            new = [dict(a) for a in self._recent if a["seq"] > seq]
            return self._seq, new

    def fired_total(self) -> int:
        """Alerts fired over this engine's lifetime (== the newest seq)."""
        with self._lock:
            return self._seq


_engine: RuleEngine | None = None
_engine_lock = threading.Lock()


def get_engine() -> RuleEngine:
    """The process-wide engine (created with `default_rules` on first use)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = RuleEngine()
        return _engine


def register_rule(rule: Rule) -> None:
    get_engine().register(rule)


def subscribe(fn: Callable[[dict], None]):
    return get_engine().subscribe(fn)


def unsubscribe(fn) -> None:
    get_engine().unsubscribe(fn)


def alerts_since(seq: int) -> tuple[int, list[dict]]:
    return get_engine().alerts_since(seq)


def heartbeat_tick(model: str | None = None) -> list[dict]:
    """The per-rank live-plane tick the instrumented loops drive at
    ``IGG_HEARTBEAT_EVERY`` cadence: publish the rolling ``slo.*`` gauges,
    then evaluate the anomaly rules — over ONE shared registry snapshot.
    Strictly local — no collectives, so ranks need not agree on it
    (unlike the skew probe it rides next to)."""
    if not _telemetry.enabled():
        return []
    snap = _telemetry.snapshot()
    publish_slo_gauges(snap)
    return get_engine().tick("heartbeat", model, snap=snap)


# -- the per-rank HTTP server -------------------------------------------------


def endpoint_filename(rank: int) -> str:
    return f"liveplane.p{rank}.json"


def _span_filter(spans: list[dict], params: dict) -> list[dict]:
    """Apply ``/spans`` query filters: ``name`` is a substring match on the
    span name; ``request`` matches a request's trace_id (single-request
    spans), a multi-request round's ``trace_ids`` entry, or the ``request``
    tag (the front-door request id)."""
    names = params.get("name")
    if names:
        spans = [s for s in spans if names[0] in s.get("name", "")]
    reqs = params.get("request")
    if reqs:
        rid = reqs[0]

        def _matches(s: dict) -> bool:
            args = s.get("args") or {}
            return (
                args.get("trace_id") == rid
                or rid in (args.get("trace_ids") or ())
                or args.get("request") == rid
            )

        spans = [s for s in spans if _matches(s)]
    return spans


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "igg-liveplane/1"
    #: per-connection socket timeout: a stalled scraper drops its
    #: connection instead of pinning a handler thread (the front door's
    #: slow-loris hardening, applied to the scrape plane too)
    timeout = 10

    def do_GET(self):  # noqa: N802 (http.server API)
        path, _, query = self.path.partition("?")
        try:
            if path == "/metrics":
                # Byte-identical to dump_metrics' .prom output for the same
                # snapshot: both render through telemetry.prometheus_text.
                body = _telemetry.prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/healthz":
                # Scrape-time rule evaluation: the vantage that can see a
                # stalled step loop from outside it (StepStallRule).  ONE
                # registry snapshot serves both the tick and the document.
                snap = _telemetry.snapshot()
                get_engine().tick("scrape", snap=snap)
                body = json.dumps(
                    health_snapshot(snap), default=str
                ).encode()
                ctype = "application/json"
            elif path == "/spans":
                # ?name=<substring> narrows by span name; ?request=<id>
                # narrows to one request's spans (trace_id, a multi-request
                # round's trace_ids entry, or the request tag) — the live
                # complement of `igg_trace.py request` for a still-running
                # rank (docs/observability.md, request-tracing tier).
                params = _urlparse.parse_qs(query)
                doc = {
                    "rank": _telemetry._proc_index(),
                    "spans": _span_filter(_tracing.span_records(), params),
                    "open": _span_filter(_tracing.open_spans(), params),
                }
                body = json.dumps(doc, default=str).encode()
                ctype = "application/json"
            else:
                self.send_error(404, "unknown endpoint")
                return
        except Exception as e:  # a scrape must never crash the server thread
            self.send_error(500, repr(e))
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam stderr
        pass


class MetricsServer:
    """One live-plane HTTP server: daemon thread, closeable, port-aware."""

    def __init__(self, host: str, port: int):
        self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="igg-liveplane",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_server: MetricsServer | None = None
_server_lock = threading.Lock()
_published_rank: int | None = None


def _publish_endpoint(server: MetricsServer) -> None:
    """Publish the bound port: the ``liveplane.port`` gauge (rides the
    rank-0 heartbeat event from there) and — when ``IGG_TELEMETRY_DIR`` is
    set — a ``liveplane.p<rank>.json`` endpoint file, the host:port
    discovery surface ``scripts/igg_top.py --dir`` reads."""
    global _published_rank
    _telemetry.gauge("liveplane.port").set(server.port)
    directory = _config.telemetry_dir_env()
    if not directory:
        return
    # Generation fence (docs/robustness.md): a zombie incarnation must not
    # overwrite the live one's discovery file — igg_top would scrape the
    # dead rank.  Advisory path: refuse (the fence.rejected event is
    # already on the timeline) instead of raising out of the server
    # bring-up.  Function-level import: utils stays supervisor-free at
    # module load.
    from ..supervisor import generation as _generation

    if _generation.fence_refused("liveplane.endpoint"):
        return
    rank = _telemetry._proc_index()
    _published_rank = rank
    host = server.host
    if host in ("0.0.0.0", "::"):
        host = socket.gethostname()
    doc = {
        "rank": rank,
        "pid": os.getpid(),
        "host": host,
        "port": server.port,
        "ts": time.time(),
    }
    try:
        os.makedirs(directory, exist_ok=True)
        _telemetry.atomic_write_json(
            os.path.join(directory, endpoint_filename(rank)), doc,
            fsync=False,  # advisory discovery file
        )
    except OSError:
        pass  # an unwritable dir must not take the run down


def start_server(port: int | None = None, host: str | None = None) -> MetricsServer:
    """Start (or return) THE per-process server, binding ``port`` (0 =
    ephemeral).  Raises on a bind failure — an explicitly requested
    endpoint that silently is not there is worse than a crash at startup."""
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        if host is None:
            host = _config.metrics_host_env() or "127.0.0.1"
        if port is None:
            port = _config.metrics_port_env() or 0
        _server = MetricsServer(host, int(port))
    _publish_endpoint(_server)
    _telemetry.event("liveplane.start", host=_server.host, port=_server.port)
    return _server


def ensure_server() -> MetricsServer | None:
    """Idempotent opt-in bring-up: start the server iff ``IGG_METRICS_PORT``
    is set AND telemetry is enabled; never raises (an instrumented loop
    must not die because a port was taken — the failure is evented).

    An already-running server re-publishes its endpoint file when the
    process RANK has resolved since the first publication: the models'
    ``run()`` brings the server up BEFORE ``init_global_grid``, where
    every rank still reads as 0 — the next ensure (the instrumented
    loop's) rewrites ``liveplane.p<true rank>.json`` so the igg_top
    discovery surface ends up correct on multi-process launches.
    """
    if _server is not None:
        if _telemetry._proc_index() != _published_rank:
            _publish_endpoint(_server)
        return _server
    if not enabled():
        return None
    try:
        return start_server()
    except OSError as e:
        _telemetry.event("liveplane.start_failed", error=repr(e))
        return None


def stop_server() -> None:
    global _server, _published_rank
    with _server_lock:
        server, _server = _server, None
        _published_rank = None
    if server is not None:
        server.close()


def server_port() -> int | None:
    return _server.port if _server is not None else None


def reset() -> None:
    """Stop the server, drop the engine and expectations (test hook)."""
    global _engine
    stop_server()
    with _engine_lock:
        _engine = None
    _teff_expectations.clear()
