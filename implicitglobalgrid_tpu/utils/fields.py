"""Field constructors and block-wise helpers for global-block arrays.

The reference's users allocate plain per-process arrays (`zeros(nx,ny,nz)`);
here a field is one `jax.Array` whose global shape is ``dims * local_shape``
with one block per device (`NamedSharding` over the grid mesh).  These
constructors are the supported way to create fields — they guarantee the
sharding that `update_halo`/`gather` expect.

`coord_fields` replaces the reference's per-element comprehension idiom for
initial conditions (`/root/reference/examples/diffusion3D_multigpu_CuArrays_novis.jl:34-37`):
it returns global-block coordinate arrays (computed per block on device with
`lax.axis_index`, never materializing the global grid on host) so ICs are
plain vectorized jnp expressions.
"""

from __future__ import annotations

import numpy as np

from ..parallel import grid as _grid
from ..parallel.topology import AXIS_NAMES


def _sharding(ndim: int, gg):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(gg.mesh, P(*AXIS_NAMES[:ndim]))


def _global_shape(local_shape, gg) -> tuple[int, ...]:
    return tuple(gg.dims[d] * int(s) for d, s in enumerate(local_shape))


def zeros(local_shape, dtype=None):
    """A zero field with per-block shape ``local_shape`` (1-, 2- or 3-D).

    Defaults to the floating dtype (like ``jnp.zeros``), not the int dtype
    ``jnp.full(shape, 0)`` would infer.
    """
    import jax

    if dtype is None:
        dtype = jax.dtypes.canonicalize_dtype(float)
    return full(local_shape, 0, dtype)


def ones(local_shape, dtype=None):
    import jax

    if dtype is None:
        dtype = jax.dtypes.canonicalize_dtype(float)
    return full(local_shape, 1, dtype)


def full(local_shape, fill_value, dtype=None):
    import jax
    import jax.numpy as jnp

    _grid.check_initialized()
    gg = _grid.global_grid()
    local_shape = (local_shape,) if np.ndim(local_shape) == 0 else tuple(local_shape)
    shape = _global_shape(local_shape, gg)
    if gg.nprocs == 1 and not gg.force_spmd:
        # Degenerate 1-device grid: a mesh sharding is semantically inert but
        # routes later computations through the SPMD executable path (slower
        # on some runtimes) — commit to the grid's device without it
        # (measured equal to plain placement, and it honors a non-default
        # ``devices=[...]`` choice).
        from jax.sharding import SingleDeviceSharding

        return jax.jit(
            lambda: jnp.full(shape, fill_value, dtype=dtype),
            out_shardings=SingleDeviceSharding(gg.mesh.devices.flat[0]),
        )()
    sharding = _sharding(len(shape), gg)
    return jax.jit(
        lambda: jnp.full(shape, fill_value, dtype=dtype), out_shardings=sharding
    )()


def from_block_fn(fn, local_shape, dtype=None):
    """Build a field by evaluating ``fn(coords) -> block`` on every device.

    ``fn`` receives the block's Cartesian coordinates ``(cx, cy, cz)`` as
    traced scalars and must return an array of shape ``local_shape``.  This is
    the device-side analogue of the reference's "fill the local array from
    global coordinates" initialization pattern.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    _grid.check_initialized()
    gg = _grid.global_grid()
    local_shape = tuple(local_shape)
    nd = len(local_shape)

    def per_block():
        coords = tuple(
            lax.axis_index(AXIS_NAMES[d]) if gg.dims[d] > 1 else jnp.int32(0)
            for d in range(3)
        )
        out = jnp.asarray(fn(coords), dtype=dtype)
        if out.shape != local_shape:
            raise ValueError(
                f"from_block_fn: fn returned shape {out.shape}, expected {local_shape}."
            )
        return out

    if gg.nprocs == 1 and not gg.force_spmd:
        # All dims are 1, so no axis_index is ever taken: no shard_map, but
        # still commit to the grid's device (see full()).
        from jax.sharding import SingleDeviceSharding

        return jax.jit(
            per_block, out_shardings=SingleDeviceSharding(gg.mesh.devices.flat[0])
        )()

    from .compat import shard_map

    mapped = shard_map(
        per_block,
        mesh=gg.mesh,
        in_specs=(),
        out_specs=P(*AXIS_NAMES[:nd]),
        check_vma=False,
    )
    return jax.jit(mapped)()


def coord_fields(A, spacings, dtype=None):
    """Global-coordinate arrays matching field ``A``'s shape.

    Returns one array per dimension of ``A`` — e.g. ``XG, YG, ZG =
    coord_fields(T, (dx, dy, dz))`` with each of ``XG[i,j,k] = x_g(i, dx, T)``
    etc., broadcast to ``A``'s global-block shape.  Staggering offsets and
    periodic wrap-around follow `x_g` exactly.
    """
    import jax.numpy as jnp

    from ..ops.halo import local_shape as _lshape
    from . import tools

    _grid.check_initialized()
    gg = _grid.global_grid()
    shp = _lshape(A, gg)
    nd = len(shp)
    spacings = (spacings,) * nd if np.ndim(spacings) == 0 else tuple(spacings)
    coord_g = (tools.x_g, tools.y_g, tools.z_g)

    outs = []
    for dim in range(nd):
        def make(dim=dim):
            def fn(coords):
                vec = coord_g[dim](
                    jnp.arange(shp[dim]), spacings[dim], A, coords=coords
                )
                bshape = [1] * nd
                bshape[dim] = shp[dim]
                return jnp.broadcast_to(vec.reshape(bshape), shp)

            return fn

        outs.append(from_block_fn(make(), shp, dtype=dtype))
    return tuple(outs)


def block_slice(A, slices):
    """Slice every local block of ``A`` (not the global array) with ``slices``.

    ``block_slice(T, (slice(1,-1),)*3)`` returns the per-block interior as a
    new global-block field — the idiom the reference uses before `gather!`
    (`/root/reference/examples/diffusion3D_multigpu_CuArrays.jl:53-54`, where
    the halo is stripped locally before gathering).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    _grid.check_initialized()
    gg = _grid.global_grid()
    from ..ops.halo import local_shape as _lshape

    _lshape(A, gg)  # validates divisibility
    nd = A.ndim
    slices = (slices,) if isinstance(slices, slice) else tuple(slices)

    def per_block(a):
        out = a[slices]
        if out.ndim != nd:
            raise ValueError("block_slice: slices must preserve the number of dimensions.")
        return out

    if gg.nprocs == 1 and not gg.force_spmd:
        from jax.sharding import SingleDeviceSharding

        return jax.jit(
            per_block, out_shardings=SingleDeviceSharding(gg.mesh.devices.flat[0])
        )(A)

    from .compat import shard_map

    spec = P(*AXIS_NAMES[:nd])
    mapped = shard_map(
        per_block, mesh=gg.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )
    return jax.jit(mapped)(A)
