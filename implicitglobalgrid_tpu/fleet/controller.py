"""`FleetController`: launch and own N serving pools as one fleet.

The tier above `supervisor.manager.RunSupervisor` (docs/robustness.md,
"fleet failure domains"): where the run supervisor owns the RANKS of one
pool, the fleet controller owns the POOLS of one fleet.  Each pool is a
supervised incarnation in its own failure domain — its own generation
fence (a per-pool fence directory, `supervisor.generation`), its own
device subset (``XLA_FLAGS``-partitioned on the CPU mesh; disjoint hosts
on chips), its own telemetry dir, and its own front-door port, discovered
through the pool's ``frontdoor.p0.json`` endpoint file (the
``igg_top.py`` path).

The state machine per pool is the supervisor's, one level up: **detect**
(process liveness + endpoint reachability) → **classify** (``died`` /
``wedged`` / ``hot`` / ``idle``) → **policy** (`fleet.policy.decide_pool`
— pure) → **fence + execute** (publish the bumped generation BEFORE the
kill, evacuate the pool's unfinished routes through the router, relaunch,
re-register).  Every transition is a structured event — the soak
``fleet`` drill asserts the order ``fleet.detect → fleet.reroute →
fleet.recovered`` from the event log.

Canary rollout rides the same machinery (`fleet.canary.CanaryTracker`):
`start_canary` launches one extra pool under a candidate config (its env
carries the PR-12 tuned-config overlay, e.g. ``IGG_TUNE_CACHE``), the
controller's poll gates it on the canary's scraped SLO windows, and a
breach executes the rollback THROUGH the strike machinery — the canary
pool is struck to its respawn limit and quarantined, so a bad config's
blast radius is one pool for one streak window.

Host-side only, the `supervisor/` discipline: subprocesses, files, HTTP
scrapes — never jax.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import os
import subprocess
import time
from typing import Callable, Sequence

from ..supervisor.classify import Incident
from ..supervisor import generation as _generation
from ..utils import config as _config
from ..utils import telemetry as _telemetry
from ..utils import tracing as _tracing
from . import canary as _canary
from . import policy as _policy
from .router import FleetRouter, scrape_health

__all__ = [
    "FleetController",
    "PoolSpec",
]

DEFAULT_POLL_S = 0.5
#: consecutive dark endpoint sweeps (process alive) before a pool is
#: classified ``wedged`` — one transient scrape drop must not kill a pool
WEDGE_AFTER = 2


@dataclasses.dataclass
class PoolSpec:
    """One pool's identity and isolation (the fleet's unit of failure).

    ``command_for(spec, generation) -> argv`` — how to launch the pool's
    serving process (the child runs its own ServingLoop + FrontDoor and
    writes its endpoint file); ``workdir`` — the pool's fence dir (its
    ``generation.json`` lives here) and log home; ``telemetry_dir`` — the
    pool's OWN evidence/event dir (per-pool event logs are what the drill
    audits); ``devices`` — the device-subset label (an ``XLA_FLAGS``
    partition on the CPU mesh), quarantined as a unit; ``key`` — the
    routing contract (``{"model": ..., "size": ...}``); ``env`` — extra
    child environment (the canary's config overlay rides here).
    """

    name: str
    command_for: Callable[["PoolSpec", int], Sequence[str]]
    workdir: str
    telemetry_dir: str
    key: dict = dataclasses.field(default_factory=dict)
    devices: str | None = None
    env: dict = dataclasses.field(default_factory=dict)


class _PoolHandle:
    """One pool incarnation's live process (+ log and discovery state)."""

    def __init__(self, proc, log_path: str, generation: int, t0: float):
        self.proc = proc
        self.log_path = log_path
        self.generation = generation
        self.t0 = t0
        self.endpoint: str | None = None

    def poll(self):
        return self.proc.poll()

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        try:
            self.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass


def _popen_spawn(argv: Sequence[str], env: dict, log_path: str):
    f = open(log_path, "w")
    try:
        return subprocess.Popen(
            list(argv), env=env, stdout=f, stderr=subprocess.STDOUT,
            text=True,
        )
    finally:
        f.close()  # the child holds its own descriptor


class FleetController:
    """Failure-domain manager for a fleet of pools (module docstring).

    ``specs`` — the seed pools; ``router`` — the `FleetRouter` front door
    (constructed here when None); ``policy`` — `fleet.policy.FleetPolicy`
    (env tier when None); ``spawn(argv, env, log_path) -> proc`` — the
    process hook (subprocess.Popen by default; tests inject fakes);
    ``scrape(endpoint) -> health | None`` — the health hook.
    """

    def __init__(self, specs: Sequence[PoolSpec], *,
                 router: FleetRouter | None = None,
                 policy: "_policy.FleetPolicy | None" = None,
                 poll_s: float | None = None,
                 spawn=None, scrape=None):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"pool names must be unique: {names}")
        self.specs: dict[str, PoolSpec] = {s.name: s for s in specs}
        self.router = router if router is not None else FleetRouter()
        self.policy = (
            policy if policy is not None else _policy.FleetPolicy.from_env()
        )
        env_poll = _config.fleet_poll_env()
        self.poll_s = (
            poll_s if poll_s is not None
            else (env_poll if env_poll is not None else DEFAULT_POLL_S)
        )
        self.spawn = spawn or _popen_spawn
        self.scrape = scrape or scrape_health
        self.state = _policy.FleetState()
        self.handles: dict[str, _PoolHandle] = {}
        self.generations: dict[str, int] = {}
        #: pools the fleet itself spawned (spill targets) — only these retire
        self.spilled: set[str] = set()
        #: pools told to shut down (a clean exit is not an incident)
        self._retiring: set[str] = set()
        self._dark: dict[str, int] = {}
        self._spill_serial = 0
        self.canary: "_canary.CanaryTracker | None" = None

    # - events -

    def _event(self, etype: str, **payload) -> None:
        _telemetry.event(etype, fleet="fleet", **payload)

    # - launch / discovery -

    def _child_env(self, spec: PoolSpec, generation: int) -> dict:
        env = dict(os.environ)
        env.update(spec.env)
        env["IGG_TELEMETRY"] = env.get("IGG_TELEMETRY", "1")
        env["IGG_TELEMETRY_DIR"] = spec.telemetry_dir
        env["IGG_GENERATION"] = str(generation)
        env["IGG_FENCE_DIR"] = spec.workdir
        return env

    def launch_pool(self, name: str, *, canary: bool = False) -> _PoolHandle:
        """Spawn one pool incarnation (fence published FIRST: the
        authoritative token always leads the processes that carry it —
        the `RunSupervisor.launch` discipline)."""
        spec = self.specs[name]
        gen = self.generations.setdefault(name, 0)
        _generation.publish_generation(gen, spec.workdir, pool=name)
        os.makedirs(spec.workdir, exist_ok=True)
        os.makedirs(spec.telemetry_dir, exist_ok=True)
        log_path = os.path.join(spec.workdir, f"{name}_g{gen}.log")
        proc = self.spawn(
            list(spec.command_for(spec, gen)),
            self._child_env(spec, gen), log_path,
        )
        handle = _PoolHandle(proc, log_path, gen, time.time())
        self.handles[name] = handle
        self._dark[name] = 0
        self._event(
            "fleet.pool_launch", pool=name, generation=gen,
            devices=spec.devices, canary=canary,
        )
        return handle

    def discover_endpoint(self, name: str) -> str | None:
        """The pool's front-door ``host:port`` from its endpoint file
        (``frontdoor.p*.json`` under the pool's OWN telemetry dir — the
        `scripts/igg_top.py` discovery path).  Files older than the
        current incarnation's launch are a superseded door's leftovers
        and are skipped (the ``ts >= t0`` staleness check)."""
        handle = self.handles.get(name)
        if handle is None:
            return None
        if handle.endpoint is not None:
            return handle.endpoint
        spec = self.specs[name]
        for path in sorted(_glob.glob(
            os.path.join(spec.telemetry_dir, "frontdoor.p*.json")
        )):
            try:
                with open(path) as f:
                    doc = json.load(f)
                if float(doc.get("ts") or 0) < handle.t0:
                    continue
                handle.endpoint = f"{doc['host']}:{doc['port']}"
            except (OSError, ValueError, KeyError, TypeError):
                continue
        if handle.endpoint is not None:
            self.router.register_pool(
                name, handle.endpoint, key=self.specs[name].key,
                canary=(self.canary is not None
                        and self.canary.pool == name),
            )
        return handle.endpoint

    def launch(self, *, wait_s: float = 60.0) -> None:
        """Bring the seed fleet up: spawn every pool, then wait for each
        endpoint file (a pool that never opens its door within ``wait_s``
        is classified ``died`` on the first poll)."""
        for name in list(self.specs):
            self.launch_pool(name)
        deadline = time.monotonic() + wait_s
        pending = set(self.specs)
        while pending and time.monotonic() < deadline:
            for name in sorted(pending):
                if self.discover_endpoint(name) is not None:
                    pending.discard(name)
                    self._event("fleet.pool_up", pool=name,
                                endpoint=self.handles[name].endpoint)
                elif self.handles[name].poll() is not None:
                    pending.discard(name)  # died during bring-up: poll_once
            if pending:
                time.sleep(min(0.2, self.poll_s))

    # - detect / classify -

    def _pool_incident(self, name: str) -> Incident | None:
        handle = self.handles.get(name)
        spec = self.specs[name]
        if handle is None:
            return None
        rc = handle.poll()
        if rc is not None:
            if name in self._retiring and rc == 0:
                return None  # a requested shutdown is a retirement, not a death
            return Incident(
                kind="died", ranks=(), rcs=(rc,),
                detail={"pool": name, "devices": spec.devices, "rc": rc},
            )
        endpoint = self.discover_endpoint(name)
        if endpoint is None:
            return None  # still booting; `launch` bounded the wait
        health = self.scrape(endpoint)
        if health is None:
            self._dark[name] = self._dark.get(name, 0) + 1
            if self._dark[name] >= WEDGE_AFTER:
                return Incident(
                    kind="wedged", ranks=(), rcs=(None,),
                    detail={"pool": name, "devices": spec.devices,
                            "dark_sweeps": self._dark[name]},
                )
            return None
        self._dark[name] = 0
        serving = health.get("serving") or {}
        queue = serving.get("queue_depth") or 0
        members = serving.get("active_members") or 0
        self.state.record_health(
            name, queue_depth=queue, active_members=members
        )
        if (
            self.policy.spill_queue is not None
            and queue >= self.policy.spill_queue
        ):
            return Incident(
                kind="hot", ranks=(), rcs=(),
                detail={"pool": name, "queue_depth": queue},
            )
        if not queue and not members:
            return Incident(kind="idle", ranks=(), rcs=(),
                            detail={"pool": name})
        return Incident(kind="healthy", ranks=(), rcs=(),
                        detail={"pool": name})

    # - execute -

    def _respawn(self, name: str, reason: str) -> None:
        """Fence → evacuate → kill → relaunch → re-adopt: the ordered
        recovery one pool death costs.  The generation moves FIRST so a
        zombie that outlives its SIGKILL is refused at every publish
        path; the routes move BEFORE the relaunch so no request ever
        waits on the reboot."""
        spec = self.specs[name]
        handle = self.handles.get(name)
        # The detection→evacuation hop belongs to every stranded request's
        # causal tree: one span tagged with the victim routes' trace ids
        # (the reroute span `evacuate` opens nests under it).
        with self.router._lock:
            trace_ids = sorted({
                r["trace"]["trace_id"] for r in self.router.routes.values()
                if r["pool"] == name and r["done"] is None and r.get("trace")
            })
        span_tags = {"trace_ids": trace_ids} if trace_ids else {}
        with _tracing.trace_span("igg.fleet.detect", pool=name,
                                 reason=reason, **span_tags):
            self.generations[name] = self.generations.get(name, 0) + 1
            _generation.publish_generation(
                self.generations[name], spec.workdir, pool=name, reason=reason
            )
            if handle is not None:
                handle.kill()
            self.router.unregister_pool(name)
            self.router.evacuate(name)
        self.launch_pool(name)
        deadline = time.monotonic() + 60.0
        while (
            self.discover_endpoint(name) is None
            and time.monotonic() < deadline
            and self.handles[name].poll() is None
        ):
            time.sleep(min(0.2, self.poll_s))
        # routes evacuation could not place (no surviving pool was
        # eligible) are re-homed onto the fresh incarnation
        self.router.evacuate(name, exclude=set())
        self._event(
            "fleet.recovered", pool=name, action="respawn",
            generation=self.generations[name],
            endpoint=self.handles[name].endpoint,
        )

    def _quarantine(self, name: str, decision) -> None:
        spec = self.specs[name]
        handle = self.handles.get(name)
        self.router.quarantine_pool(name)
        self.router.evacuate(name)
        if handle is not None:
            handle.kill()
        self._event(
            "fleet.quarantine", pool=name, devices=spec.devices,
            reason=decision.reason,
        )

    def _spill(self, name: str) -> None:
        """Clone the hot pool's spec onto fresh dirs/port and spawn it —
        growth WITHOUT resizing a live pool (the fleet answer to the
        autoscaler's checkpoint-restart cycle)."""
        base = self.specs[name]
        self._spill_serial += 1
        spill_name = f"{name}-spill{self._spill_serial}"
        spec = PoolSpec(
            name=spill_name,
            command_for=base.command_for,
            workdir=os.path.join(base.workdir, spill_name),
            telemetry_dir=os.path.join(base.telemetry_dir, spill_name),
            key=dict(base.key),
            devices=base.devices,
            env=dict(base.env),
        )
        self.specs[spill_name] = spec
        self.spilled.add(spill_name)
        self.launch_pool(spill_name)
        self._event("fleet.spill", pool=name, spill=spill_name)

    def _retire(self, name: str) -> None:
        handle = self.handles.get(name)
        self._retiring.add(name)
        if handle is not None and handle.endpoint is not None:
            self.router.transport(
                handle.endpoint, "POST", "/v1/shutdown", {}
            )
        self.router.unregister_pool(name)
        self._event("fleet.retire", pool=name)

    def execute(self, decision: "_policy.FleetDecision") -> None:
        """Apply one fleet-policy verdict (bookkeeping folded first, the
        `SupervisorState.apply` discipline)."""
        self.state.apply(decision)
        if decision.action == "respawn":
            self._respawn(decision.pool, decision.reason)
        elif decision.action == "quarantine":
            self._quarantine(decision.pool, decision)
        elif decision.action == "spill":
            self._spill(decision.pool)
        elif decision.action == "retire":
            self._retire(decision.pool)

    # - the poll loop -

    def poll_once(self) -> list:
        """One detect → classify → policy → execute sweep over every pool
        (+ one canary gate evaluation).  Returns the executed decisions."""
        executed = []
        for name in sorted(self.handles):
            if name in self._retiring:
                continue
            incident = self._pool_incident(name)
            if incident is None or incident.kind == "healthy":
                if incident is not None:
                    self.state.apply(_policy.FleetDecision(
                        action="none", pool=name, reason="healthy"
                    ))
                continue
            if (
                self.canary is not None
                and self.canary.state == "baking"
                and name == self.canary.pool
            ):
                # a dying/wedged BAKING canary is a breach of the config
                # under trial, not a pool to respawn under it: feed the
                # gate an unreachable observation and let the rollback
                # path (strike machinery) do the rest
                if incident.kind in ("died", "wedged"):
                    self._event("fleet.detect", pool=name, kind=incident.kind,
                                canary=True)
                    self.canary.observe(None)
                    self._publish_canary()
                    self._canary_rollback()
                continue
            if incident.kind in ("died", "wedged"):
                self._event(
                    "fleet.detect", pool=name, kind=incident.kind,
                    **{k: v for k, v in (incident.detail or {}).items()
                       if k != "pool"},
                )
            decision = _policy.decide_pool(
                incident, self.state, self.policy,
                spilled=name in self.spilled,
            )
            if decision.action != "none":
                self.execute(decision)
                executed.append(decision)
        if self.canary is not None and self.canary.state == "baking":
            self._canary_gate()
        return executed

    def run(self, *, until: Callable[[], bool], timeout: float = 600.0) -> None:
        """Poll at the fleet cadence until ``until()`` or ``timeout``."""
        deadline = time.monotonic() + timeout
        while not until() and time.monotonic() < deadline:
            self.poll_once()
            time.sleep(self.poll_s)

    # - canary rollout -

    def start_canary(self, spec: PoolSpec, candidate: dict) -> None:
        """Launch one canary pool under ``candidate`` (its config overlay
        rides ``spec.env`` — e.g. ``IGG_TUNE_CACHE`` pointing at the
        trial layer) and arm the SLO gate."""
        if self.canary is not None and self.canary.state == "baking":
            raise RuntimeError(
                f"a canary is already baking ({self.canary.pool})"
            )
        self.specs[spec.name] = spec
        self.spilled.add(spec.name)  # a rolled-back canary may retire
        self.canary = _canary.CanaryTracker(
            pool=spec.name, candidate=candidate, policy=self.policy
        )
        self.launch_pool(spec.name, canary=True)
        self._publish_canary()

    def _publish_canary(self) -> None:
        if self.canary is None:
            return
        spec = self.specs.get(self.canary.pool)
        if spec is not None:
            _canary.publish_canary_state(spec.workdir, self.canary.doc())

    def _canary_gate(self) -> None:
        """One canary observation: scrape the canary pool, feed the
        tracker, and execute promote/rollback."""
        tracker = self.canary
        name = tracker.pool
        endpoint = self.discover_endpoint(name)
        health = self.scrape(endpoint) if endpoint is not None else None
        if health is None and endpoint is None:
            return  # still booting — the gate starts at the first scrape
        verdict = tracker.observe(health)
        self._publish_canary()
        if verdict == "promoted":
            # the candidate is fleet-safe: non-canary pools pick the
            # overlay up on their next (re)launch
            for other in self.specs.values():
                if other.name != name:
                    other.env.update(self.specs[name].env)
        elif verdict == "rolled_back":
            self._event("fleet.detect", pool=name, kind="canary_breach",
                        breach=tracker.breach)
            self._canary_rollback()

    def _canary_rollback(self) -> None:
        """The strike machinery IS the rollback path: the canary pool is
        struck straight to its limit and quarantined, so the candidate
        never reaches a second pool."""
        tracker = self.canary
        name = tracker.pool
        self.state.respawns[name] = self.policy.respawn_limit
        incident = Incident(
            kind="died", ranks=(), rcs=(None,),
            detail={"pool": name,
                    "devices": self.specs[name].devices,
                    "canary_breach": tracker.breach},
        )
        decision = _policy.decide_pool(incident, self.state, self.policy)
        self.execute(decision)

    # - teardown -

    def shutdown(self) -> None:
        """Stop every pool (clean doors first, then the reap) and the
        router."""
        for name, handle in sorted(self.handles.items()):
            if handle.endpoint is not None and handle.poll() is None:
                self.router.transport(
                    handle.endpoint, "POST", "/v1/shutdown", {}
                )
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and any(
            h.poll() is None for h in self.handles.values()
        ):
            time.sleep(0.1)
        for handle in self.handles.values():
            handle.kill()
        self.router.close()
        self._event("fleet.shutdown", pools=sorted(self.handles))
