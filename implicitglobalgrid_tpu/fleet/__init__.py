"""igg.fleet — multi-pool failure domains over the serving tier (ISSUE 16).

Everything through PR 14 is ONE pool on ONE topology behind ONE rank-0
HTTP thread — a single failure domain owning all traffic.  This package
is the layer that turns one self-healing pool into a self-healing FLEET
(ROADMAP item 3), in four pieces forming the per-pool state machine
**detect → classify → policy → fence** one level above the run
supervisor:

* `router` — `FleetRouter`, the single public HTTP entry: routes
  ``POST /v1/submit`` on the request's (model, size, tenant) key and the
  pools' scraped ``/healthz`` state; ``GET /v1/result/<id>`` is sticky
  (the route remembers the owning pool and follows it through a replay),
  and the epoch-checked `FleetRouter.adopt_result` refuses a zombie
  pool's late answer (``fleet.zombie_result``).
* `policy` — pure pool incident → fleet action (`decide_pool`): died/
  wedged → respawn + replay, strikes exhausted → quarantine the pool's
  device subset, hot → spill to a fresh pool, idle spill → retire; plus
  `fleet_plan`, the per-rank in-band schedule the
  ``collective-consistency`` analyzer censuses
  (`analysis.collectives.fleet_plan_censuses`).
* `canary` — `CanaryTracker`, the SLO-gated rollout state machine:
  a candidate config (a PR-12 tuned-config overlay) serves one pool,
  auto-promotes after a healthy streak, auto-rolls-back through the
  strike machinery on breach (``fleet.canary.*`` events throughout).
* `controller` — `FleetController`, the orchestration loop: launch N
  pools (per-pool generation fences, device subsets, telemetry dirs,
  front-door ports), watch, classify, decide, fence-then-execute.  The
  soak ``fleet`` drill (`scripts/soak.py`) is a thin wrapper over it.

Host-side only, the `supervisor/` discipline: this package never imports
jax — the fleet must keep routing while a pool's fabric is wedged.
"""

from .canary import CanaryTracker, publish_canary_state
from .controller import FleetController, PoolSpec
from .policy import (
    FLEET_ACTIONS,
    FleetDecision,
    FleetPolicy,
    FleetState,
    decide_pool,
    fleet_plan,
)
from .router import FleetRouter, choose_pool, scrape_health

__all__ = [
    "FLEET_ACTIONS",
    "CanaryTracker",
    "FleetController",
    "FleetDecision",
    "FleetPolicy",
    "FleetRouter",
    "FleetState",
    "PoolSpec",
    "choose_pool",
    "decide_pool",
    "fleet_plan",
    "publish_canary_state",
    "scrape_health",
]
