"""Fleet recovery policy: classified pool incident -> fleet action.

The decide step of the fleet state machine (detect → classify → **policy**
→ fence; docs/robustness.md, "fleet failure domains").  Same split as
`supervisor.policy`: `decide_pool` is a PURE function of
``(incident, state, policy)`` — deterministic, clock-free, pinned by
synthetic-incident tests — and `FleetState` is the bookkeeping shell
(per-pool strike counts, quarantined device subsets, idle streaks) the
`fleet.controller.FleetController` owns.

Actions (`FLEET_ACTIONS`):

``respawn``          the pool died or wedged: fence its superseded
                     generation, relaunch it on the same device subset,
                     and replay its unfinished request specs (requests
                     carry parameters, never arrays — replay is safe).
``quarantine``       respawn strikes exhausted — or an ``sdc`` incident
                     (silent data corruption proven by the `integrity`
                     plane), which skips the strikes entirely: pin the
                     pool's device subset out of the fleet and stop
                     routing to it.
``spill``            a pool is hot (sustained queue depth at/above
                     ``IGG_FLEET_SPILL_QUEUE``): spawn a FRESH pool and
                     route overflow there instead of resizing a live one.
``retire``           a spilled pool sat idle ``IGG_FLEET_IDLE_RETIRE``
                     observations in a row: drain and shut it down.
``canary_promote``   the canary pool's candidate config stayed healthy a
                     full ``IGG_FLEET_CANARY_STREAK`` streak: promote it.
``canary_rollback``  the canary breached its SLO gate: roll the candidate
                     back through the quarantine/strike machinery.
``none``             healthy — nothing to do.

`fleet_plan` states, per pool FRONT-DOOR RANK, the ordered host-transport
collective schedule that applying one fleet directive implies inside a
pool — the contract the ``collective-consistency`` analyzer censuses per
simulated rank (`analysis.collectives.fleet_plan_censuses`): a routing or
canary decision keyed on rank identity is the `_gather_chunked` deadlock
class wearing a fleet hat, and the census catches it statically.
"""

from __future__ import annotations

import dataclasses

from ..utils import config as _config

__all__ = [
    "FLEET_ACTIONS",
    "FleetDecision",
    "FleetPolicy",
    "FleetState",
    "decide_pool",
    "fleet_plan",
]

FLEET_ACTIONS = (
    "none",
    "respawn",
    "quarantine",
    "spill",
    "retire",
    "canary_promote",
    "canary_rollback",
)

#: pool incident kinds that consume a respawn strike
_POOL_FAILED = ("died", "wedged")

DEFAULT_RESPAWN_LIMIT = 2
DEFAULT_CANARY_STREAK = 3


@dataclasses.dataclass(frozen=True)
class FleetDecision:
    """One fleet-policy verdict: what to do to which pool, and why."""

    action: str
    pool: str
    reason: str
    #: device subsets pinned out of the fleet by this decision
    quarantined: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """The knobs of `decide_pool` (kwarg > fleet env tier > default).

    ``respawn_limit`` — in-place pool respawns per CONTINUOUS failure
    streak before the pool's device subset is quarantined;
    ``spill_queue`` — scraped queue depth at/above which a hot pool
    spills to a fresh one (``None`` = spill off); ``idle_retire`` —
    consecutive idle observations before a spilled pool retires
    (``None`` = never); ``canary_streak`` — healthy canary observations
    before auto-promote; ``canary_p99_s`` — round-p99 breach bar for the
    canary gate (``None`` = alerts-only).
    """

    respawn_limit: int = DEFAULT_RESPAWN_LIMIT
    spill_queue: int | None = None
    idle_retire: int | None = None
    canary_streak: int = DEFAULT_CANARY_STREAK
    canary_p99_s: float | None = None

    @classmethod
    def from_env(cls, **kw) -> "FleetPolicy":
        kw.setdefault("respawn_limit", _config.fleet_respawn_limit_env())
        kw.setdefault("spill_queue", _config.fleet_spill_queue_env())
        kw.setdefault("idle_retire", _config.fleet_idle_retire_env())
        kw.setdefault("canary_streak", _config.fleet_canary_streak_env())
        kw.setdefault("canary_p99_s", _config.fleet_canary_p99_env())
        return cls(**{k: v for k, v in kw.items() if v is not None})

    def __post_init__(self):
        if self.respawn_limit < 0:
            raise ValueError(
                f"respawn_limit must be >= 0 (got {self.respawn_limit})"
            )
        if self.spill_queue is not None and self.spill_queue < 1:
            raise ValueError(
                f"spill_queue must be >= 1 (got {self.spill_queue})"
            )
        if self.idle_retire is not None and self.idle_retire < 1:
            raise ValueError(
                f"idle_retire must be >= 1 (got {self.idle_retire})"
            )
        if self.canary_streak < 1:
            raise ValueError(
                f"canary_streak must be >= 1 (got {self.canary_streak})"
            )
        if self.canary_p99_s is not None and self.canary_p99_s <= 0:
            raise ValueError(
                f"canary_p99_s must be > 0 (got {self.canary_p99_s})"
            )


@dataclasses.dataclass
class FleetState:
    """Mutable bookkeeping across pool incidents (owned by the controller)."""

    #: respawns consumed during each pool's CURRENT failure streak
    respawns: dict = dataclasses.field(default_factory=dict)
    #: quarantined device-subset labels (never handed to a new pool)
    quarantined_devices: set = dataclasses.field(default_factory=set)
    #: consecutive idle observations per pool
    idle_streaks: dict = dataclasses.field(default_factory=dict)
    #: consecutive hot observations per pool (spill hysteresis)
    hot_streaks: dict = dataclasses.field(default_factory=dict)

    def record_health(self, pool: str, *, queue_depth, active_members) -> None:
        """Fold one scraped health observation into the streak counters
        BEFORE the decision (the `SupervisorState.record_incident`
        discipline — without it spill/retire could never trigger)."""
        idle = (not queue_depth) and (not active_members)
        self.idle_streaks[pool] = (
            self.idle_streaks.get(pool, 0) + 1 if idle else 0
        )

    def apply(self, decision: FleetDecision) -> None:
        """Advance the bookkeeping for an executed decision."""
        if decision.action == "respawn":
            self.respawns[decision.pool] = (
                self.respawns.get(decision.pool, 0) + 1
            )
        elif decision.action == "none":
            self.respawns[decision.pool] = 0
        self.quarantined_devices.update(decision.quarantined)
        if decision.action in ("retire", "quarantine"):
            self.idle_streaks.pop(decision.pool, None)
            self.hot_streaks.pop(decision.pool, None)


def decide_pool(incident, state: FleetState, policy: FleetPolicy,
                *, spilled: bool = False) -> FleetDecision:
    """PURE verdict for one pool observation (module docstring).

    ``incident`` is a `supervisor.classify.Incident`-shaped object whose
    ``kind`` is a pool liveness verdict: ``died`` (process gone),
    ``wedged`` (alive but unreachable/stalled), ``sdc`` (an integrity-
    plane detector convicted a member of silent data corruption — device-
    subset quarantine, never a respawn strike), ``hot`` (sustained queue
    pressure), ``idle`` or ``healthy``.  ``spilled`` marks pools the
    fleet itself spawned (only those ever retire — the seed pools are the
    capacity floor).  Same inputs, same decision — no clocks, no globals.
    """
    pool = incident.detail.get("pool") if incident.detail else None
    if pool is None:
        raise ValueError("incident.detail must carry the pool name")
    if incident.kind == "sdc":
        # An integrity-plane detector (``reason=sdc`` bundle, the
        # `integrity` package) convicted a member of this pool of FINITE
        # wrong values.  No respawn strikes: a crashed pool gets its
        # devices back because crashes are usually software, but silent
        # corruption is the silicon itself lying — respawning on the same
        # device subset re-seats the liar under fresh state.  The subset
        # is pinned out immediately; capacity recovers through the normal
        # spill path on healthy devices.
        devices = incident.detail.get("devices")
        detector = incident.detail.get("detector", "integrity")
        return FleetDecision(
            action="quarantine", pool=pool,
            reason=(
                f"pool {pool} caught corrupting data in flight "
                f"({detector}, rank(s) {tuple(incident.ranks)}): "
                f"quarantining its device subset immediately — respawn "
                f"would re-seat the lying core"
            ),
            quarantined=(devices,) if devices else (),
        )
    if incident.kind in _POOL_FAILED:
        used = state.respawns.get(pool, 0)
        if used >= policy.respawn_limit:
            devices = incident.detail.get("devices")
            return FleetDecision(
                action="quarantine", pool=pool,
                reason=(
                    f"pool {pool} {incident.kind} with {used} respawn(s) "
                    f"exhausted (IGG_FLEET_RESPAWN_LIMIT="
                    f"{policy.respawn_limit}): quarantining its devices"
                ),
                quarantined=(devices,) if devices else (),
            )
        return FleetDecision(
            action="respawn", pool=pool,
            reason=(
                f"pool {pool} {incident.kind}: respawn "
                f"{used + 1}/{policy.respawn_limit} and replay its "
                f"unfinished request specs"
            ),
        )
    if incident.kind == "hot":
        if policy.spill_queue is not None:
            return FleetDecision(
                action="spill", pool=pool,
                reason=(
                    f"pool {pool} queue at/above "
                    f"IGG_FLEET_SPILL_QUEUE={policy.spill_queue}: "
                    f"spilling to a fresh pool"
                ),
            )
        return FleetDecision(action="none", pool=pool,
                             reason="hot but spill is off")
    if incident.kind == "idle":
        streak = state.idle_streaks.get(pool, 0)
        if (
            spilled
            and policy.idle_retire is not None
            and streak >= policy.idle_retire
        ):
            return FleetDecision(
                action="retire", pool=pool,
                reason=(
                    f"spilled pool {pool} idle x{streak} "
                    f"(IGG_FLEET_IDLE_RETIRE={policy.idle_retire}): retiring"
                ),
            )
        return FleetDecision(action="none", pool=pool, reason="idle")
    return FleetDecision(action="none", pool=pool, reason="healthy")


# -- the in-band control plan (analyzer contract) -----------------------------


def fleet_plan(is_root: bool, action: str, stale: bool) -> tuple:
    """The ordered host-transport collective schedule ONE POOL RANK
    follows when a fleet directive lands in-band.

    ``is_root`` exists precisely so the ``collective-consistency`` census
    can prove the schedule ignores rank identity (the
    `supervisor.policy.recovery_plan` contract).  ``stale`` is the fence
    verdict — rank-uniform by construction
    (`supervisor.generation.fence_refusal`), so a superseded pool
    incarnation refuses the directive on EVERY rank together (empty plan).

    Schedules: ``respawn``/``spill`` = the adopting pool's replay
    admission (`serving.frontdoor.broadcast_control` of the re-submitted
    specs) — one control broadcast, no checkpoint barrier (replayed
    requests restart from their parameters); ``canary_promote``/
    ``canary_rollback`` = one config-directive broadcast inside the
    affected pool; ``retire`` = a drain directive broadcast;
    ``quarantine``/``none`` = out-of-band (the controller stops routing /
    kills processes; no surviving rank does in-band work).
    """
    del is_root  # rank identity must not shape the schedule
    if stale:
        return ()  # fenced: every rank refuses the directive together
    if action in ("respawn", "spill"):
        return (("broadcast_control", "adopt-replay"),)
    if action in ("canary_promote", "canary_rollback"):
        return (("broadcast_control", "config-directive"),)
    if action == "retire":
        return (("broadcast_control", "drain"),)
    return ()
