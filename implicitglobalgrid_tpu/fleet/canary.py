"""Canary rollout: a candidate config serves ONE pool before the fleet.

The rollout half of the fleet tier (docs/serving.md, "canary state
machine").  A candidate configuration — a PR-12 tuned-config cache
overlay (`tuning.cache.TuneCache` primary layer, pointed at by the
canary pool's ``IGG_TUNE_CACHE``) or a code-version env — is given to
exactly one pool, and that pool's live SLO surface gates what happens
next: the SAME rolling ``slo.serving.round_seconds`` windows and active
CRITICAL alerts the admission gate reads (`serving.admission`,
`utils.liveplane.slo_view`), scraped off the canary's ``/healthz``.

State machine (every transition a structured ``fleet.canary.*`` event):

    baking --healthy x IGG_FLEET_CANARY_STREAK--> promoted
    baking --breach (p99 > IGG_FLEET_CANARY_P99_S | CRITICAL alert |
            unreachable)--> rolled_back

``promoted`` means the candidate is safe fleet-wide (the controller
re-points the remaining pools at the overlay on their next respawn);
``rolled_back`` routes through the strike machinery — the controller
strikes and retires the canary pool, and the overlay never reaches a
second pool.  One breach is enough: a canary exists precisely so the
blast radius of a bad config is one pool for one streak window.

`publish_canary_state` persists the machine's state next to the fence
file, gated on the generation fence like every durable fleet publish: a
superseded controller incarnation's write is refused
(`supervisor.generation.fence_refused` → ``fence.rejected``), so a
zombie controller can never flip a canary verdict under the live one.

Host-side only, the `supervisor/` discipline — never jax.
"""

from __future__ import annotations

import dataclasses
import os

from ..supervisor import generation as _generation
from ..utils import telemetry as _telemetry
from .policy import FleetPolicy
from .router import UNREACHABLE, pool_health_view

__all__ = [
    "CANARY_STATE",
    "CanaryTracker",
    "publish_canary_state",
]

#: the canary-state file (lives in the controller's fence/work dir)
CANARY_STATE = "canary.json"


def publish_canary_state(directory: str, doc: dict) -> bool:
    """Atomically persist one canary-state document; fence-gated.

    Returns False (refusing the write, ``fence.rejected`` already on the
    timeline) when this process' generation is superseded — the
    advisory-publish discipline of the front door's endpoint file.
    """
    if _generation.fence_refused("fleet.canary"):
        return False
    _telemetry.atomic_write_json(
        os.path.join(directory, CANARY_STATE), doc, fsync=False
    )
    return True


@dataclasses.dataclass
class CanaryTracker:
    """The per-rollout state machine (module docstring).

    ``pool`` — the canary pool's name; ``candidate`` — an opaque,
    JSON-serializable description of what is being trialed (an overlay
    dir, a code version); ``policy`` — the gate knobs
    (`fleet.policy.FleetPolicy`: ``canary_streak``, ``canary_p99_s``).
    `observe` folds one scraped ``/healthz`` document (or None for an
    unreachable canary) and returns the machine's state.
    """

    pool: str
    candidate: dict
    policy: FleetPolicy = dataclasses.field(default_factory=FleetPolicy)
    state: str = "baking"
    streak: int = 0
    observations: int = 0
    breach: dict | None = None

    def __post_init__(self):
        _telemetry.event(
            "fleet.canary.start", pool=self.pool, candidate=self.candidate,
            streak_needed=self.policy.canary_streak,
            p99_s=self.policy.canary_p99_s,
        )

    def _breach_of(self, view: dict) -> dict | None:
        if view["state"] == UNREACHABLE:
            return {"kind": "unreachable"}
        critical = [
            a for a in view["alerts"] if a is not None
        ] if view["state"] == "alerting" else []
        if critical:
            return {"kind": "alert", "rules": critical}
        p99, bar = view["round_p99_s"], self.policy.canary_p99_s
        if bar is not None and p99 is not None and p99 > bar:
            return {"kind": "slo", "round_p99_s": p99, "bar_s": bar}
        return None

    def observe(self, health: dict | None) -> str:
        """One gate evaluation; returns ``baking`` | ``promoted`` |
        ``rolled_back`` (terminal states are sticky)."""
        if self.state != "baking":
            return self.state
        self.observations += 1
        view = pool_health_view(health)
        breach = self._breach_of(view)
        if breach is not None:
            self.state = "rolled_back"
            self.breach = breach
            _telemetry.counter("fleet.canary.rollbacks_total").inc()
            _telemetry.event(
                "fleet.canary.rollback", pool=self.pool,
                candidate=self.candidate, observations=self.observations,
                **breach,
            )
            return self.state
        self.streak += 1
        _telemetry.event(
            "fleet.canary.observe", pool=self.pool, streak=self.streak,
            streak_needed=self.policy.canary_streak,
            round_p99_s=view["round_p99_s"],
        )
        if self.streak >= self.policy.canary_streak:
            self.state = "promoted"
            _telemetry.counter("fleet.canary.promotions_total").inc()
            _telemetry.event(
                "fleet.canary.promote", pool=self.pool,
                candidate=self.candidate, streak=self.streak,
            )
        return self.state

    def doc(self) -> dict:
        """The JSON-serializable snapshot `publish_canary_state` persists."""
        return {
            "pool": self.pool,
            "candidate": self.candidate,
            "state": self.state,
            "streak": self.streak,
            "observations": self.observations,
            "breach": self.breach,
            "generation": _generation.current_generation(),
        }
