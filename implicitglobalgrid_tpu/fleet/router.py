"""Fleet routing front door: ONE public HTTP endpoint over N pools.

Each pool is a whole PR-12 serving stack (ServingLoop + FrontDoor) behind
its own rank-0 port; this module is the tier above (docs/serving.md,
"fleet tier"): ``POST /v1/submit`` lands here, a pool is chosen on the
request's routing key (model, size, tenant) and the pools' scraped
``/healthz`` state — occupancy, windowed round p99, active alerts,
reachability — and the request is forwarded to the winner's own front
door.  ``GET /v1/result/<fleet id>`` is STICKY: the router remembers
which pool owns each request and proxies the fetch there; after a
re-route (`evacuate`) the route points at the adoptive pool and the same
fleet id keeps answering.

Replay safety is inherited, not added: requests carry *parameters*, never
arrays (`serving.frontdoor`), so re-submitting a dead pool's unfinished
specs to another pool rebuilds bit-identical members — the property the
soak ``fleet`` drill checks against an undisturbed oracle.

Zombie-result guard: every route carries an ``epoch`` that increments
when the route is evacuated.  A result can only be adopted into the
router's done-cache by the pool that CURRENTLY owns the route at the
epoch the adoption quotes (`adopt_result`) — a chaos-killed pool's
process that outlives its SIGKILL and answers one last fetch is refused
with a ``fleet.zombie_result`` event, the router-tier twin of the
generation fence (`supervisor.generation`).

Host-side only, the `supervisor/` discipline: stdlib HTTP + JSON, never
jax — the router must keep routing while a pool's fabric is wedged.
"""

from __future__ import annotations

import http.server
import json
import threading
import time
import urllib.error
import urllib.request

from ..utils import config as _config
from ..utils import telemetry as _telemetry
from ..utils import tracing as _tracing

__all__ = [
    "FleetRouter",
    "UNREACHABLE",
    "choose_pool",
    "pool_health_view",
    "scrape_health",
]

#: explicit row/pool state for an endpoint that stayed dark through the
#: whole retry budget (shared vocabulary with ``scripts/igg_top.py``)
UNREACHABLE = "UNREACHABLE"

DEFAULT_SCRAPE_RETRIES = 2
SCRAPE_TIMEOUT_S = 3.0
#: how long one scraped health document keeps feeding routing decisions
HEALTH_TTL_S = 0.25
#: body bound of the router's own POST surface (the per-pool front door
#: re-validates with its full hardening; this only caps the proxy buffer)
MAX_BODY = 1 << 20


def scrape_health(endpoint: str, *, retries: int | None = None,
                  backoff_s: float = 0.05,
                  timeout: float = SCRAPE_TIMEOUT_S) -> dict | None:
    """One pool's ``/healthz`` document, or None after the retry budget.

    ``retries`` (default ``IGG_FLEET_SCRAPE_RETRIES``, else 2) extra
    attempts ride an exponential backoff — one transiently-dropped scrape
    must not mark a healthy pool down (the `scripts/igg_top.py` contract).
    """
    if retries is None:
        env = _config.fleet_scrape_retries_env()
        retries = DEFAULT_SCRAPE_RETRIES if env is None else env
    for attempt in range(retries + 1):
        try:
            with urllib.request.urlopen(
                f"http://{endpoint}/healthz", timeout=timeout
            ) as r:
                return json.loads(r.read().decode())
        except (OSError, ValueError):
            if attempt < retries:
                time.sleep(backoff_s * (2 ** attempt))
    return None


def pool_health_view(health: dict | None) -> dict:
    """The routing-relevant slice of one ``/healthz`` document.

    ``state`` is ``"ok"`` | ``"alerting"`` | ``UNREACHABLE``; the latency
    figure is the rolling-window round p99 (`utils.liveplane.slo_view`),
    matching what admission control and the canary gate read.
    """
    if health is None:
        return {"state": UNREACHABLE, "queue_depth": None,
                "active_members": None, "capacity": None,
                "round_p99_s": None, "alerts": ()}
    serving = health.get("serving") or {}
    slo = health.get("slo") or {}
    rnd = next(
        (s for n, s in sorted(slo.items()) if n.endswith("round_seconds")),
        {},
    )
    active = tuple(
        a.get("rule") for a in health.get("alerts", {}).get("active", [])
    )
    return {
        "state": "ok" if health.get("ok") else "alerting",
        "queue_depth": serving.get("queue_depth"),
        "active_members": serving.get("active_members"),
        "capacity": serving.get("capacity"),
        "round_p99_s": rnd.get("p99"),
        "alerts": active,
    }


def choose_pool(doc: dict, candidates: list[dict]) -> str | None:
    """PURE routing decision: the pool name for one submit document.

    ``candidates`` — ``[{name, key, quarantined, health}, ...]`` where
    ``key`` is the pool's (model, size) contract (None entries =
    wildcard) and ``health`` a `pool_health_view`.  Eligibility: key
    matches the request's (model, size), not quarantined, reachable.
    Among the eligible, deterministic least-loaded order — queue depth,
    then occupancy, then windowed round p99, then name — so every caller
    with the same view picks the same pool (rank identity and RNG never
    enter: the `fleet.policy.fleet_plan` census contract).
    """
    model, size = doc.get("model"), doc.get("size")

    def eligible(c):
        if c.get("quarantined"):
            return False
        if c["health"]["state"] == UNREACHABLE:
            return False
        key = c.get("key") or {}
        if model is not None and key.get("model") not in (None, model):
            return False
        if size is not None and key.get("size") is not None \
                and list(key["size"]) != list(size):
            return False
        return True

    pool = sorted(
        (c for c in candidates if eligible(c)),
        key=lambda c: (
            c["health"]["queue_depth"] or 0,
            c["health"]["active_members"] or 0,
            c["health"]["round_p99_s"] or 0.0,
            c["name"],
        ),
    )
    return pool[0]["name"] if pool else None


def _http_transport(endpoint: str, method: str, path: str,
                    doc: dict | None) -> tuple[int, dict]:
    """Default pool transport: ``(status, body)``; (0, {}) when the pool
    is unreachable (the `_DoorClient` convention the soak drills use)."""
    url = f"http://{endpoint}{path}"
    try:
        if method == "GET":
            req = urllib.request.Request(url)
        else:
            req = urllib.request.Request(
                url, data=json.dumps(doc or {}).encode(),
                headers={"Content-Type": "application/json"},
            )
        with urllib.request.urlopen(req, timeout=SCRAPE_TIMEOUT_S) as r:
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except (ValueError, OSError):
            return e.code, {}
    except (OSError, ValueError):
        return 0, {}


def _make_handler(router: "FleetRouter"):
    class _Handler(http.server.BaseHTTPRequestHandler):
        server_version = "igg-fleet/1"
        timeout = 10

        def _reply(self, code: int, body: dict,
                   headers: dict | None = None):
            data = json.dumps(body, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            try:
                if path.startswith("/v1/result/"):
                    fid = path[len("/v1/result/"):]
                    code, body = router.result(fid)
                    self._reply(code, body, headers=router.trace_header(fid))
                elif path == "/v1/status":
                    self._reply(200, router.status_view())
                elif path == "/healthz":
                    self._reply(200, router.health_view())
                else:
                    self.send_error(404, "unknown endpoint")
            except Exception as e:  # a fetch must never kill the router
                self.send_error(500, repr(e))

        def do_POST(self):  # noqa: N802
            path = self.path.split("?", 1)[0]
            try:
                raw_len = self.headers.get("Content-Length")
                try:
                    length = int(raw_len) if raw_len is not None else 0
                except ValueError:
                    self._reply(400, {"error": f"bad Content-Length {raw_len!r}"})
                    return
                if not 0 <= length <= MAX_BODY:
                    self._reply(413, {"error": "request body too large",
                                      "bytes": length, "max_bytes": MAX_BODY})
                    return
                body = self.rfile.read(length)
                if path == "/v1/submit":
                    try:
                        doc = json.loads(body.decode() or "{}")
                        if not isinstance(doc, dict):
                            raise ValueError("body must be a JSON object")
                    except (ValueError, UnicodeDecodeError) as e:
                        self._reply(400, {"error": f"bad JSON body: {e}"})
                        return
                    tp = self.headers.get("traceparent")
                    code, out = router.submit(doc, traceparent=tp)
                    hdrs = router.trace_header(out.get("request_id"))
                    if hdrs is None and tp:
                        # untraced (sampled-out / error) replies still echo
                        # the caller's context verbatim — pure passthrough
                        hdrs = {"traceparent": tp}
                    self._reply(code, out, headers=hdrs)
                else:
                    self.send_error(404, "unknown endpoint")
            except Exception as e:
                self.send_error(500, repr(e))

        def log_message(self, *args):  # requests must not spam stderr
            pass

    return _Handler


class FleetRouter:
    """The fleet's single public entry (module docstring).

    ``transport(endpoint, method, path, doc) -> (status, body)`` — the
    pool RPC hook (default: stdlib HTTP; tests inject fakes and never
    open a socket).  ``scrape(endpoint) -> health | None`` — the health
    hook (default `scrape_health` with the retry budget).  ``port`` /
    ``host`` override ``IGG_FLEET_PORT`` / loopback; ``serve=False``
    keeps the router a pure in-process object (the unit-test mode).
    """

    def __init__(self, *, port: int | None = None, host: str | None = None,
                 transport=None, scrape=None, serve: bool = True):
        self.transport = transport or _http_transport
        self.scrape = scrape or scrape_health
        self._lock = threading.RLock()
        #: name -> {endpoint, key, quarantined, canary, health, health_ts}
        self.pools: dict[str, dict] = {}
        #: fleet id -> {pool, rid, spec, epoch, done}
        self.routes: dict[str, dict] = {}
        self._next_id = 0
        self._httpd = None
        self._thread = None
        self.port: int | None = None
        if serve:
            self._start_server(port, host)

    # - server lifecycle -

    def _start_server(self, port: int | None, host: str | None) -> None:
        if host is None:
            host = "127.0.0.1"
        if port is None:
            port = _config.fleet_port_env() or 0
        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), _make_handler(self)
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="igg-fleet-router",
            daemon=True,
        )
        self._thread.start()
        _telemetry.gauge("fleet.port").set(self.port)
        _telemetry.event("fleet.router_start", host=host, port=self.port)

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    # - pool membership (driven by the FleetController) -

    def register_pool(self, name: str, endpoint: str, *,
                      key: dict | None = None, canary: bool = False) -> None:
        with self._lock:
            prev = self.pools.get(name, {})
            self.pools[name] = {
                "name": name, "endpoint": endpoint, "key": dict(key or {}),
                "quarantined": False, "canary": canary,
                "health": None, "health_ts": 0.0,
            }
            if prev:
                # a respawned pool returns clean: stale health forgotten
                _telemetry.event("fleet.pool_replaced", pool=name,
                                 endpoint=endpoint)

    def quarantine_pool(self, name: str) -> None:
        with self._lock:
            if name in self.pools:
                self.pools[name]["quarantined"] = True

    def unregister_pool(self, name: str) -> None:
        with self._lock:
            self.pools.pop(name, None)

    # - health -

    def _refresh_health(self, pool: dict) -> None:
        now = time.monotonic()
        if now - pool["health_ts"] < HEALTH_TTL_S:
            return
        pool["health"] = pool_health_view(self.scrape(pool["endpoint"]))
        pool["health_ts"] = now

    def _candidates(self) -> list[dict]:
        with self._lock:
            pools = list(self.pools.values())
        for p in pools:
            self._refresh_health(p)
        return pools

    # - the routed surface -

    def submit(self, doc: dict,
               *, traceparent: str | None = None) -> tuple[int, dict]:
        """Route one submit: choose a pool, forward, record the sticky
        route.  A pool that drops the forward (transport (0, _)) is
        marked unreachable for this pass and the next-best pool tried —
        a wedged pool costs one timeout, never a failed request.

        Trace context: an inbound ``doc["trace"]`` (a replayed spec) or a
        W3C ``traceparent`` header is adopted; otherwise one is minted
        here, head-sampled (`tracing.should_sample`).  The routing hop
        records an ``igg.fleet.route`` span and the doc forwarded to the
        pool carries that span's context as ``doc["trace"]`` — the pool's
        front door chains under it, and a later `evacuate` re-submits the
        same spec, so re-routes inherit the request's identity for free."""
        inbound = doc.get("trace") if isinstance(doc.get("trace"), dict) \
            else None
        if inbound is None:
            inbound = _tracing.parse_traceparent(traceparent)
        ctx = None
        t0 = 0.0
        if _tracing.enabled() and (
            inbound is not None or _tracing.should_sample()
        ):
            tid = inbound["trace_id"] if inbound else _tracing.new_trace_id()
            ctx = {"trace_id": tid, "span_id": _tracing.new_span_id()}
            if inbound and inbound.get("span_id"):
                ctx["parent_id"] = inbound["span_id"]
            doc = dict(doc)
            doc["trace"] = {"trace_id": tid, "span_id": ctx["span_id"]}
            t0 = time.perf_counter()
        tried: set[str] = set()
        while True:
            cands = [
                dict(c, health=c["health"] or pool_health_view(None))
                for c in self._candidates() if c["name"] not in tried
            ]
            name = choose_pool(doc, cands)
            if name is None:
                _telemetry.counter("fleet.unroutable_total").inc()
                return 503, {"error": "no reachable pool for this request",
                             "tried": sorted(tried)}
            pool = self.pools[name]
            code, body = self.transport(
                pool["endpoint"], "POST", "/v1/submit", doc
            )
            if code == 0:
                tried.add(name)
                pool["health"] = pool_health_view(None)
                pool["health_ts"] = time.monotonic()
                _telemetry.event("fleet.pool_unreachable", pool=name)
                continue
            if code != 202:
                return code, body  # the pool's own 400/429 passes through
            with self._lock:
                fid = f"f{self._next_id:06d}"
                self._next_id += 1
                self.routes[fid] = {
                    "pool": name, "rid": body["request_id"],
                    "spec": dict(doc), "epoch": 0, "done": None,
                    "trace": ctx,
                }
            _telemetry.counter("fleet.routed_total").inc()
            trace_tags = {"trace_id": ctx["trace_id"]} if ctx else {}
            _telemetry.event("fleet.route", request=fid, pool=name,
                             rid=body["request_id"],
                             tenant=doc.get("tenant", "default"),
                             **trace_tags)
            if ctx is not None:
                _tracing.record_span(
                    "igg.fleet.route",
                    t0=t0, dur=time.perf_counter() - t0,
                    parent={"trace_id": ctx["trace_id"],
                            "span_id": ctx.get("parent_id")},
                    span_id=ctx["span_id"],
                    request=fid, pool=name,
                    tenant=doc.get("tenant", "default"),
                )
            return 202, {"request_id": fid, "pool": name}

    def trace_header(self, fid: str | None) -> dict | None:
        """The ``traceparent`` echo header for a routed request (None when
        the route is unknown or untraced) — every response that names a
        fleet id carries the request's context back to the caller."""
        if not fid:
            return None
        with self._lock:
            route = self.routes.get(fid)
            ctx = route.get("trace") if route else None
        if not ctx:
            return None
        return {"traceparent": _tracing.format_traceparent(ctx)}

    def adopt_result(self, fid: str, pool: str, epoch: int,
                     body: dict) -> bool:
        """Cache one done result IF ``(pool, epoch)`` still own the route.

        The zombie guard (module docstring): a superseded owner — the
        route was evacuated, or the answer arrived from a pool the route
        no longer names — is refused, its result dropped, and a
        ``fleet.zombie_result`` event marks the attempt.
        """
        with self._lock:
            route = self.routes.get(fid)
            if route is None:
                return False
            if route["pool"] != pool or route["epoch"] != epoch:
                _telemetry.counter("fleet.zombie_results_total").inc()
                _telemetry.event(
                    "fleet.zombie_result", request=fid, pool=pool,
                    epoch=epoch, owner=route["pool"],
                    owner_epoch=route["epoch"],
                )
                return False
            route["done"] = dict(body)
            return True

    def result(self, fid: str) -> tuple[int, dict]:
        """Sticky fetch: proxy to the owning pool, caching done results
        through the epoch-checked `adopt_result` path."""
        with self._lock:
            route = self.routes.get(fid)
            if route is None:
                return 404, {"error": f"unknown request {fid!r}"}
            if route["done"] is not None:
                return 200, {**route["done"], "request_id": fid,
                             "pool": route["pool"]}
            pool, rid, epoch = route["pool"], route["rid"], route["epoch"]
        endpoint = None
        with self._lock:
            if pool in self.pools:
                endpoint = self.pools[pool]["endpoint"]
        if endpoint is None:
            return 200, {"request_id": fid, "status": "pending",
                         "detail": "owning pool is being replaced"}
        code, body = self.transport(endpoint, "GET", f"/v1/result/{rid}", None)
        if code == 0:
            # the owner is dark: the controller's evacuation will re-route;
            # to the client this is still just in flight
            return 200, {"request_id": fid, "status": "pending",
                         "detail": f"pool {pool} unreachable"}
        if code == 200 and body.get("status") == "done":
            self.adopt_result(fid, pool, epoch, body)
            return 200, {**body, "request_id": fid, "pool": pool}
        if code == 404:
            # the pool lost the rid (a respawn without replay yet): pending
            return 200, {"request_id": fid, "status": "pending",
                         "detail": f"pool {pool} has no ledger entry yet"}
        body = dict(body)
        body["request_id"] = fid
        return code, body

    # - evacuation (the replay half of a respawn/quarantine) -

    def evacuate(self, name: str, *, exclude: set | None = None) -> list[str]:
        """Re-route every unfinished request owned by ``name``: bump each
        route's epoch (disowning late answers from the old incarnation),
        re-submit the spec to the best surviving pool, and point the
        route there.  Returns the re-routed fleet ids; emits ONE
        ``fleet.reroute`` event naming them (the drill's ordered middle
        marker between ``fleet.detect`` and ``fleet.recovered``).
        ``exclude`` — pools never chosen as the target (default: the
        evacuated pool itself; pass ``set()`` after a respawn to re-home
        leftover routes onto the fresh incarnation)."""
        base_exclude = {name} if exclude is None else set(exclude)
        with self._lock:
            victims = [
                (fid, route) for fid, route in self.routes.items()
                if route["pool"] == name and route["done"] is None
            ]
            for _fid, route in victims:
                route["epoch"] += 1  # late answers are zombies from here on
        # The re-route hop is part of every evacuated request's causal
        # tree: one span tagged with ALL victims' trace ids (the
        # multi-request form, like a serving round).
        trace_ids = sorted({
            route["trace"]["trace_id"] for _fid, route in victims
            if route.get("trace")
        })
        span_tags = {"trace_ids": trace_ids} if trace_ids else {}
        moved: list[str] = []
        with _tracing.trace_span("igg.fleet.reroute", pool=name,
                                 victims=len(victims), **span_tags):
            for fid, route in victims:
                tried = set(base_exclude)
                while True:
                    cands = [
                        dict(c, health=c["health"] or pool_health_view(None))
                        for c in self._candidates() if c["name"] not in tried
                    ]
                    target = choose_pool(route["spec"], cands)
                    if target is None:
                        break  # unroutable now; the next evacuate retries
                    code, body = self.transport(
                        self.pools[target]["endpoint"], "POST", "/v1/submit",
                        route["spec"],
                    )
                    if code != 202:
                        tried.add(target)
                        continue
                    with self._lock:
                        route["pool"] = target
                        route["rid"] = body["request_id"]
                    moved.append(fid)
                    break
        _telemetry.counter("fleet.rerouted_total").inc(len(moved))
        _telemetry.event("fleet.reroute", pool=name, requests=moved,
                         count=len(moved))
        return moved

    # - views -

    def status_view(self) -> dict:
        with self._lock:
            done = sum(1 for r in self.routes.values() if r["done"])
            return {
                "pools": {
                    n: {"endpoint": p["endpoint"], "key": p["key"],
                        "quarantined": p["quarantined"],
                        "canary": p["canary"],
                        "health": p["health"]}
                    for n, p in self.pools.items()
                },
                "requests": {"total": len(self.routes), "done": done},
            }

    def health_view(self) -> dict:
        cands = self._candidates()
        reachable = sum(
            1 for c in cands
            if (c["health"] or {}).get("state") not in (None, UNREACHABLE)
        )
        return {
            "ok": reachable > 0,
            "pools": {c["name"]: c["health"] for c in cands},
            "reachable": reachable,
        }
