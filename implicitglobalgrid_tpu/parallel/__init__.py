"""Topology, grid state and distributed runtime."""
