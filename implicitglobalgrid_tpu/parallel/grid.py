"""Global-grid state: the TPU-native `GlobalGrid` and its singleton.

Re-designs the reference's mutable singleton (`/root/reference/src/shared.jl:46-81`)
as a frozen dataclass holding a `jax.sharding.Mesh`.  The grid is still a
module-level singleton guarded by ``check_initialized`` with the reference's
exact error contract, because the whole point of the library is the
three-function promise (`init_global_grid` / `update_halo` /
`finalize_global_grid`) with no grid object threaded through user code.

The implicit global grid: ``nxyz_g = dims*(nxyz-overlaps) + overlaps*(periods==0)``
(`/root/reference/src/init_global_grid.jl:93`).  Arrays are represented as
*global-block* `jax.Array`s: the array holding per-device local blocks of
shape ``(nx, ny, nz)`` has global shape ``(dims[0]*nx, dims[1]*ny, dims[2]*nz)``
sharded one block per device on the mesh — overlapping cells are stored
redundantly, exactly like the reference's per-process local arrays, and the
de-duplicated global grid is never materialized (except by `gather`).
"""

from __future__ import annotations

import contextlib as _contextlib
import dataclasses
import os
import time
from typing import Any

import numpy as np

from . import topology
from .topology import AXIS_NAMES, NDIMS, PROC_NULL

DEVICE_TYPE_AUTO = "auto"
DEVICE_TYPE_TPU = "tpu"
DEVICE_TYPE_CPU = "cpu"
DEVICE_TYPE_GPU = "gpu"
_DEVICE_TYPES = (DEVICE_TYPE_AUTO, DEVICE_TYPE_TPU, DEVICE_TYPE_CPU, DEVICE_TYPE_GPU)


@dataclasses.dataclass(frozen=True)
class GlobalGrid:
    """Immutable snapshot of the grid topology (reference: src/shared.jl:46-65).

    ``nprocs`` counts *blocks* (= devices), the analogue of MPI ranks; ``me``
    and ``coords`` are the process-level view (the first local device's block)
    used by host-side helpers like `x_g` and as `gather`'s root identity.
    """

    nxyz_g: tuple[int, int, int]
    nxyz: tuple[int, int, int]
    dims: tuple[int, int, int]
    overlaps: tuple[int, int, int]
    nprocs: int
    me: int
    coords: tuple[int, int, int]
    neighbors: Any  # np.ndarray (2, 3), PROC_NULL where absent
    periods: tuple[int, int, int]
    disp: int
    reorder: int
    mesh: Any  # jax.sharding.Mesh with axis names ("x", "y", "z")
    device_type: str
    quiet: bool
    # monotonically increasing across init/finalize cycles; keys jit caches
    epoch: int = 0
    # Snapshot at init time of whether this library brought up the
    # distributed runtime (see `distributed.owns_runtime`, which is the
    # live, module-level flag `finalize_global_grid` actually consults —
    # ownership survives `finalize_distributed=False` re-init cycles).
    owns_distributed: bool = False
    # Route even degenerate 1-device grids through shard_map/NamedSharding
    # (used by the weak-scaling benchmark so t(1) and t(N) measure the same
    # execution path; see docs/performance.md on the SPMD-path cost).
    force_spmd: bool = False

    def replace(self, **kw) -> "GlobalGrid":
        return dataclasses.replace(self, **kw)

    def checkpoint_meta(self) -> dict:
        """Topology metadata a checkpoint must match to be restorable here
        (`utils.checkpoint`): the implicit-global-grid identity — local
        sizes, dims, overlaps, periods — without runtime objects (mesh,
        devices) that legitimately differ across restarts."""
        return {
            "dims": list(self.dims),
            "nxyz": list(self.nxyz),
            "nxyz_g": list(self.nxyz_g),
            "overlaps": list(self.overlaps),
            "periods": list(self.periods),
            "disp": int(self.disp),
            "nprocs": int(self.nprocs),
            "device_type": self.device_type,
        }


def elastic_topology_error(saved: dict, current: dict) -> str | None:
    """Why ``current`` cannot elastically restore a checkpoint written under
    ``saved``, or None when it can.

    Both arguments are `GlobalGrid.checkpoint_meta` dicts.  The implicit
    global grid makes topology a *derived* quantity — any ``(nxyz, dims,
    overlaps, periods)`` implying the same de-duplicated global size
    (`topology.implied_global_shape`) describes the same physical grid, so a
    checkpoint written at ``dims=(2,2,2)`` is restorable on a surviving
    ``(2,2,1)`` or replacement ``(4,1,2)`` slice.  Periodicity must match:
    it is part of the physical problem (and changes the de-dup identity of
    the boundary overlap), not of the decomposition.
    """
    mismatches = []
    if tuple(saved.get("periods", ())) != tuple(current.get("periods", ())):
        mismatches.append(
            f"periods: checkpoint {list(saved.get('periods', []))} vs "
            f"current {list(current.get('periods', []))} (periodicity is "
            f"part of the physical problem, not of the decomposition)"
        )
    saved_g = topology.implied_global_shape(
        saved["nxyz"], saved["dims"], saved["overlaps"], saved["periods"]
    )
    cur_g = topology.implied_global_shape(
        current["nxyz"], current["dims"], current["overlaps"], current["periods"]
    )
    if saved_g != cur_g:
        mismatches.append(
            f"implied global size nxyz_g = dims*(nxyz-overlaps) + "
            f"overlaps*(periods==0): checkpoint "
            f"{list(saved.get('nxyz_g', saved_g))} (from nxyz="
            f"{list(saved['nxyz'])}, dims={list(saved['dims'])}, overlaps="
            f"{list(saved['overlaps'])}) vs current {list(cur_g)} (from "
            f"nxyz={list(current['nxyz'])}, dims={list(current['dims'])}, "
            f"overlaps={list(current['overlaps'])}) — adjust the local "
            f"sizes so the target topology spans the same global grid"
        )
    if mismatches:
        return "; ".join(mismatches)
    return None


_global_grid: GlobalGrid | None = None
_epoch = 0


def grid_is_initialized() -> bool:
    return _global_grid is not None


def check_initialized() -> None:
    # Error message contract from /root/reference/src/shared.jl:77.
    if not grid_is_initialized():
        raise RuntimeError(
            "No function of the module can be called before init_global_grid() "
            "or after finalize_global_grid()."
        )


def global_grid() -> GlobalGrid:
    check_initialized()
    return _global_grid


def set_global_grid(gg: GlobalGrid | None) -> None:
    global _global_grid
    _global_grid = gg


def get_global_grid() -> GlobalGrid:
    """Return the (immutable) current grid (reference: src/shared.jl:80)."""
    check_initialized()
    return _global_grid


def init_global_grid(
    nx: int,
    ny: int = 1,
    nz: int = 1,
    *,
    dimx: int = 0,
    dimy: int = 0,
    dimz: int = 0,
    periodx: int = 0,
    periody: int = 0,
    periodz: int = 0,
    overlapx: int | None = None,
    overlapy: int | None = None,
    overlapz: int | None = None,
    disp: int = 1,
    reorder: int | None = None,
    devices=None,
    device_type: str | None = None,
    init_distributed: bool = False,
    distributed_kwargs: dict | None = None,
    select_device: bool = True,
    quiet: bool | None = None,
    force_spmd: bool = False,
):
    """Initialize the Cartesian device topology, implicitly defining a global grid.

    TPU-native counterpart of `/root/reference/src/init_global_grid.jl:40-99`.
    ``nx, ny, nz`` are the *local* (per-device-block) grid sizes.  The device
    count is factored into ``dims`` (fixed entries honored, zeros filled
    balanced — `dims_create`), a 3-D `Mesh` is created over the TPU slice
    (``reorder=1`` aligns mesh axes with the ICI torus), and the implicit
    global size is derived as ``dims*(nxyz-overlaps) + overlaps*(periods==0)``.

    Configuration tiers (reference: src/init_global_grid.jl:40,51-68):
    explicit kwargs > ``IGG_*`` env vars (`utils.config.env_config`) >
    defaults.  ``init_distributed=True`` (the reference's ``init_MPI``) brings
    up the JAX multi-host runtime first; ``devices`` (the reference's
    ``comm``) restricts the grid to a device subset.

    Returns ``(me, dims, nprocs, coords, mesh)`` — the mesh takes the place of
    the reference's Cartesian communicator in the return tuple.
    """
    global _epoch
    import jax

    from ..utils.config import env_config

    if grid_is_initialized():
        raise RuntimeError("The global grid has already been initialized.")
    # Env tier (reference: src/init_global_grid.jl:51-68): kwargs > env > defaults.
    env = env_config()
    env_overlap = env.get("overlap", 2)
    overlapx = env_overlap if overlapx is None else overlapx
    overlapy = env_overlap if overlapy is None else overlapy
    overlapz = env_overlap if overlapz is None else overlapz
    reorder = env.get("reorder", 1) if reorder is None else reorder
    device_type = env.get("device_type", DEVICE_TYPE_AUTO) if device_type is None else device_type
    quiet = env.get("quiet", False) if quiet is None else quiet
    owns_distributed = False
    if init_distributed:
        # The reference's `init_MPI=true` analogue: bring up the multi-host
        # runtime before touching devices (src/init_global_grid.jl:78-83).
        # ``distributed_kwargs`` (coordinator_address, num_processes,
        # process_id, ...) pass through for manual cluster setups; on Cloud
        # TPU pods they auto-detect.
        from . import distributed as _distributed

        _distributed.init_distributed(**(distributed_kwargs or {}))
        owns_distributed = _distributed.owns_runtime()
    nxyz = [int(nx), int(ny), int(nz)]
    dims = [int(dimx), int(dimy), int(dimz)]
    periods = [int(periodx), int(periody), int(periodz)]
    overlaps = [int(overlapx), int(overlapy), int(overlapz)]

    if device_type not in _DEVICE_TYPES:
        raise ValueError(
            f"Argument `device_type`: invalid value obtained ({device_type}). "
            f"Valid values are: {', '.join(_DEVICE_TYPES)}"
        )
    # Argument validation ported from src/init_global_grid.jl:73-77.
    if nxyz[0] == 1:
        raise ValueError("Invalid arguments: nx can never be 1.")
    if nxyz[1] == 1 and nxyz[2] > 1:
        raise ValueError("Invalid arguments: ny cannot be 1 if nz is greater than 1.")
    if any(n == 1 and d > 1 for n, d in zip(nxyz, dims)):
        raise ValueError(
            "Incoherent arguments: if nx, ny, or nz is 1, then the corresponding "
            "dimx, dimy or dimz must not be set (or set 0 or 1)."
        )
    if any(n < 2 * o - 1 and p > 0 for n, o, p in zip(nxyz, overlaps, periods)):
        raise ValueError(
            "Incoherent arguments: if nx, ny, or nz is smaller than 2*overlapx-1, "
            "2*overlapy-1 or 2*overlapz-1, respectively, then the corresponding "
            "periodx, periody or periodz must not be set (or set 0)."
        )
    for d in range(NDIMS):
        if nxyz[d] == 1 and dims[d] == 0:
            dims[d] = 1  # src/init_global_grid.jl:77

    if devices is None:
        if device_type == DEVICE_TYPE_AUTO:
            devices = jax.devices()
        else:
            devices = jax.devices(device_type)
    nprocs = len(devices)
    dims = topology.dims_create(nprocs, tuple(dims))
    mesh = topology.create_mesh(dims, devices=devices, reorder=reorder)

    # This process's block identity = the mesh position of its first local
    # device (create_mesh with reorder=1 may permute devices for ICI locality,
    # so positions cannot be inferred from rank arithmetic).
    first_local = jax.local_devices()[0]
    pos = np.argwhere(mesh.devices == first_local)
    coords = tuple(int(c) for c in pos[0]) if len(pos) else (0, 0, 0)
    me = topology.rank_of_coords(coords, dims)
    neighbors = topology.neighbors_table(coords, dims, periods, disp)
    nxyz_g = topology.implied_global_shape(
        nxyz, dims, overlaps, periods
    )  # src/init_global_grid.jl:93

    _epoch += 1
    gg = GlobalGrid(
        nxyz_g=nxyz_g,
        nxyz=tuple(nxyz),
        dims=dims,
        overlaps=tuple(overlaps),
        nprocs=nprocs,
        me=me,
        coords=coords,
        neighbors=neighbors,
        periods=tuple(periods),
        disp=int(disp),
        reorder=int(reorder),
        mesh=mesh,
        device_type=device_type,
        quiet=bool(quiet),
        epoch=_epoch,
        owns_distributed=owns_distributed,
        force_spmd=bool(force_spmd),
    )
    set_global_grid(gg)
    if not quiet and jax.process_index() == 0:
        print(
            f"Global grid: {nxyz_g[0]}x{nxyz_g[1]}x{nxyz_g[2]} "
            f"(nprocs: {nprocs}, dims: {dims[0]}x{dims[1]}x{dims[2]})"
        )
    if select_device:
        _select_device()
    # The first barrier is the first collective every process must enter: a
    # straggler or mis-set coordinator hangs exactly here, in C++ where
    # Python tracebacks see nothing — the IGG_WATCHDOG_S watchdog dumps
    # all-thread stacks (and the env tier keeps it out of the hot loop).
    from ..utils import config as _cfg
    from ..utils import tracing as _tracing
    from ..utils.resilience import watchdog as _watchdog

    with _watchdog(_cfg.watchdog_env()):
        init_timing_functions()
        # Cross-rank clock sync (docs/observability.md): one more barrier,
        # with every rank's wall/perf clocks sampled right at its exit —
        # the shared instant `igg.dump_trace` merges per-rank timelines on.
        # The recorded uncertainty is the measured barrier duration (the
        # honest bound on cross-rank alignment).  Single process: no
        # barrier needed, the one local clock aligns with itself.
        _tracing.record_clock_sync(
            _barrier if jax.process_count() > 1 else None, epoch=_epoch
        )
    return me, dims, nprocs, coords, mesh


def finalize_global_grid(*, finalize_distributed: bool = True) -> None:
    """Tear down the grid singleton (reference: src/finalize_global_grid.jl:15-27).

    There are no MPI handles, pinned host buffers or persistent streams to
    free on TPU — communication state lives inside compiled XLA executables —
    so finalization drops the singleton and the grid-keyed jit caches.

    If `init_global_grid(init_distributed=True)` brought up the multi-host
    runtime, it is shut down here too — the reference's guarded
    ``MPI.Finalize`` (`/root/reference/src/finalize_global_grid.jl:19-23`).
    Pass ``finalize_distributed=False`` (the reference's ``finalize_MPI=false``)
    to keep the runtime alive, e.g. to re-init another grid in this process.
    """
    global _barrier_fn
    check_initialized()
    from ..models import _batched as _batched_mod
    from ..ops import gather as _gather
    from ..ops import halo as _halo
    from ..ops import stencil as _stencil
    from ..serving import frontdoor as _frontdoor
    from ..utils import resilience as _resilience
    from ..utils import tracing as _tracing

    _halo._clear_caches()
    _stencil._clear_caches()
    _gather._clear_caches()
    _resilience._clear_caches()
    _batched_mod._clear_caches()
    _tracing._clear_caches()
    _frontdoor._clear_caches()
    _barrier_fn = None
    set_global_grid(None)
    if finalize_distributed:
        from . import distributed as _distributed

        if _distributed.owns_runtime():
            _distributed.shutdown_distributed()


def select_device():
    """Bind this process to its accelerator and return the device.

    Parity shim for `/root/reference/src/select_device.jl:15-38`: under JAX's
    multi-controller runtime each process already owns its local devices
    (the work `MPI.Comm_split_type(COMM_TYPE_SHARED)` + `CUDA.device!` does in
    the reference happens implicitly at runtime init), so this validates the
    binding and returns the first local device.
    """
    import jax

    check_initialized()
    gg = global_grid()
    if gg.device_type != DEVICE_TYPE_AUTO:
        platforms = {d.platform for d in jax.local_devices()}
        if gg.device_type not in platforms:
            raise RuntimeError(
                f"Cannot select a device of type {gg.device_type!r}: local devices "
                f"are of platform(s) {sorted(platforms)}."
            )
    return jax.local_devices()[0]


def _select_device():
    return select_device()


# -- Timing tools (reference: src/tools.jl:230-236) --------------------------

# None = no user tic() yet: toc() must raise instead of measuring from an
# arbitrary epoch (init_timing_functions primes the barrier but resets this).
_t0: list[float | None] = [None]
_barrier_fn = None


def _barrier() -> None:
    """Synchronize all devices (the reference's `MPI.Barrier(comm())`).

    A tiny jitted all-device `psum` is dispatched and blocked on; on a
    multi-host runtime this synchronizes every process through ICI/DCN.
    """
    global _barrier_fn
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils.compat import shard_map

    gg = global_grid()
    if _barrier_fn is None or _barrier_fn[0] is not gg.mesh:
        mesh = gg.mesh
        mapped = shard_map(
            lambda: jnp.zeros((), jnp.int32),
            mesh=mesh,
            in_specs=(),
            out_specs=P(),
            check_vma=False,
        )
        _barrier_fn = (mesh, jax.jit(mapped, out_shardings=NamedSharding(mesh, P())))
    jax.block_until_ready(_barrier_fn[1]())


def tic() -> None:
    """Start the chronometer once all devices have reached this point.

    Monotonic (`time.perf_counter`): a wall-clock (`time.time`) chronometer
    jumps with NTP slews/steps, which at multi-minute production timings is
    a real error source — and the reference's own contract is pure elapsed
    time, not timestamps.
    """
    check_initialized()
    _barrier()
    _t0[0] = time.perf_counter()


def toc() -> float:
    """Elapsed seconds since `tic` once all devices have reached this point."""
    check_initialized()
    if _t0[0] is None:
        raise RuntimeError(
            "toc() called before tic(): the chronometer was never started "
            "(call igg.tic() at the start of the timed section)."
        )
    _barrier()
    return time.perf_counter() - _t0[0]


def init_timing_functions() -> None:
    # Pre-compile the barrier so the first user tic()/toc() is fast
    # (reference: src/init_global_grid.jl:97,102-105) — then reset the
    # chronometer: the priming tic must not masquerade as a user tic (a
    # user's toc()-without-tic() would silently time since init).
    tic()
    toc()
    _t0[0] = None


@_contextlib.contextmanager
def profile_trace(logdir, **kwargs):
    """Profiler hook: record a `jax.profiler` trace of the enclosed block.

    Thin alias of `utils.profiling.profile_trace` — the ONE capture
    implementation of the device-timeline plane (docs/observability.md):
    ``create_perfetto_trace`` now defaults True so the capture always
    emits the parseable ``*.trace.json.gz`` that
    ``scripts/igg_prof.py attribute`` and ``igg_trace.py merge --device``
    consume.  Kept at its historical home for API stability; new code
    should prefer the env-armed windowed capture (``IGG_PROFILE=
    steps:A-B``), which needs no code changes and writes the per-rank
    capture meta the tooling discovers::

        with igg.profile_trace("/tmp/igg-trace"):
            for _ in range(100):
                state = step(*state)
        # then: python scripts/igg_prof.py attribute /tmp/igg-trace
    """
    from ..utils import profiling as _profiling

    with _profiling.profile_trace(logdir, **kwargs):
        yield
