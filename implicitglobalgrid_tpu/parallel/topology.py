"""Cartesian process/device topology for the implicit global grid.

TPU-native replacement for the reference's MPI topology layer
(`/root/reference/src/init_global_grid.jl:84-92`): instead of
``MPI_Dims_create`` + ``MPI_Cart_create`` + ``MPI_Cart_shift`` we factor the
device count into a 1/2/3-D grid and build a `jax.sharding.Mesh` over the TPU
slice.  With ``reorder=1`` (the analogue of ``MPI_Cart_create``'s reorder
flag) the device order is chosen by ``mesh_utils.create_device_mesh`` so mesh
axes ride the physical ICI torus; with ``reorder=0`` devices are laid out in
row-major rank order.

Rank convention: like an MPI Cartesian communicator created in C order, the
rank of the block at Cartesian coordinates ``(cx, cy, cz)`` is
``(cx * dims[1] + cy) * dims[2] + cz`` (dimension 0 varies slowest).
"""

from __future__ import annotations

import numpy as np

PROC_NULL = -1  # analogue of MPI.PROC_NULL (reference: src/shared.jl neighbors init)
NDIMS = 3  # fixed internal dimensionality (reference: src/shared.jl:29 NDIMS_MPI = 3)
NNEIGHBORS_PER_DIM = 2  # left + right (reference: src/shared.jl:30)

AXIS_NAMES = ("x", "y", "z")  # mesh axis names used by all collectives


def _prime_factors(n: int) -> list[int]:
    fs = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            fs.append(d)
            n //= d
        d += 1
    if n > 1:
        fs.append(n)
    return fs


def dims_create(nprocs: int, dims: tuple[int, int, int]) -> tuple[int, int, int]:
    """Factor ``nprocs`` into a balanced Cartesian grid.

    Semantics of ``MPI_Dims_create`` (used by the reference at
    `/root/reference/src/init_global_grid.jl:85`): entries of ``dims`` that are
    nonzero are kept fixed; zero entries are filled with a factorization of
    ``nprocs / prod(fixed)`` that is as balanced as possible, with larger
    factors placed in lower (earlier) free dimensions.
    """
    dims = tuple(int(d) for d in dims)
    if any(d < 0 for d in dims):
        raise ValueError(f"dims entries must be >= 0, got {dims}")
    fixed_prod = 1
    for d in dims:
        if d > 0:
            fixed_prod *= d
    if nprocs % fixed_prod != 0:
        raise ValueError(
            f"The number of devices ({nprocs}) is not divisible by the product of "
            f"the fixed dims entries ({fixed_prod})."
        )
    free = [i for i, d in enumerate(dims) if d == 0]
    rem = nprocs // fixed_prod
    if not free:
        if fixed_prod != nprocs:
            raise ValueError(
                f"prod(dims)={fixed_prod} does not match the number of devices ({nprocs})."
            )
        return dims
    # Distribute prime factors of `rem` over the free slots as evenly as possible:
    # repeatedly multiply the currently-smallest slot by the largest remaining factor.
    slots = [1] * len(free)
    for f in sorted(_prime_factors(rem), reverse=True):
        slots[int(np.argmin(slots))] *= f
    # MPI_Dims_create returns free dims in non-increasing order.
    slots.sort(reverse=True)
    out = list(dims)
    for i, s in zip(free, slots):
        out[i] = s
    return tuple(out)


def implied_global_shape(nxyz, dims, overlaps, periods) -> tuple[int, ...]:
    """The implicit global grid size ``nxyz_g`` a topology defines.

    The identity the whole library rests on
    (`/root/reference/src/init_global_grid.jl:93`)::

        nxyz_g = dims*(nxyz - overlaps) + overlaps*(periods == 0)

    Exposed as a pure function so `init_global_grid` and the elastic
    checkpoint restore (`utils.checkpoint`) derive the global size from ONE
    formula: a checkpoint written under one ``(nxyz, dims, overlaps,
    periods)`` is restorable under any other that implies the same
    ``nxyz_g`` (`parallel.grid.elastic_topology_error`).
    """
    return tuple(
        int(d) * (int(n) - int(o)) + int(o) * (int(p) == 0)
        for n, d, o, p in zip(nxyz, dims, overlaps, periods)
    )


def rank_of_coords(coords, dims) -> int:
    """Row-major (C-order) rank of Cartesian coordinates, dim 0 slowest."""
    cx, cy, cz = coords
    return (cx * dims[1] + cy) * dims[2] + cz


def coords_of_rank(rank: int, dims) -> tuple[int, int, int]:
    cz = rank % dims[2]
    cy = (rank // dims[2]) % dims[1]
    cx = rank // (dims[1] * dims[2])
    return (cx, cy, cz)


def neighbors_table(coords, dims, periods, disp: int = 1) -> np.ndarray:
    """Neighbor ranks, shape (NNEIGHBORS_PER_DIM, NDIMS).

    ``neighbors[0, d]`` is the lower/left neighbor in dimension ``d`` (the
    source of an ``MPI_Cart_shift(d, disp)``), ``neighbors[1, d]`` the
    upper/right one (the destination); ``PROC_NULL`` (-1) where the grid is
    non-periodic and the shift falls off the edge.  Mirrors the table built at
    `/root/reference/src/init_global_grid.jl:89-92`.
    """
    nbrs = np.full((NNEIGHBORS_PER_DIM, NDIMS), PROC_NULL, dtype=np.int32)
    for d in range(NDIMS):
        for sgn, n in ((-1, 0), (+1, 1)):
            c = list(coords)
            c[d] += sgn * disp
            if periods[d]:
                c[d] %= dims[d]
            elif not (0 <= c[d] < dims[d]):
                continue
            nbrs[n, d] = rank_of_coords(c, dims)
    return nbrs


def create_mesh(dims, devices=None, reorder: int = 1):
    """Build the 3-D device mesh with axis names ("x", "y", "z").

    ``reorder=1`` lets JAX pick a device order that maps mesh axes onto the
    physical ICI torus (`mesh_utils.create_device_mesh`) — the analogue of
    ``MPI_Cart_create(..., reorder=1)`` at
    `/root/reference/src/init_global_grid.jl:86`.  ``reorder=0`` keeps plain
    rank order (row-major over the Cartesian coordinates).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    dims = tuple(int(d) for d in dims)
    n = int(np.prod(dims))
    if n != len(devices):
        raise ValueError(
            f"prod(dims)={n} does not match the number of devices ({len(devices)})."
        )
    if reorder and len(devices) > 1:
        try:
            dev_array = mesh_utils.create_device_mesh(dims, devices=devices)
        except Exception:  # fall back to rank order (e.g. heterogeneous CPU meshes)
            dev_array = np.asarray(devices).reshape(dims)
    else:
        dev_array = np.asarray(devices).reshape(dims)
    return Mesh(dev_array, AXIS_NAMES)
