"""Multi-host runtime helpers (the reference's "MPI layer", §2.3 of SURVEY.md).

The reference leans on `MPI.Init`/communicators for its process runtime
(`/root/reference/src/init_global_grid.jl:78-92`).  JAX's multi-controller
runtime plays that role on TPU pods: one Python process per host, all devices
visible as one mesh, collectives compiled to ICI/DCN transfers.  These are
thin, explicit wrappers so applications keep the reference's
init-before-grid / finalize-after-grid lifecycle.
"""

from __future__ import annotations

import os

# True while THIS module brought the distributed runtime up and it has not
# been shut down — module-level (not per-grid) so ownership survives
# `finalize_global_grid(finalize_distributed=False)` + re-init cycles
# (the reference's guarded `MPI.Finalize` semantics,
# `/root/reference/src/finalize_global_grid.jl:19-23`).
_owns_runtime = False


def owns_runtime() -> bool:
    return _owns_runtime


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    retries: int | None = None,
    timeout_s: float | None = None,
    backoff_s: float | None = None,
    **kwargs,
) -> None:
    """Initialize the JAX distributed runtime (multi-host), with retry.

    The analogue of `MPI.Init()` in `init_global_grid`
    (`/root/reference/src/init_global_grid.jl:78-83`).  On Cloud TPU pods the
    arguments are auto-detected and may all be ``None``.  Safe to call when
    already initialized (no-op), mirroring the reference's `init_MPI=false`
    escape hatch.

    Bring-up is *guarded* (coordinator races are the #1 multi-host failure
    at pod scale): a failed `jax.distributed.initialize` is retried with
    exponential backoff + seeded jitter under an overall deadline, and a
    watchdog dumps all-thread stacks if an attempt hangs past the deadline.
    Knobs resolve kwarg > env > default (the reference's configuration
    tiers): ``retries`` / ``IGG_INIT_RETRIES`` (default 3), ``timeout_s`` /
    ``IGG_INIT_TIMEOUT_S`` (default 600), ``backoff_s`` /
    ``IGG_INIT_BACKOFF_S`` (default 1).
    """
    import jax

    from ..utils import config as _config
    from ..utils import resilience as _resilience

    global _owns_runtime
    if is_distributed_initialized():
        return
    if retries is None:
        retries = _config.init_retries_env()
        retries = _resilience.DEFAULT_INIT_RETRIES if retries is None else retries
    if timeout_s is None:
        timeout_s = _config.init_timeout_env()
        timeout_s = (
            _resilience.DEFAULT_INIT_TIMEOUT_S if timeout_s is None else timeout_s
        )
    if backoff_s is None:
        backoff_s = _config.init_backoff_env()
        backoff_s = (
            _resilience.DEFAULT_INIT_BACKOFF_S if backoff_s is None else backoff_s
        )
    if retries < 0:
        raise ValueError(f"retries must be >= 0 (got {retries})")
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0 (got {timeout_s})")
    injector = _resilience.get_fault_injector()
    if process_id is not None:
        # Bring-up events (retries, injected flakes) fire before the runtime
        # can answer jax.process_index(); stage the known rank so they are
        # tagged (and filed) correctly instead of all claiming rank 0.
        from ..utils import telemetry as _telemetry

        _telemetry.set_rank_hint(process_id)

    def attempt():
        injector.maybe_flake_init()  # IGG_FAULT_INJECT=init_flake:N harness
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except BaseException:
            # A half-initialized client must not poison the next attempt
            # (initialize raises "already initialized" otherwise).
            try:
                if is_distributed_initialized():
                    jax.distributed.shutdown()
            except Exception:
                pass
            raise

    # Watchdog default = the overall deadline; IGG_WATCHDOG_S overrides it,
    # and an explicit 0 disables (watchdog(0) is the off path).
    wd_env = _config.watchdog_env()
    with _resilience.watchdog(timeout_s if wd_env is None else wd_env):
        _resilience.retry_call(
            attempt,
            retries=retries,
            timeout_s=timeout_s,
            base_backoff_s=backoff_s,
            seed=process_id,
            describe="jax.distributed.initialize",
        )
    _owns_runtime = True


def is_distributed_initialized() -> bool:
    """Whether the multi-host runtime is up.

    Prefers the private ``jax._src.distributed.global_state`` (the only
    introspection older JAX offers) but degrades to the public
    ``jax.distributed.is_initialized`` — a JAX upgrade that moves the
    private module yields a clear error naming the missing APIs instead of
    an AttributeError from deep inside.
    """
    import jax

    try:
        state = getattr(jax._src.distributed, "global_state", None)
    except AttributeError:
        state = None
    if state is not None:
        return state.client is not None
    public = getattr(getattr(jax, "distributed", None), "is_initialized", None)
    if callable(public):
        return bool(public())
    raise RuntimeError(
        "Cannot determine whether the JAX distributed runtime is "
        "initialized: this JAX version exposes neither "
        "jax._src.distributed.global_state nor "
        "jax.distributed.is_initialized. Please report the installed JAX "
        "version to implicitglobalgrid_tpu."
    )


def shutdown_distributed() -> None:
    """Shut down the distributed runtime (`MPI.Finalize` analogue,
    `/root/reference/src/finalize_global_grid.jl:19-23`)."""
    import jax

    global _owns_runtime
    if is_distributed_initialized():
        jax.distributed.shutdown()
    _owns_runtime = False


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def sync_all_processes() -> None:
    """Host-level barrier across all processes (and their devices)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("igg_sync")
