"""Multi-host runtime helpers (the reference's "MPI layer", §2.3 of SURVEY.md).

The reference leans on `MPI.Init`/communicators for its process runtime
(`/root/reference/src/init_global_grid.jl:78-92`).  JAX's multi-controller
runtime plays that role on TPU pods: one Python process per host, all devices
visible as one mesh, collectives compiled to ICI/DCN transfers.  These are
thin, explicit wrappers so applications keep the reference's
init-before-grid / finalize-after-grid lifecycle.
"""

from __future__ import annotations

import os

# True while THIS module brought the distributed runtime up and it has not
# been shut down — module-level (not per-grid) so ownership survives
# `finalize_global_grid(finalize_distributed=False)` + re-init cycles
# (the reference's guarded `MPI.Finalize` semantics,
# `/root/reference/src/finalize_global_grid.jl:19-23`).
_owns_runtime = False


def owns_runtime() -> bool:
    return _owns_runtime


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> None:
    """Initialize the JAX distributed runtime (multi-host).

    The analogue of `MPI.Init()` in `init_global_grid`
    (`/root/reference/src/init_global_grid.jl:78-83`).  On Cloud TPU pods the
    arguments are auto-detected and may all be ``None``.  Safe to call when
    already initialized (no-op), mirroring the reference's `init_MPI=false`
    escape hatch.
    """
    import jax

    global _owns_runtime
    if is_distributed_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _owns_runtime = True


def is_distributed_initialized() -> bool:
    import jax

    state = getattr(jax._src.distributed, "global_state", None)
    return bool(state is not None and state.client is not None)


def shutdown_distributed() -> None:
    """Shut down the distributed runtime (`MPI.Finalize` analogue,
    `/root/reference/src/finalize_global_grid.jl:19-23`)."""
    import jax

    global _owns_runtime
    if is_distributed_initialized():
        jax.distributed.shutdown()
    _owns_runtime = False


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def sync_all_processes() -> None:
    """Host-level barrier across all processes (and their devices)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("igg_sync")
