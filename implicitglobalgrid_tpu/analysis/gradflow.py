"""Grad-soundness analyzer (``grad-soundness``) — zero-gradient sinks.

PR 5's bug class: `lax.bitcast_convert_type` has NO tangent rule — JAX
treats it like an integer-valued op and produces a zero cotangent — so a
bitcast-packed transport without a registered custom VJP makes ``jax.grad``
silently drop every cotangent that crosses a block boundary.  Nothing
crashes; the gradient is just wrong, and only a finite-difference oracle
notices.  This pass makes that class a static invariant along two legs:

1. **Dropper scan** (`dropper_findings`) — walk the traced jaxpr of every
   entry point in the config matrix and flag cotangent-dropping primitives
   on the tangent path: ``bitcast_convert_type`` and float→integer
   ``convert_element_type`` are CRITICAL, ``stop_gradient`` is a WARNING
   (often intentional, never invisible).  "On the tangent path" = at least
   one floating operand derived from the entry's differentiable inputs AND
   an output that feeds the entry's outputs.  Sub-programs under a
   ``custom_vjp``/``custom_jvp`` envelope are exempt — a registered VJP is
   exactly the documented fix (`_packed_transport`, `fused_with_xla_grad`)
   — and ``pallas_call`` bodies are kernel-internal, reached only through
   such envelopes.

2. **Backward-collective census** (`census_findings`) — trace the VJP of
   every differentiable entry point (`ir.trace_grad_entries`: the coalesced
   exchange per model + each fused cadence) and require the VJP program to
   issue MORE collectives than its primal: a cross-boundary cotangent must
   ride collectives backward, so a VJP trace with no backward collectives
   has dropped its cross-rank gradient even if no known dropper primitive
   was spotted.  This leg is detector-of-last-resort: it catches droppers
   the scan's list does not know about yet.

ROADMAP item 4 (adjoint inversion) builds directly on the gradient path;
this pass is the contract it builds on.
"""

from __future__ import annotations

import os

import numpy as np

from .core import Context, Finding

ANALYZER = "grad-soundness"

#: Envelope primitives whose sub-programs carry a REGISTERED derivative —
#: their internals may legally use non-differentiable transports.
_PROTECTED = ("custom_vjp", "custom_jvp")

#: Cotangent-dropping primitives and their severities.  ``stop_gradient``
#: warns rather than fails: cutting a gradient is sometimes the point, but
#: it must never be invisible on a production tangent path.
_DROPPERS = {
    "bitcast_convert_type": "CRITICAL",
    "stop_gradient": "WARNING",
}


def _inexact(v) -> bool:
    dt = getattr(getattr(v, "aval", None), "dtype", None)
    return dt is not None and np.issubdtype(dt, np.inexact)


def _eqn_location(eqn) -> tuple[str, int]:
    """Best-effort ``(file, line)`` of one equation (private-API tolerant).

    Paths under the repo come back REPO-RELATIVE — the fingerprint hashes
    the path, so an absolute checkout prefix would pin baselines (and the
    SARIF ``artifactLocation.uri``) to one machine.  Foreign paths
    (site-packages) stay as-is: they are diagnostics, not suppressables.
    """
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            path = str(frame.file_name)
            if os.path.isabs(path) and path.startswith(repo + os.sep):
                path = os.path.relpath(path, repo)
            return path, int(frame.start_line)
    except Exception:  # noqa: BLE001 — source info is best-effort decoration
        pass
    return "", 0


def _is_float_to_int_cast(eqn) -> bool:
    if eqn.primitive.name != "convert_element_type":
        return False
    new = eqn.params.get("new_dtype")
    try:
        drops = np.issubdtype(np.dtype(new), np.integer) or np.issubdtype(
            np.dtype(new), np.bool_
        )
    except Exception:  # noqa: BLE001 — exotic target dtype: not our class
        return False
    return drops and any(_inexact(v) for v in eqn.invars)


def dropper_findings(jaxpr, entry_name: str) -> list[Finding]:
    """Cotangent-dropping primitives on the tangent path of one traced
    entry (empty = clean).  Scopes are analyzed independently and
    conservatively: within each (sub-)jaxpr, a variable is tainted when it
    derives from a floating input of that scope, and feeding when it
    reaches that scope's outputs — over-approximate across nesting, which
    errs toward reporting (the finding names file:line to triage)."""
    out = []
    _scan_scope(jaxpr, entry_name, (), out)
    return out


def _scan_scope(jaxpr, entry_name: str, path: tuple, out: list) -> None:
    tainted = {id(v) for v in jaxpr.invars if _inexact(v)}
    for eqn in jaxpr.eqns:
        if any(id(v) in tainted for v in eqn.invars):
            tainted.update(id(v) for v in eqn.outvars)
    feeding = {id(v) for v in jaxpr.outvars}
    for eqn in reversed(jaxpr.eqns):
        if any(id(v) in feeding for v in eqn.outvars):
            feeding.update(id(v) for v in eqn.invars)

    from .ir import _sub_jaxprs

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if any(p in name for p in _PROTECTED):
            continue  # registered derivative — the documented fix
        if name == "pallas_call":
            continue  # kernel-internal; reached via a custom-VJP envelope
        severity = _DROPPERS.get(name)
        if severity is None and _is_float_to_int_cast(eqn):
            severity = "CRITICAL"
        if severity is not None:
            on_path = any(
                _inexact(v) and id(v) in tainted for v in eqn.invars
            ) and any(id(v) in feeding for v in eqn.outvars)
            if on_path:
                fpath, line = _eqn_location(eqn)
                dtypes = ",".join(
                    str(getattr(getattr(v, "aval", None), "dtype", "?"))
                    for v in eqn.invars
                )
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="cotangent-dropper",
                        severity=severity,
                        message=(
                            f"{entry_name}: `{name}` on the tangent path "
                            f"(operands {dtypes}"
                            + (f", under {'/'.join(path)}" if path else "")
                            + ") has no derivative — jax.grad will "
                            "silently produce ZERO cotangents through it "
                            "(the PR-5 coalesced-transport class)."
                        ),
                        path=fpath,
                        line=line,
                        symbol=entry_name,
                        anchor=f"{name}[{dtypes}]",
                        fix_hint=(
                            "wrap the transport in jax.custom_vjp and "
                            "differentiate a value-identical per-field "
                            "twin (see ops/halo.py::_packed_transport), "
                            "or keep the op off the differentiable path"
                        ),
                    )
                )
            continue
        for _, sub in _sub_jaxprs(eqn):
            _scan_scope(sub, entry_name, path + (name,), out)


# -- backward-collective census ----------------------------------------------


def census_findings(grad_entries) -> list[Finding]:
    """The VJP-trace collective census (empty = clean).

    Every entry in the matrix communicates by construction, so its primal
    count must be positive (otherwise the census itself went blind) and
    its VJP trace — forward replay plus backward pass — must issue
    STRICTLY MORE collectives than the primal: the surplus is the backward
    transport of cross-boundary cotangents.
    """
    out = []
    for entry in grad_entries:
        grad_n, primal_n = entry.collective_counts()
        if primal_n == 0:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="census-broken",
                    severity="ERROR",
                    message=(
                        f"{entry.name}: primal trace shows ZERO collectives "
                        f"— the grad census has nothing to compare against "
                        f"(config no longer communicates?)."
                    ),
                    symbol=entry.name,
                    anchor="primal0",
                )
            )
            continue
        if grad_n <= primal_n:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="cotangent-sink",
                    severity="CRITICAL",
                    message=(
                        f"{entry.name}: the VJP trace issues {grad_n} "
                        f"collective(s) vs {primal_n} in the primal — no "
                        f"backward collectives means cross-boundary "
                        f"cotangents are NOT transported and jax.grad "
                        f"silently zeroes every gradient that crosses a "
                        f"rank boundary."
                    ),
                    symbol=entry.name,
                    anchor=f"{grad_n}<={primal_n}",
                    fix_hint=(
                        "a primitive on the tangent path lost its "
                        "derivative; register a custom VJP that "
                        "differentiates a value-identical transport "
                        "(ops/halo.py::_packed_transport is the pattern)"
                    ),
                )
            )
    return out


def run(ctx: Context) -> list[Finding]:
    out = []
    for entry in list(ctx.exchange_entries()) + list(ctx.cadence_entries()):
        out.extend(dropper_findings(entry.jaxpr, entry.name))
    out.extend(census_findings(ctx.grad_entries()))
    return out
