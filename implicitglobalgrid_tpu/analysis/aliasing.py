"""Pallas aliasing / buffer-donation lint.

The fused kernels' carry steps are in-place: ``input_output_aliases`` tells
XLA (and the generic Pallas interpreter, which honors it — the env-notes
contract the pipelined ring/mid combine relies on) that an input buffer IS
an output buffer.  A wrong declaration is silent corruption, not an error:
XLA happily reuses the buffer while the kernel still reads it.  Donation
(``donate_argnums``) has the same failure shape — a donated buffer that no
output can reuse is a silent perf lie, and a reused one that the caller
still holds is corruption.

Checks, over both IRs:

* **AST** — literal ``input_output_aliases`` dicts must map non-negative
  int constants injectively (a duplicated output index would alias two
  inputs onto one buffer); literal ``donate_argnums`` must be non-negative
  int constants.
* **traced** — every ``pallas_call`` equation in the cadence matrix carries
  its RESOLVED alias pairs; each pair must be in range and the aliased
  operand/result avals must match exactly (shape and dtype — the in-place
  contract).  Every ``pjit`` equation's donated operands must match some
  output aval, else the donation can never be honored (XLA drops it with a
  warning at best).
"""

from __future__ import annotations

import ast

from .core import Context, Finding
from .ir import iter_eqns

ANALYZER = "pallas-aliasing"


# -- shared validation core (unit-tested directly) ----------------------------


def validate_alias_pairs(pairs, in_avals, out_avals) -> list[str]:
    """Human-readable problems of resolved (input, output) alias pairs
    against operand/result avals (``(shape, dtype)`` tuples or jax avals).
    Empty list = valid."""

    def sig(a):
        return (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else a

    probs = []
    seen_in: set[int] = set()
    seen_out: set[int] = set()
    for i, o in pairs:
        if not (0 <= i < len(in_avals)):
            probs.append(f"alias input index {i} out of range "
                         f"(have {len(in_avals)} operands)")
            continue
        if not (0 <= o < len(out_avals)):
            probs.append(f"alias output index {o} out of range "
                         f"(have {len(out_avals)} results)")
            continue
        if i in seen_in:
            probs.append(f"input {i} aliased to two outputs")
        if o in seen_out:
            probs.append(f"output {o} aliased from two inputs")
        seen_in.add(i)
        seen_out.add(o)
        si, so = sig(in_avals[i]), sig(out_avals[o])
        if si != so:
            probs.append(
                f"alias pair ({i}, {o}) mismatches: operand {si} vs "
                f"result {so} — an in-place buffer must keep shape+dtype"
            )
    return probs


# -- AST pass -----------------------------------------------------------------


def _literal_alias_findings(rel: str, call: ast.Call, qual: str) -> list:
    out = []
    for kw in call.keywords:
        if kw.arg == "input_output_aliases" and isinstance(kw.value, ast.Dict):
            keys, vals = [], []
            ok = True
            for k, v in zip(kw.value.keys, kw.value.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, int)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, int)
                ):
                    ok = False  # computed entries: the traced pass covers it
                    break
                keys.append(k.value)
                vals.append(v.value)
            if not ok:
                continue
            probs = []
            if any(x < 0 for x in keys + vals):
                probs.append("negative index")
            if len(set(keys)) != len(keys):
                probs.append(
                    "duplicate input index (later dict entry silently wins)"
                )
            if len(set(vals)) != len(vals):
                probs.append("duplicate output index (two inputs on one "
                             "output buffer)")
            for p in probs:
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="bad-alias-literal",
                        severity="ERROR",
                        message=(
                            f"pallas_call input_output_aliases "
                            f"{{{', '.join(f'{k}: {v}' for k, v in zip(keys, vals))}}}: {p}."
                        ),
                        path=rel,
                        line=kw.value.lineno,
                        symbol=qual,
                        anchor=f"aliases:{sorted(zip(keys, vals))}",
                    )
                )
        if kw.arg == "donate_argnums":
            elts = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else []
            )
            for e in elts:
                # -1 parses as UnaryOp(USub, Constant(1)), not Constant(-1)
                if (
                    isinstance(e, ast.UnaryOp)
                    and isinstance(e.op, ast.USub)
                    and isinstance(e.operand, ast.Constant)
                    and isinstance(e.operand.value, int)
                ):
                    e = ast.copy_location(
                        ast.Constant(value=-e.operand.value), e
                    )
                if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                        and e.value < 0:
                    out.append(
                        Finding(
                            analyzer=ANALYZER,
                            code="bad-donate-literal",
                            severity="ERROR",
                            message=(
                                f"donate_argnums contains {e.value}: "
                                f"donation indices are positional argument "
                                f"numbers and must be >= 0."
                            ),
                            path=rel,
                            line=e.lineno,
                            symbol=qual,
                            anchor=f"donate:{e.value}",
                        )
                    )
    return out


def ast_findings(ctx: Context) -> list:
    out = []
    for rel, (_src, tree) in ctx.module_asts().items():
        stack: list[str] = []

        class V(ast.NodeVisitor):
            def _f(self, node):
                stack.append(node.name)
                self.generic_visit(node)
                stack.pop()

            visit_FunctionDef = _f
            visit_AsyncFunctionDef = _f

            def visit_Call(self, node: ast.Call):
                name = node.func.attr if isinstance(
                    node.func, ast.Attribute
                ) else getattr(node.func, "id", "")
                if name in ("pallas_call", "jit", "stencil"):
                    out.extend(
                        _literal_alias_findings(
                            rel, node, ".".join(stack) or "<module>"
                        )
                    )
                self.generic_visit(node)

        V().visit(tree)
    return out


# -- traced pass --------------------------------------------------------------


def traced_findings(ctx: Context) -> list:
    out = []
    for entry in ctx.cadence_entries():
        for eqn, _path in iter_eqns(entry.jaxpr):
            if eqn.primitive.name == "pallas_call":
                pairs = [
                    tuple(p) for p in eqn.params.get(
                        "input_output_aliases", ()
                    )
                ]
                probs = validate_alias_pairs(
                    pairs,
                    [v.aval for v in eqn.invars],
                    [v.aval for v in eqn.outvars],
                )
                for p in probs:
                    out.append(
                        Finding(
                            analyzer=ANALYZER,
                            code="bad-alias-traced",
                            severity="CRITICAL",
                            message=(
                                f"entry `{entry.name}`: pallas_call "
                                f"aliases {pairs}: {p}."
                            ),
                            symbol=entry.name,
                            anchor=f"{pairs}:{p[:32]}",
                            fix_hint=(
                                "fix the input_output_aliases mapping in "
                                "the kernel builder — the aliased operand "
                                "must be the same logical buffer as the "
                                "result."
                            ),
                        )
                    )
            elif eqn.primitive.name == "pjit":
                donated = eqn.params.get("donated_invars", ())
                if not any(donated):
                    continue
                out_sigs = {
                    (tuple(v.aval.shape), str(v.aval.dtype))
                    for v in eqn.outvars
                    if hasattr(v.aval, "shape")
                }
                for iv, don in zip(eqn.invars, donated):
                    if not don or not hasattr(iv.aval, "shape"):
                        continue
                    sig = (tuple(iv.aval.shape), str(iv.aval.dtype))
                    if sig not in out_sigs:
                        out.append(
                            Finding(
                                analyzer=ANALYZER,
                                code="unusable-donation",
                                severity="WARNING",
                                message=(
                                    f"entry `{entry.name}`: a donated "
                                    f"operand {sig} matches no result of "
                                    f"its jit — the buffer can never be "
                                    f"reused; the donation is a no-op and "
                                    f"the caller still loses the array."
                                ),
                                symbol=entry.name,
                                anchor=f"donate:{sig}",
                            )
                        )
    return out


def run(ctx: Context) -> list:
    return ast_findings(ctx) + traced_findings(ctx)
