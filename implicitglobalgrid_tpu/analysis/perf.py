"""Bench-regression analyzer (``bench-regression``) — the perf trajectory
as a gate.

The repo's perf evidence is the ``BENCH_r*.json`` trajectory (one record
per round, written by the bench driver around ``bench.py``'s single JSON
line).  Until this pass nothing READ it: a PR could halve a headline
number and tier-1 stayed green (ROADMAP item 5).  This module turns the
trajectory into a machine-checked invariant:

* `load_bench_records` parses every committed ``BENCH_r*.json`` (the
  driver wrapper ``{"n", "parsed", "tail", ...}`` or a raw ``bench.py``
  record), skipping rounds whose record is truncated beyond recovery
  (r05's ``tail`` is mid-JSON) — those are reported, never silently used;
* `gate_metrics` flattens a record to its gated metrics: the headline
  ``value`` plus every ``teff``/``teff_grad`` in ``extras`` (throughput,
  higher-is-better — wall-time columns drift with chip tenancy and are
  deliberately NOT gated);
* `compare_metrics` fails a candidate metric that DROPS more than ``tol``
  (default 15% — the real trajectory's worst cross-round drop is 7.9%,
  r02→r03 ``diffusion_512``, chip-tenancy drift) below the reference,
  unless a waiver in `analysis/perf_waivers.json` covers it.  Waivers
  mirror the justified-suppression baseline: every entry REQUIRES a
  justification, and stale waivers are reported.

Consumers: ``scripts/check_perf.py`` (CLI gate — nonzero on regression),
the ``bench-regression`` registry pass (tier-1: the committed trajectory
itself must be self-consistent), and ``bench.py`` (attaches an
``extras.perf_gate`` verdict to every fresh record).
"""

from __future__ import annotations

import glob
import json
import os
import re

from .core import Context, Finding

ANALYZER = "bench-regression"

#: Allowed fractional DROP per metric vs the reference record.  One-sided:
#: improvements never fail (the next round's reference simply rises).
DEFAULT_TOL = 0.15

#: Machine-readable waiver file, next to the analyzers like baseline.json.
PERF_WAIVERS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "perf_waivers.json"
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


# -- record discovery ---------------------------------------------------------


def parse_bench_file(path: str) -> dict | None:
    """The bench record inside one ``BENCH_*.json`` (None = unrecoverable).

    Accepts the driver wrapper (``parsed`` preferred, then a full-JSON
    ``tail``) and the raw ``bench.py`` record itself.
    """
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except ValueError:  # truncated mid-write: a skip-and-report, not a crash
        return None
    if not isinstance(data, dict):
        return None
    if "metric" in data and "extras" in data:
        return data
    rec = data.get("parsed")
    if isinstance(rec, dict) and "extras" in rec:
        return rec
    tail = data.get("tail", "")
    start = tail.find("{")  # the record is the line's first JSON object
    if start >= 0:
        try:
            # raw_decode: the record may be followed by trailing log text
            # (a normal capture shape) — only a TRUNCATED object fails
            rec, _ = json.JSONDecoder().raw_decode(tail[start:])
            if isinstance(rec, dict) and "extras" in rec:
                return rec
        except ValueError:
            pass
    return None


def load_bench_records(repo_root: str) -> tuple[list, list]:
    """``([(round, record)...] ascending, [unparseable paths])``."""
    records, skipped = [], []
    for path in sorted(glob.glob(os.path.join(repo_root, "BENCH_*.json"))):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        rec = parse_bench_file(path)
        if rec is None:
            skipped.append(os.path.basename(path))
        else:
            records.append((int(m.group(1)), rec))
    records.sort()
    return records, skipped


#: extras keys gated as higher-is-better throughput metrics.  ``teff`` /
#: ``teff_grad`` are GB/s; ``members_per_s`` is the batched-serving
#: members/s/chip record (``bench.py batch``, ISSUE 8) — same one-sided
#: drop semantics, so a batching regression fails like a bandwidth one.
#: ``rounds_per_s`` plus the INVERSE submit→result latencies
#: ``result_p50_per_s``/``result_p99_per_s`` are the front-door serving
#: record (``extras.frontdoor_serving``, ISSUE 12): inverting the latency
#: makes "p99 got slower" a one-sided DROP, so the existing gate catches
#: it without new comparison semantics (the raw seconds ride along as
#: `REPORTED_KEYS`).  ``tuned_speedup`` is the autotuner's closed loop
#: (``extras.tuned_vs_default``, ISSUE 13): t_default / t_tuned per model,
#: so a tuner that starts picking slower-than-default configs (or a
#: regression that erases a tuned win) drops the ratio and fails the gate
#: the way a bandwidth drop does.
GATED_KEYS = ("teff", "teff_grad", "members_per_s", "rounds_per_s",
              "result_p50_per_s", "result_p99_per_s", "tuned_speedup")


def gate_metrics(record: dict) -> dict:
    """Flatten one bench record to ``{metric path: value}`` for the gated
    throughput metrics (headline ``value`` + every nested `GATED_KEYS`
    entry under ``extras``; error-bearing extras contribute nothing —
    wall-time columns drift with chip tenancy and are deliberately not
    gated)."""
    out = {}
    if isinstance(record.get("value"), (int, float)):
        out["headline"] = float(record["value"])

    def walk(prefix: str, node) -> None:
        if not isinstance(node, dict):
            return
        for key, val in node.items():
            if key in GATED_KEYS and isinstance(val, (int, float)):
                out[f"{prefix}{key}"] = float(val)
            elif isinstance(val, dict):
                walk(f"{prefix}{key}.", val)

    walk("", record.get("extras", {}))
    return out


#: extras keys REPORTED alongside the gate verdict but not (yet) gated:
#: ``achieved_fraction`` is the cost-model reconciliation number
#: (`analysis.reconcile` — ``extras.efficiency``), carried per round so a
#: future gate has a trajectory to regress against before it starts
#: failing PRs on it; the ``submit_to_result_*`` seconds are the raw
#: front-door latencies whose inverses are gated (human-readable twins).
#: ``overlap_fraction`` is the MEASURED comm/compute overlap of the
#: device-timeline capture (``extras.profile_attribution``, ISSUE 15 —
#: `utils.profiling.overlap_measure`): the number ROADMAP item 1's
#: Pallas-native exchange must push up, on the same reported-first on-ramp
#: achieved_fraction took (promote to GATED once a chip-env round records
#: it).  The ``*_share`` keys are the request critical-path decomposition
#: (``extras.request_trace``, ISSUE 19 — `utils.tracing.critical_path`):
#: the traced request's latency attributed to queue-wait / admission /
#: rounds / exchange / checkpoint / re-route and the uncovered remainder —
#: reported per round so a latency regression names its segment before
#: anyone opens a trace viewer.
REPORTED_KEYS = ("achieved_fraction", "submit_to_result_p50_s",
                 "submit_to_result_p99_s", "overlap_fraction",
                 "queue_wait_share", "admission_share", "rounds_share",
                 "exchange_share", "checkpoint_share", "reroute_share",
                 "other_share")


def reported_metrics(record: dict) -> dict:
    """Flatten one bench record to ``{metric path: value}`` for the
    report-only keys (`REPORTED_KEYS`) — same walk as `gate_metrics`,
    no comparison semantics."""
    out = {}

    def walk(prefix: str, node) -> None:
        if not isinstance(node, dict):
            return
        for key, val in node.items():
            if key in REPORTED_KEYS and isinstance(val, (int, float)):
                out[f"{prefix}{key}"] = float(val)
            elif isinstance(val, dict):
                walk(f"{prefix}{key}.", val)

    walk("", record.get("extras", {}))
    return out


# -- waivers ------------------------------------------------------------------


def load_waivers(path: str = PERF_WAIVERS) -> list[dict]:
    """Waiver entries (``[]`` when the file is absent).  Schema::

        {"waivers": [{"metric": "...", "justification": "...",
                      "max_drop": 0.5, "rounds": [5]}]}

    ``metric`` names a `gate_metrics` path; ``max_drop`` bounds the waived
    drop (a waiver is a measured concession, not a blank check — default
    1.0 = any drop); ``rounds`` restricts the waiver to specific candidate
    rounds (omit = any).  A waiver without a justification is an error —
    same contract as the suppression baseline.
    """
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    waivers = data.get("waivers", [])
    for w in waivers:
        if not (w.get("metric") or "").strip():
            raise ValueError(f"perf waiver without a metric: {w!r}")
        if not (w.get("justification") or "").strip():
            raise ValueError(
                f"perf waiver for {w['metric']!r} has no justification — "
                f"every waived regression must say WHY it is acceptable."
            )
    return waivers


def _waiver_for(metric: str, drop: float, round_n, waivers) -> dict | None:
    for w in waivers:
        if w["metric"] != metric:
            continue
        rounds = w.get("rounds")
        if rounds is not None and round_n not in rounds:
            # A round-scoped waiver covers ONLY those committed rounds; a
            # fresh --candidate record has no round (None) and must not
            # inherit a concession granted to a historical dip.
            continue
        if drop <= float(w.get("max_drop", 1.0)):
            return w
    return None


# -- comparison ---------------------------------------------------------------


def compare_metrics(candidate: dict, reference: dict, *,
                    tol: float = DEFAULT_TOL, waivers=None,
                    candidate_round=None) -> dict:
    """Compare flattened metric maps.  Returns::

        {"regressions": [{metric, reference, candidate, drop}...],
         "waived":      [{..., "justification"}...],
         "missing":     [metrics in reference absent from candidate],
         "checked":     n}

    Only metrics present in BOTH records are compared (configs come and go
    across rounds); reference metrics the candidate lost entirely are
    listed in ``missing`` — the caller decides whether absence fails.
    """
    waivers = load_waivers() if waivers is None else waivers
    regressions, waived, missing = [], [], []
    checked = 0
    for metric, ref in sorted(reference.items()):
        if ref <= 0:
            continue
        if metric not in candidate:
            missing.append(metric)
            continue
        checked += 1
        cand = candidate[metric]
        drop = (ref - cand) / ref
        if drop <= tol:
            continue
        rec = {
            "metric": metric,
            "reference": ref,
            "candidate": cand,
            "drop": round(drop, 4),
        }
        w = _waiver_for(metric, drop, candidate_round, waivers)
        if w is not None:
            rec["justification"] = w["justification"]
            # which ENTRY matched (not just which metric): staleness
            # detection must see that a second, round-scoped waiver for
            # the same metric never fired
            rec["waiver_index"] = waivers.index(w)
            waived.append(rec)
        else:
            regressions.append(rec)
    return {
        "regressions": regressions,
        "waived": waived,
        "missing": missing,
        "checked": checked,
    }


def gate_summary(candidate_record: dict, repo_root: str, *,
                 tol: float = DEFAULT_TOL) -> dict:
    """The ``bench.py`` hook: compare a FRESH record against the newest
    committed round.  Returns a JSON-ready verdict (never raises on an
    empty trajectory — a first bench run has nothing to regress from)."""
    records, skipped = load_bench_records(repo_root)
    if not records:
        return {"ok": True, "note": "no committed BENCH records to compare",
                "skipped_records": skipped}
    ref_round, ref_rec = records[-1]
    cmp = compare_metrics(
        gate_metrics(candidate_record), gate_metrics(ref_rec), tol=tol
    )
    return {
        "ok": not cmp["regressions"],
        "reference_round": ref_round,
        "tol": tol,
        **cmp,
        "reported": reported_metrics(candidate_record),
        "skipped_records": skipped,
    }


def run(ctx: Context) -> list[Finding]:
    """Registry pass: the COMMITTED trajectory must be self-consistent —
    the newest parseable round within tolerance of its predecessor (modulo
    waivers).  This is what keeps a PR from committing a regressed bench
    artifact; the live gate for fresh runs is ``scripts/check_perf.py``."""
    records, skipped = load_bench_records(ctx.repo_root)
    out = []
    for name in skipped:
        # An unparseable committed round is a gate blind spot: a regressed
        # record could merge wearing truncation as camouflage.  Known
        # historical truncations (r01/r05, damaged before this gate
        # existed) are baselined with justifications; a NEW one must be
        # looked at, not waved through.
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="unparseable-record",
                severity="ERROR",
                message=(
                    f"{name} holds no parseable bench record — the gate "
                    f"cannot audit it, so the round merges sight-unseen.  "
                    f"Re-emit the record, or baseline the truncation with "
                    f"a justification."
                ),
                symbol=name,
                anchor="unparseable",
            )
        )
    if len(records) < 2:
        return out  # one (or zero) records: nothing to regress from
    (prev_round, prev), (cand_round, cand) = records[-2], records[-1]
    cmp = compare_metrics(
        gate_metrics(cand), gate_metrics(prev),
        candidate_round=cand_round,
    )
    for reg in cmp["regressions"]:
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="perf-regression",
                severity="ERROR",
                message=(
                    f"BENCH_r{cand_round:02d}: {reg['metric']} dropped "
                    f"{reg['drop']:.1%} vs r{prev_round:02d} "
                    f"({reg['reference']:.2f} -> {reg['candidate']:.2f} "
                    f"GB/s, tolerance {DEFAULT_TOL:.0%}) — waive it in "
                    f"analysis/perf_waivers.json with a justification, or "
                    f"fix the regression."
                ),
                symbol=f"r{cand_round:02d}",
                anchor=reg["metric"],
            )
        )
    for metric in cmp["missing"]:
        # A gated metric that vanished from the newest round is the other
        # escape hatch: a regression can hide by deleting its benchmark.
        # Legit config retirements get a baseline entry saying WHY.
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="metric-vanished",
                severity="ERROR",
                message=(
                    f"BENCH_r{cand_round:02d}: gated metric {metric} "
                    f"(present in r{prev_round:02d}) is absent — a "
                    f"regression can hide by dropping its benchmark.  "
                    f"Re-measure the config, or baseline the retirement "
                    f"with a justification."
                ),
                symbol=f"r{cand_round:02d}",
                anchor=metric,
            )
        )
    return out
