"""Tune-cache validity analyzer (``tune-cache-valid``).

The committed seed layer of the autotuner (`tuning.cache.SEED_DIR`) is
configuration-as-data: a stale or hand-mangled entry would silently steer
every chip run that trusts it.  This pass makes the layer a tier-1
invariant with the same contract as the other gates (a finding fails the
suite unless baselined with a justification):

* every committed entry PARSES against the schema
  (`tuning.cache.validate_entry`) — a corrupt file or an unknown config
  field is an ERROR, not a runtime surprise;
* a ``schema_version`` other than the current `tuning.cache.SCHEMA_VERSION`
  is a ``stale-schema`` finding — the entry must be re-seeded, because
  readers (correctly) refuse it and the layer silently stops serving;
* the keyed config must be CURRENTLY ADMISSIBLE
  (`tuning.cache.admissibility_error`): the tile clears the kernel
  envelope's ``IGG_VMEM_MB`` ladder for the keyed size/dtype, and a porous
  width is accepted by the kernel builder's PT schedule — an entry the
  models would refuse at build time is dead weight wearing authority;
* the filename must match the key digest (`tuning.cache.entry_filename`)
  — a hand-edited key that drifts from its digest would shadow (or never
  serve) its lookups.

Pure file + math checks (no jax runtime): registered at ``ast`` cost.
"""

from __future__ import annotations

import json
import os

from .core import Context, Finding

ANALYZER = "tune-cache-valid"


def cache_findings(directory: str) -> list[Finding]:
    """Findings over one committed entry directory (empty dir = clean —
    the seed layer starts existing the first time ``igg_tune.py seed``
    commits a round's winners)."""
    from ..tuning import cache as _cache

    out = []
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except ValueError as e:
            out.append(Finding(
                analyzer=ANALYZER, code="entry-corrupt", severity="ERROR",
                message=(f"{name}: not parseable JSON ({e}) — lookups "
                         f"refuse it, the entry serves nothing."),
                symbol=name, anchor="corrupt",
                fix_hint="re-seed the entry (igg_tune.py seed) or delete it.",
            ))
            continue
        ver = doc.get("schema_version") if isinstance(doc, dict) else None
        if ver != _cache.SCHEMA_VERSION:
            out.append(Finding(
                analyzer=ANALYZER, code="stale-schema", severity="ERROR",
                message=(f"{name}: schema_version {ver!r} is not the "
                         f"current {_cache.SCHEMA_VERSION} — readers refuse "
                         f"the entry, so the committed layer silently "
                         f"stopped serving this key."),
                symbol=name, anchor="schema",
                fix_hint="re-seed at the current schema (igg_tune.py seed).",
            ))
            continue
        try:
            key, config = _cache.validate_entry(doc)
        except ValueError as e:
            out.append(Finding(
                analyzer=ANALYZER, code="entry-invalid", severity="ERROR",
                message=f"{name}: {e}",
                symbol=name, anchor="schema",
                fix_hint="re-seed the entry (igg_tune.py seed).",
            ))
            continue
        want = _cache.entry_filename(key)
        if name != want:
            out.append(Finding(
                analyzer=ANALYZER, code="key-drift", severity="ERROR",
                message=(f"{name}: the embedded key digests to {want} — a "
                         f"hand-edited key shadows (or never serves) its "
                         f"lookups."),
                symbol=name, anchor="digest",
            ))
            continue
        err = _cache.admissibility_error(key, config)
        if err is not None:
            out.append(Finding(
                analyzer=ANALYZER, code="inadmissible-config",
                severity="ERROR",
                message=(f"{name}: config {config} is not admissible for "
                         f"key {key['model']}/{key['size']}/{key['dtype']}: "
                         f"{err} — the model builders would refuse it at "
                         f"apply time."),
                symbol=name, anchor="admissible",
                fix_hint=("re-measure the point (igg_tune.py sweep) or "
                          "delete the entry."),
            ))
    return out


def run(ctx: Context) -> list[Finding]:
    directory = os.path.join(ctx.package_root, "tuning", "entries")
    return cache_findings(directory)
