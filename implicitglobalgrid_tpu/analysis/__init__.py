"""``igg.analysis`` — pluggable static-analysis suite (docs/static-analysis.md).

Four shipped analyzers run over three IRs the codebase already produces
(package AST, traced jaxprs of the public entry points under the production
config matrix, optimized HLO via `utils.hlo_analysis`):

* ``collective-consistency`` — cross-rank collective-ordering divergence
  (the distributed-deadlock class found by hand in PR 1), as AST
  rank-guard detection + traced perm/``cond`` checks + the
  per-simulated-rank host-plan census (`ops.gather.collective_plan`);
* ``knob-binding`` — ``IGG_*``/``os.environ`` reads reachable from
  jit/shard_map/Pallas-traced code (values silently baked into stale jit
  caches);
* ``pallas-aliasing`` — ``input_output_aliases``/donation declarations vs
  the actual in-place contract;
* ``overlap-independence`` — the pipelined schedules' structural
  kernel/exchange independence, enforced across all models;

plus the two pre-existing lints as registry passes: ``collective-budget``
and ``knob-decl`` (their scripts are now thin wrappers).

Entry points: `run` (in-process), ``scripts/igg_lint.py`` (CLI),
``tests/test_lint_suite.py`` (tier-1).  This module imports no jax — the
traced IRs build lazily inside a run.
"""

from .core import (
    DEFAULT_BASELINE,
    FAILING,
    SEVERITIES,
    AnalyzerSpec,
    Baseline,
    Context,
    Finding,
    Report,
    available_analyzers,
    changed_files,
    run,
    select_for_paths,
)

__all__ = [
    "AnalyzerSpec",
    "Baseline",
    "Context",
    "DEFAULT_BASELINE",
    "FAILING",
    "Finding",
    "Report",
    "SEVERITIES",
    "available_analyzers",
    "changed_files",
    "run",
    "select_for_paths",
]
