"""Overlap-independence analyzer (ISSUE 2's structural guarantee, suite-wide).

The pipelined group schedule's whole value is a dataflow shape: each
group's boundary exchange (`collective-permute`s) and its interior kernel
launch must be mutually independent in the traced program, so the compiler
is licensed to run them concurrently.  `tests/test_pipelined_schedule.py`
proved that for ONE diffusion config; this analyzer runs the same
independence-pair census (`ir.independence_pairs`) over every model's
cadence, pipelined on and off, so the guarantee is enforced everywhere a
cadence exists — including models added later.

Invariants:

* serialized cadence: ZERO free (kernel, ppermute) pairs.  This is the
  census' liveness control (like the per-field control in the collective
  budget): the serialized schedule orders every launch against every
  exchange by construction, so free pairs there mean the counter stopped
  seeing dependencies — a broken analyzer, not a fast schedule.
* admissible pipelined cadence: at least one free pair per in-flight
  exchange group (we require ``pairs >= n_kernels / 2`` — ring+interior
  per group, each group's interior free against its own permutes).
* a pipelined config that traced as inadmissible (serialized fallback,
  warn-once) is skipped — "no overlap possible" is not "overlap lost".
"""

from __future__ import annotations

from .core import Context, Finding
from .ir import independence_pairs

ANALYZER = "overlap-independence"


def run(ctx: Context) -> list[Finding]:
    out = []
    for entry in ctx.cadence_entries():
        pairs, nk, np_ = independence_pairs(entry.jaxpr)
        if nk == 0 or np_ == 0:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="census-empty",
                    severity="ERROR",
                    message=(
                        f"entry `{entry.name}`: found {nk} kernel "
                        f"launch(es) and {np_} collective(s) — the cadence "
                        f"census sees nothing to analyze; the kernel/"
                        f"collective detection drifted from the models."
                    ),
                    symbol=entry.name,
                    anchor="empty",
                )
            )
            continue
        pipelined = bool(entry.config.get("pipelined"))
        if not pipelined:
            if pairs != 0:
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="control-broken",
                        severity="ERROR",
                        message=(
                            f"entry `{entry.name}`: the SERIALIZED cadence "
                            f"shows {pairs} free (kernel, collective) "
                            f"pair(s) — it must order every launch against "
                            f"every exchange, so the independence counter "
                            f"is no longer seeing dependencies."
                        ),
                        symbol=entry.name,
                        anchor="control",
                    )
                )
            continue
        if not entry.admissible:
            continue  # fell back to serialized (warn-once path): no claim
        want = max(1, nk // 2)
        if pairs < want:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="overlap-lost",
                    severity="ERROR",
                    message=(
                        f"entry `{entry.name}`: only {pairs} free "
                        f"(kernel, collective) pair(s) for {nk} kernel "
                        f"launch(es) / {np_} collective(s) — expected "
                        f">= {want}.  The pipelined schedule no longer "
                        f"creates the kernel/exchange independence ISSUE 2 "
                        f"exists for; the compiler must serialize them."
                    ),
                    symbol=entry.name,
                    anchor="pairs",
                    fix_hint=(
                        "the interior pass grew a dependency on the "
                        "in-flight exchange (or the early-dispatch "
                        "begin/finish split regressed) — diff the cadence "
                        "against tests/test_pipelined_schedule.py's "
                        "independence proof."
                    ),
                )
            )
    return out
