"""Traced-IR producers for the analysis suite.

Three IRs feed the analyzers: the package AST (built by `core.Context`),
the traced jaxprs produced here, and optimized-HLO text (parsed by
`utils.hlo_analysis` — the HLO-level analyzers reuse that module wholesale).

This module traces the package's public entry points under the production
config matrix on the 8-device virtual CPU mesh:

* **exchange entries** — `exchange_dims_multi` over each model's production
  field set (plain, staggered faces, padded layout, begin/finish slab
  pipeline), coalesce auto/off, on one grid that has BOTH periodic and
  PROC_NULL transports;
* **cadence entries** — each model's fused `make_multi_step` program,
  pipelined on/off (the kernels trace through the generic Pallas
  interpreter, `utils.compat.pallas_force_interpret`).

Everything here is TRACE-only (`jax.make_jaxpr`): no executable is built, no
device computation runs — which is what makes a full-matrix census cheap
enough for tier-1.  Each producer manages its own grid (init/finalize), so
callers need no grid state; conftest's finalize-after-test fixture composes.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, field

import numpy as np

#: jaxpr primitive names that move data across ranks.  ``ppermute`` is the
#: halo transport; the reductions appear in gather/guard paths.
COLLECTIVE_PRIMS = (
    "ppermute",
    "psum",
    "pmax",
    "pmin",
    "pmean",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "pbroadcast",
)

#: Control-flow primitives whose sub-jaxprs we descend into, tracking the
#: nesting path.  A collective under ``cond`` is a deadlock hazard (a
#: rank-divergent predicate runs the collective on some ranks only); under
#: ``while``/``scan`` it is fine when the trip count is a trace-time
#: constant, which jax guarantees for ``fori_loop``/``scan``.
_SUBJAXPR_PARAM_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                       "body_jaxpr", "branches")


@dataclass(frozen=True)
class CollectiveOp:
    """One collective equation in a traced program."""

    kind: str            # primitive name
    axes: tuple          # mesh axis name(s) it operates over
    perm: tuple | None   # ppermute source->target pairs (positions on axis)
    payload_bytes: int   # sum of operand aval bytes
    shapes: tuple        # operand aval strings
    path: tuple          # enclosing higher-order primitive names

    @property
    def signature(self) -> tuple:
        """The cross-rank identity of the op: what every rank must agree
        on for the collective to match up (kind, axes, payload)."""
        return (self.kind, self.axes, self.shapes)


@dataclass(frozen=True)
class TracedEntry:
    """One traced entry point of the config matrix."""

    name: str            # e.g. "cadence/diffusion[pipelined=True]"
    kind: str            # "exchange" | "cadence"
    config: dict
    jaxpr: object        # the inner (shard_map-unwrapped) jaxpr
    mesh_shape: dict     # axis name -> size
    admissible: bool = True  # pipelined configs: did the schedule engage?

    def collectives(self) -> list:
        return collect_collectives(self.jaxpr)


@dataclass(frozen=True)
class RankCensus:
    """Per-rank ordered collective sequences of one entry point.

    ``sequences`` maps a rank key (coords tuple, process index, or any
    hashable label) to the ordered tuple of signature records that rank
    issues.  The divergence detector's invariant: ALL values are equal —
    one rank running a different sequence is the `_gather_chunked` hang
    class (PR 1) and MUST/GSPMD's classic deadlock condition.
    """

    name: str
    sequences: dict = field(default_factory=dict)


# -- jaxpr walking ------------------------------------------------------------


def _sub_jaxprs(eqn):
    """(param_key, jaxpr) sub-programs of one equation, ClosedJaxpr-unwrapped."""
    for k, v in eqn.params.items():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield k, x.jaxpr
            elif hasattr(x, "eqns"):
                yield k, x


def iter_eqns(jaxpr, path=()):
    """Yield ``(eqn, path)`` over a jaxpr and its sub-jaxprs in program
    order; ``path`` is the tuple of enclosing primitive names.  Does not
    descend into ``pallas_call`` bodies — a kernel's internal DMA control
    flow is not rank-level communication."""
    for eqn in jaxpr.eqns:
        yield eqn, path
        if eqn.primitive.name == "pallas_call":
            continue
        for _, sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, path + (eqn.primitive.name,))


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:  # noqa: BLE001 — abstract tokens etc.
        return 0


def collect_collectives(jaxpr) -> list:
    """Ordered `CollectiveOp` records of a traced program."""
    out = []
    for eqn, path in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        axes = eqn.params.get("axis_name", eqn.params.get("axes", ()))
        if not isinstance(axes, tuple):
            axes = (axes,)
        perm = eqn.params.get("perm")
        out.append(
            CollectiveOp(
                kind=name,
                axes=tuple(str(a) for a in axes),
                perm=tuple(map(tuple, perm)) if perm is not None else None,
                payload_bytes=sum(_aval_bytes(v.aval) for v in eqn.invars),
                shapes=tuple(str(v.aval) for v in eqn.invars),
                path=path,
            )
        )
    return out


def unwrap_inner(jaxpr):
    """The analysis view of a traced SPMD program: the shard_map body,
    unwrapped past the kernel-vs-fallback ``custom_vjp`` envelope
    (`fused_with_xla_grad` nests the whole cadence under one eqn)."""
    sms = [e for e in jaxpr.eqns if e.primitive.name == "shard_map"]
    inner = sms[0].params["jaxpr"] if sms else jaxpr
    if hasattr(inner, "eqns") is False and hasattr(inner, "jaxpr"):
        inner = inner.jaxpr
    while (
        len(inner.eqns) == 1
        and "custom_vjp" in inner.eqns[0].primitive.name
    ):
        inner = inner.eqns[0].params["fun_jaxpr"].jaxpr
    return inner


def rank_roles(entry: TracedEntry, coords: tuple) -> list[str]:
    """Per-op send/recv role of one rank (``"sr"``/``"s"``/``"r"``/``""``),
    derived from each ppermute's perm — the debugging view of a census
    entry (which rank moves payload in which hop)."""
    axes = list(entry.mesh_shape)
    pos = dict(zip(axes, coords))
    roles = []
    for op in entry.collectives():
        role = ""
        if op.kind == "ppermute" and op.perm is not None and op.axes:
            p = pos.get(op.axes[0], 0)
            role = (
                ("s" if any(s == p for s, _ in op.perm) else "")
                + ("r" if any(d == p for _, d in op.perm) else "")
            )
        roles.append(role)
    return roles


# -- traced entry producers ---------------------------------------------------


def model_field_structs(model: str, n: int):
    """The model's exchanged field set as traced shapes (staggered ``n+1``
    faces like the real states; f32 like the production configs).  Shared
    with the collective-budget analyzer — one field census for both."""
    import jax
    import jax.numpy as jnp

    def s(shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    cell = (n, n, n)
    faces = [
        tuple(n + (1 if d == ax else 0) for d in range(3)) for ax in range(3)
    ]
    if model == "diffusion":
        return (s(cell),)
    if model == "acoustic":
        return (s(cell), *map(s, faces))
    if model == "porous":
        return (s(cell), *map(s, faces), s(cell))
    raise ValueError(model)


def _trace_mapped(body, fields, gg, out_fields=None):
    """shard_map + make_jaxpr a local-block body over global-shaped args.

    ``out_fields`` overrides the output structure when it differs from the
    inputs (a traced VJP takes seeds + primals but returns one cotangent
    per primal — `trace_grad_entries`); default: outputs mirror inputs.

    Fields of rank > NDIMS carry a leading BATCH/ensemble axis
    (`models._batched` layout): the batch axis stays replicated
    (``P(None, 'x', 'y', 'z')``) and is not multiplied by the mesh dims —
    the tracing convention the batched-exchange census relies on.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from .. import AXIS_NAMES, NDIMS
    from ..utils.compat import shard_map

    def spec(f):
        nbatch = max(f.ndim - NDIMS, 0)
        return P(*(None,) * nbatch, *AXIS_NAMES[: f.ndim - nbatch])

    specs = tuple(spec(f) for f in fields)
    out_specs = (
        specs if out_fields is None else tuple(spec(f) for f in out_fields)
    )
    mapped = shard_map(
        body, mesh=gg.mesh, in_specs=specs, out_specs=out_specs,
        check_vma=False,
    )

    def gshape(f):
        nbatch = max(f.ndim - NDIMS, 0)
        return f.shape[:nbatch] + tuple(
            s * gg.dims[i] for i, s in enumerate(f.shape[nbatch:])
        )

    gargs = tuple(
        jax.ShapeDtypeStruct(gshape(f), f.dtype) for f in fields
    )
    return jax.make_jaxpr(mapped)(*gargs)


def trace_exchange_entries(n: int = 8) -> list:
    """The halo-exchange half of the config matrix.

    One grid — dims (2,2,2), periodic z — exercises PROC_NULL and periodic
    transports together; per model the production field set is traced with
    the coalesced and the per-field exchange, plus the padded-faces layout
    and the begin/finish slab pipeline (the pipelined schedules' exchange).
    """
    import implicitglobalgrid_tpu as igg
    from ..ops import halo

    entries = []
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        gg = igg.get_global_grid()
        mesh_shape = {a: int(s) for a, s in zip(igg.AXIS_NAMES, gg.dims)}
        for model in ("diffusion", "acoustic", "porous"):
            fields = model_field_structs(model, n)
            for coalesce in (True, False):

                def body(*fs, _c=coalesce):
                    return halo.exchange_dims_multi(
                        fs, (0, 1, 2), width=1, coalesce=_c
                    )

                entries.append(
                    TracedEntry(
                        name=f"exchange/{model}[coalesce={coalesce}]",
                        kind="exchange",
                        config={"model": model, "coalesce": coalesce},
                        jaxpr=unwrap_inner(
                            _trace_mapped(body, fields, gg).jaxpr
                        ),
                        mesh_shape=mesh_shape,
                    )
                )

        # Padded-faces layout (the fused cadences' exchange geometry).
        from ..ops.pallas_leapfrog import pad_faces

        fields4 = model_field_structs("acoustic", n)

        # pad_faces changes shapes, so the body returns the ORIGINAL fields
        # to keep in/out specs symmetric; the exchange still traces fully.
        def padded_body(C, Vx, Vy, Vz):
            Vxp, Vyp, Vzp = pad_faces(Vx, Vy, Vz)
            halo.update_halo_padded_faces(
                C, Vxp, Vyp, Vzp, width=1, coalesce=True
            )
            return C, Vx, Vy, Vz

        entries.append(
            TracedEntry(
                name="exchange/padded_faces",
                kind="exchange",
                config={"layout": "pad_faces"},
                jaxpr=unwrap_inner(
                    _trace_mapped(padded_body, fields4, gg).jaxpr
                ),
                mesh_shape=mesh_shape,
            )
        )

        # Early-dispatch slab pipeline (begin/finish).
        def slab_body(*fs):
            pends = halo.begin_slab_exchange(fs, (0, 1, 2), width=1)
            return halo.finish_slab_exchange(fs, pends)

        entries.append(
            TracedEntry(
                name="exchange/slab_pipeline",
                kind="exchange",
                config={"layout": "begin/finish"},
                jaxpr=unwrap_inner(
                    _trace_mapped(
                        slab_body, model_field_structs("porous", n), gg
                    ).jaxpr
                ),
                mesh_shape=mesh_shape,
            )
        )
    finally:
        igg.finalize_global_grid()
    return entries


# -- compiled programs (the optimized-HLO IR) ---------------------------------


@dataclass(frozen=True)
class CompiledProgram:
    """One XLA:CPU-compiled program of the config matrix.

    ``text`` is the optimized-HLO text (`utils.hlo_analysis` parses it);
    ``memory``/``cost`` carry the toolchain's own buffer-assignment and
    cost-analysis numbers (`memory_analysis`/`cost_analysis` — empty dicts
    where a toolchain does not expose them, and the cost model reports
    that as a lost metric rather than silently passing).
    """

    name: str
    kind: str            # "exchange" | "cadence"
    config: dict
    text: str
    memory: dict
    cost: dict


#: The compiled half of the config matrix.  The exchange program shares its
#: NAME (and grid/field config) with the traced entry of the same name, so
#: the cost model's payload cross-check compares the SAME program across
#: the jaxpr and optimized-HLO IRs.  Cadences compile pipelined=True — the
#: production schedule whose fusion/collective structure the baseline pins.
EXCHANGE_HLO_PROGRAM = "exchange/porous[coalesce=True]"
#: Ensemble size of the batched compiled programs (ISSUE 8): the batched
#: exchange must keep the unbatched program's collective count with
#: payload bytes scaled ×B — pinned by the cost baseline's
#: ``collective_permutes`` / ``collective_payload_bytes`` metrics.
BATCH_HLO_B = 4
BATCHED_EXCHANGE_PROGRAM = f"exchange/porous[coalesce=True,batch={BATCH_HLO_B}]"
BATCHED_CADENCE_PROGRAM = f"cadence/diffusion[batch={BATCH_HLO_B}]"
COMPILED_MATRIX = (
    EXCHANGE_HLO_PROGRAM,
    "cadence/diffusion[pipelined=True]",
    "cadence/acoustic[pipelined=True]",
    "cadence/porous[pipelined=True]",
    BATCHED_EXCHANGE_PROGRAM,
    BATCHED_CADENCE_PROGRAM,
)


def _compiled_stats(compiled) -> tuple[dict, dict]:
    """(memory, cost) numbers of one compiled executable, best-effort."""
    memory, cost = {}, {}
    try:
        ma = compiled.memory_analysis()
        memory = {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
        }
    except Exception:  # noqa: BLE001 — backend without memory stats
        pass
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        for key, out in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
            if key in ca:
                cost[out] = float(ca[key])
    except Exception:  # noqa: BLE001 — backend without cost analysis
        pass
    return memory, cost


def compile_program(name: str) -> CompiledProgram:
    """Compile one named program of `COMPILED_MATRIX` (XLA:CPU).

    Callers go through `core.Context.compiled_program`, which caches per
    config — the budget analyzer's HLO cross-check and the cost model's
    census share ONE compile of the exchange instead of building it twice.
    """
    if name == EXCHANGE_HLO_PROGRAM:
        return _compile_exchange_program()
    if name == BATCHED_EXCHANGE_PROGRAM:
        return _compile_batched_exchange_program()
    if name == BATCHED_CADENCE_PROGRAM:
        return _compile_batched_cadence_program()
    for model in ("diffusion", "acoustic", "porous"):
        if name == f"cadence/{model}[pipelined=True]":
            return _compile_cadence_program(model)
    raise ValueError(
        f"unknown compiled program {name!r}; matrix: {COMPILED_MATRIX}"
    )


def _compile_exchange_program(model: str = "porous", n: int = 8) -> CompiledProgram:
    """The porous 5-field coalesced exchange, compiled.

    The richest exchange program — where the PR-5 message-combining
    evidence (30 → 6 collective-permutes) lives.  Same grid (2,2,2)
    periodic-z and same ``n`` as `trace_exchange_entries`, so the traced
    twin of the same name is byte-comparable hop for hop.
    """
    import jax

    import implicitglobalgrid_tpu as igg
    from ..ops import halo

    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        gg = igg.get_global_grid()
        fields = model_field_structs(model, n)

        def body(*fs):
            return halo.exchange_dims_multi(fs, (0, 1, 2), width=1,
                                            coalesce=True)

        from jax.sharding import PartitionSpec as P

        from .. import AXIS_NAMES
        from ..utils.compat import shard_map

        specs = tuple(P(*AXIS_NAMES[: f.ndim]) for f in fields)
        mapped = shard_map(
            body, mesh=gg.mesh, in_specs=specs, out_specs=specs,
            check_vma=False,
        )
        gargs = tuple(
            jax.ShapeDtypeStruct(
                tuple(s * gg.dims[i] for i, s in enumerate(f.shape)),
                f.dtype,
            )
            for f in fields
        )
        compiled = jax.jit(mapped).lower(*gargs).compile()
        memory, cost = _compiled_stats(compiled)
        return CompiledProgram(
            name=f"exchange/{model}[coalesce=True]",
            kind="exchange",
            config={"model": model, "n": n, "coalesce": True},
            text=compiled.as_text(),
            memory=memory,
            cost=cost,
        )
    finally:
        igg.finalize_global_grid()


def _compile_batched_exchange_program(model: str = "porous", n: int = 8,
                                      B: int | None = None) -> CompiledProgram:
    """The porous coalesced exchange under a vmapped B-member ensemble axis,
    compiled — the optimized-HLO half of the B-for-the-price-of-1 evidence:
    the cost baseline pins its ``collective_permutes`` EQUAL to the
    unbatched twin's and its ``collective_payload_bytes`` at ×B."""
    import jax

    import implicitglobalgrid_tpu as igg
    from ..ops import halo

    B = BATCH_HLO_B if B is None else B
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        gg = igg.get_global_grid()
        fields = model_field_structs(model, n)

        def single(*fs):
            return halo.exchange_dims_multi(fs, (0, 1, 2), width=1,
                                            coalesce=True)

        def body(*fs):
            return jax.vmap(single)(*fs)

        from ..models._batched import _batched_spec
        from ..utils.compat import shard_map

        # THE batched-layout spec (`models._batched`): one definition for
        # the traced census, the serving pool and these compiled programs.
        specs = tuple(_batched_spec(f.ndim + 1) for f in fields)
        mapped = shard_map(
            body, mesh=gg.mesh, in_specs=specs, out_specs=specs,
            check_vma=False,
        )
        gargs = tuple(
            jax.ShapeDtypeStruct(
                (B,) + tuple(s * gg.dims[i] for i, s in enumerate(f.shape)),
                f.dtype,
            )
            for f in fields
        )
        compiled = jax.jit(mapped).lower(*gargs).compile()
        memory, cost = _compiled_stats(compiled)
        return CompiledProgram(
            name=f"exchange/{model}[coalesce=True,batch={B}]",
            kind="exchange",
            config={"model": model, "n": n, "coalesce": True, "batch": B},
            text=compiled.as_text(),
            memory=memory,
            cost=cost,
        )
    finally:
        igg.finalize_global_grid()


def _compile_batched_cadence_program(n: int = 8, B: int | None = None,
                                     nt: int = 2) -> CompiledProgram:
    """The batched diffusion serving cadence, compiled: ``make_multi_step(
    exchange_every=2, batch=True)`` on a deep-halo 2-device grid — the
    production shape of `serving.ServingLoop`'s round step (XLA cadence;
    the fused kernels' batched structure is covered by the vmap census,
    keeping this build seconds-cheap)."""
    import jax

    import implicitglobalgrid_tpu as igg
    from ..models import diffusion3d

    import jax.numpy as jnp

    B = BATCH_HLO_B if B is None else B
    # setup OUTSIDE the try (like `_compile_exchange_program`): if a
    # caller's grid is live, setup raises BEFORE the finally exists — the
    # teardown must only ever finalize the grid THIS function created.
    # dtype pinned like `_cadence_setup_kwargs`: the census must not
    # depend on the process's x64 default.
    state, params = diffusion3d.setup(
        n, n, n, devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
        overlapx=4, overlapy=4, overlapz=4, quiet=True,
        dtype=jnp.float32,
    )
    try:
        from ..models._batched import stack_states

        bstate = stack_states([state] * B)
        step = diffusion3d.make_multi_step(
            params, nt, donate=False, exchange_every=2, batch=True
        )
        gg = igg.get_global_grid()
        from ..models._batched import _batched_spec
        from ..utils.compat import shard_map

        spec = _batched_spec(4)  # the one batched-layout definition
        mapped = shard_map(
            step.__wrapped__, mesh=gg.mesh,
            in_specs=(spec,) * 2, out_specs=(spec,) * 2, check_vma=False,
        )
        compiled = jax.jit(mapped).lower(*bstate).compile()
        memory, cost = _compiled_stats(compiled)
        return CompiledProgram(
            name=f"cadence/diffusion[batch={B}]",
            kind="cadence",
            config={"model": "diffusion", "n": n, "batch": B, "nt": nt,
                    "exchange_every": 2},
            text=compiled.as_text(),
            memory=memory,
            cost=cost,
        )
    finally:
        if igg.grid_is_initialized():
            igg.finalize_global_grid()


def compile_exchange_hlo(model: str = "porous", n: int = 8) -> str:
    """Optimized-HLO text of the porous coalesced exchange (back-compat
    text-only view of `_compile_exchange_program`)."""
    return _compile_exchange_program(model, n).text


#: Cadence matrix: one admissible pipelined config per model (from the
#: pipelined-schedule test matrix) traced with pipelined on AND off.
_CADENCES = (
    ("diffusion", dict(nloc=(40, 32, 128), nt=4, k=2, tile=(8, 16),
                       periods={})),
    ("acoustic", dict(nloc=(24, 32, 128), nt=4, k=2, tile=(8, 16),
                      periods={"periodz": 1})),
    ("porous", dict(nloc=(24, 32, 128), nt=2, k=2, tile=(8, 16),
                    periods={"periodz": 1}, npt=5)),
)

_MODEL_MODULES = {
    "diffusion": "diffusion3d",
    "acoustic": "acoustic3d",
    "porous": "porous_convection3d",
}


def _cadence_setup_kwargs(cfg) -> dict:
    """`setup(...)` kwargs of one cadence config (2-device x-split grid)."""
    import jax
    import jax.numpy as jnp

    kw = dict(
        devices=jax.devices()[:2], dimx=2, dimy=1, dimz=1,
        overlapx=2 * cfg["k"], overlapy=2 * cfg["k"],
        overlapz=2 * cfg["k"], quiet=True, dtype=jnp.float32,
        **cfg["periods"],
    )
    if "npt" in cfg:
        kw["npt"] = cfg["npt"]
    return kw


def trace_cadence_entries() -> list:
    """Trace each model's fused multi-step cadence, pipelined on/off.

    Trace-only through the generic Pallas interpreter — no execution.  A
    pipelined config that falls back to the serialized schedule (warn-once
    path) is recorded with ``admissible=False`` so the overlap analyzer can
    distinguish "no overlap possible" from "overlap lost".
    """
    import importlib

    import jax
    from jax.sharding import PartitionSpec as P

    import implicitglobalgrid_tpu as igg
    from ..utils.compat import pallas_force_interpret, shard_map

    entries = []
    for model, cfg in _CADENCES:
        mod = importlib.import_module(
            "implicitglobalgrid_tpu.models." + _MODEL_MODULES[model]
        )
        for pipelined in (False, True):
            try:
                state, params = mod.setup(
                    *cfg["nloc"], **_cadence_setup_kwargs(cfg)
                )
                admissible = True
                with pallas_force_interpret():
                    with warnings.catch_warnings(record=True) as caught:
                        warnings.simplefilter("always")
                        step = mod.make_multi_step(
                            params, cfg["nt"], donate=False,
                            fused_k=cfg["k"], fused_tile=cfg["tile"],
                            pipelined=pipelined,
                        )
                        gg = igg.get_global_grid()
                        nf = len(state)
                        mapped = shard_map(
                            step.__wrapped__, mesh=gg.mesh,
                            in_specs=(P(*igg.AXIS_NAMES),) * nf,
                            out_specs=(P(*igg.AXIS_NAMES),) * nf,
                            check_vma=False,
                        )
                        jaxpr = jax.make_jaxpr(mapped)(*state)
                    if pipelined and any(
                        "not admissible" in str(w.message) for w in caught
                    ):
                        admissible = False
                mesh_shape = {
                    a: int(s) for a, s in zip(igg.AXIS_NAMES, gg.dims)
                }
            finally:
                # a failed trace must not leak the grid into the next
                # config's setup (or a later analyzer's init)
                if igg.grid_is_initialized():
                    igg.finalize_global_grid()
            entries.append(
                TracedEntry(
                    name=f"cadence/{model}[pipelined={pipelined}]",
                    kind="cadence",
                    config={"model": model, "pipelined": pipelined, **cfg},
                    jaxpr=unwrap_inner(jaxpr.jaxpr),
                    mesh_shape=mesh_shape,
                    admissible=admissible,
                )
            )
    return entries


def _compile_cadence_program(model: str) -> CompiledProgram:
    """Compile one model's fused cadence (pipelined=True, the `_CADENCES`
    config) through the generic Pallas interpreter — the optimized-HLO view
    of the production multi-step program the cost model pins.  One XLA:CPU
    build per model, seconds each; `Context` caches the result."""
    import importlib

    import jax
    from jax.sharding import PartitionSpec as P

    import implicitglobalgrid_tpu as igg
    from ..utils.compat import pallas_force_interpret, shard_map

    cfg = dict(_CADENCES)[model]
    mod = importlib.import_module(
        "implicitglobalgrid_tpu.models." + _MODEL_MODULES[model]
    )
    try:
        state, params = mod.setup(*cfg["nloc"], **_cadence_setup_kwargs(cfg))
        with pallas_force_interpret():
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                step = mod.make_multi_step(
                    params, cfg["nt"], donate=False,
                    fused_k=cfg["k"], fused_tile=cfg["tile"],
                    pipelined=True,
                )
                gg = igg.get_global_grid()
                nf = len(state)
                mapped = shard_map(
                    step.__wrapped__, mesh=gg.mesh,
                    in_specs=(P(*igg.AXIS_NAMES),) * nf,
                    out_specs=(P(*igg.AXIS_NAMES),) * nf,
                    check_vma=False,
                )
                compiled = jax.jit(mapped).lower(*state).compile()
        memory, cost = _compiled_stats(compiled)
        return CompiledProgram(
            name=f"cadence/{model}[pipelined=True]",
            kind="cadence",
            config={"model": model, "pipelined": True, **cfg},
            text=compiled.as_text(),
            memory=memory,
            cost=cost,
        )
    finally:
        if igg.grid_is_initialized():
            igg.finalize_global_grid()


# -- traced VJP producers (grad-soundness) ------------------------------------


@dataclass(frozen=True)
class GradTrace:
    """One differentiable entry point traced through `jax.vjp`.

    ``jaxpr`` is the inner jaxpr of the whole VJP program (forward replay +
    backward pass — seeds and primals in, one cotangent per primal out);
    ``primal_jaxpr`` is the matching primal-only trace.  The grad-soundness
    census compares their collective counts: a cross-boundary cotangent
    MUST ride collectives backward, so a VJP trace whose collective count
    does not exceed the primal's has dropped its cross-rank gradient — the
    PR-5 bitcast-without-VJP class, statically.
    """

    name: str
    kind: str            # "exchange" | "cadence"
    config: dict
    jaxpr: object
    primal_jaxpr: object

    def collective_counts(self) -> tuple[int, int]:
        """(grad_collectives, primal_collectives)."""
        return (
            len(collect_collectives(self.jaxpr)),
            len(collect_collectives(self.primal_jaxpr)),
        )


def trace_grad_entries(n: int = 8) -> list:
    """VJP traces of every differentiable entry point.

    Two families (trace-only, no execution):

    * the coalesced exchange of each model's production field set — the
      `_packed_transport` custom-VJP path (PR 5's hand-written transpose);
    * each model's fused multi-step cadence — the `fused_with_xla_grad`
      family (primal replays the fused body, backward differentiates the
      XLA twin).

    Seeds are passed as leading ARGUMENTS (not synthesized inside), so the
    traced program's cotangent outputs carry real dataflow from the seed
    inputs — the census counts collectives, which only appear when the
    backward pass actually transports cotangents across ranks.
    """
    import importlib

    import jax
    from jax.sharding import PartitionSpec as P

    import implicitglobalgrid_tpu as igg
    from ..ops import halo
    from ..utils.compat import pallas_force_interpret, shard_map

    entries = []

    # Exchange family: one grid, all models, coalesce=True (the packed
    # transport whose custom VJP the census proves alive).
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        gg = igg.get_global_grid()
        for model in ("diffusion", "acoustic", "porous"):
            fields = model_field_structs(model, n)
            nf = len(fields)

            def body(*fs):
                return halo.exchange_dims_multi(fs, (0, 1, 2), width=1,
                                                coalesce=True)

            def grad_body(*args, _body=body, _nf=nf):
                seeds, prims = args[:_nf], args[_nf:]
                _, vjp = jax.vjp(_body, *prims)
                return vjp(tuple(seeds))

            gj = _trace_mapped(grad_body, fields * 2, gg, out_fields=fields)
            pj = _trace_mapped(body, fields, gg)
            entries.append(
                GradTrace(
                    name=f"grad/exchange/{model}",
                    kind="exchange",
                    config={"model": model, "coalesce": True},
                    jaxpr=unwrap_inner(gj.jaxpr),
                    primal_jaxpr=unwrap_inner(pj.jaxpr),
                )
            )
    finally:
        igg.finalize_global_grid()

    # Cadence family: the fused multi-step of each model (serialized
    # schedule — the default production grad path; the pipelined twin's
    # structure is covered by `overlap-independence`).
    for model, cfg in _CADENCES:
        mod = importlib.import_module(
            "implicitglobalgrid_tpu.models." + _MODEL_MODULES[model]
        )
        try:
            state, params = mod.setup(
                *cfg["nloc"], **_cadence_setup_kwargs(cfg)
            )
            with pallas_force_interpret():
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    step = mod.make_multi_step(
                        params, cfg["nt"], donate=False,
                        fused_k=cfg["k"], fused_tile=cfg["tile"],
                        pipelined=False,
                    )
                    gg = igg.get_global_grid()
                    nf = len(state)

                    def grad_body(*args, _step=step, _nf=nf):
                        seeds, prims = args[:_nf], args[_nf:]
                        _, vjp = jax.vjp(_step.__wrapped__, *prims)
                        return vjp(tuple(seeds))

                    specs = (P(*igg.AXIS_NAMES),) * nf
                    mapped = shard_map(
                        grad_body, mesh=gg.mesh, in_specs=specs * 2,
                        out_specs=specs, check_vma=False,
                    )
                    gj = jax.make_jaxpr(mapped)(*state, *state)
                    pm = shard_map(
                        step.__wrapped__, mesh=gg.mesh, in_specs=specs,
                        out_specs=specs, check_vma=False,
                    )
                    pj = jax.make_jaxpr(pm)(*state)
        finally:
            if igg.grid_is_initialized():
                igg.finalize_global_grid()
        entries.append(
            GradTrace(
                name=f"grad/cadence/{model}",
                kind="cadence",
                config={"model": model, **cfg},
                jaxpr=unwrap_inner(gj.jaxpr),
                primal_jaxpr=unwrap_inner(pj.jaxpr),
            )
        )
    return entries


# -- kernel identification (shared by overlap + aliasing) ---------------------


def is_kernel_eqn(eqn) -> bool:
    """A Pallas kernel launch: a ``pallas_call`` eqn, or a ``pjit`` whose
    body is (recursively) just kernel launches — the kernels' cached
    ``jax.jit(pallas_call)`` builders appear as pjit eqns."""
    if eqn.primitive.name == "pallas_call":
        return True
    if eqn.primitive.name == "pjit":
        sub = eqn.params.get("jaxpr")
        if sub is None:
            return False
        body = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        return any(
            e.primitive.name == "pallas_call"
            or (e.primitive.name == "pjit" and is_kernel_eqn(e))
            for e in body.eqns
        )
    return False


def iter_pallas_calls(jaxpr):
    """Yield every ``pallas_call`` eqn in a program (all nesting levels)."""
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name == "pallas_call":
            yield eqn


def _eqn_presence(eqn) -> tuple:
    """``(has_kernel, has_collective)`` anywhere inside one equation.

    The classification the independence census keys on: a ``pjit`` or
    ``custom_vjp`` envelope containing only kernel launches IS a kernel
    (the cached ``jax.jit(pallas_call)`` builders); one containing only
    collectives IS a collective (the coalesced exchange's
    ``_packed_transport`` custom-VJP envelope, PR 5); one containing BOTH
    is a composite sub-program (`fused_with_xla_grad` wraps a whole
    cadence step per iteration) that must be analyzed at its own level.
    """
    if eqn.primitive.name == "pallas_call":
        return True, False
    if eqn.primitive.name in COLLECTIVE_PRIMS:
        return False, True
    has_k = has_c = False
    for _, sub in _sub_jaxprs(eqn):
        for e, _ in iter_eqns(sub):
            if e.primitive.name == "pallas_call":
                has_k = True
            elif e.primitive.name in COLLECTIVE_PRIMS:
                has_c = True
            if has_k and has_c:
                return True, True
    return has_k, has_c


def independence_pairs(jaxpr, *, is_kernel=None, is_collective=None):
    """Count (kernel, collective) pairs with NO transitive dependency in
    either direction among the direct equations of ``jaxpr`` — the
    dataflow freedom the pipelined schedule exists to create, asserted
    below the compiler.

    Returns ``(free_pairs, n_kernels, n_collectives)``.  By default an
    equation counts as a kernel/collective by CONTENT (`_eqn_presence`):
    kernel-only and collective-only envelopes join the census at this
    level, while composite envelopes containing both (the per-step
    ``fused_with_xla_grad`` custom-VJP wrapper) are recursed into and
    their counts summed — each wrapped step body is its own independence
    scope.  Predicates are injectable so tests can probe the counter with
    stand-in "kernels" (injection disables the composite recursion and
    restores the literal top-level census).  Generalized from
    ``tests/test_pipelined_schedule.py`` (ISSUE 2's structural-overlap
    evidence) to run across all models.
    """
    composites = []
    if is_kernel is None and is_collective is None:
        presence = {id(e): _eqn_presence(e) for e in jaxpr.eqns}
        is_kernel = lambda e: presence[id(e)] == (True, False)  # noqa: E731
        is_collective = lambda e: presence[id(e)] == (False, True)  # noqa: E731
        composites = [e for e in jaxpr.eqns if presence[id(e)] == (True, True)]
    else:
        is_kernel = is_kernel or is_kernel_eqn
        is_collective = is_collective or (
            lambda e: e.primitive.name == "ppermute"
        )
    producer = {}
    for e in jaxpr.eqns:
        for ov in e.outvars:
            producer[id(ov)] = e

    def closure(eqn):
        seen, stack = set(), [eqn]
        while stack:
            for v in stack.pop().invars:
                p = producer.get(id(v))
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    stack.append(p)
        return seen

    kernels = [e for e in jaxpr.eqns if is_kernel(e)]
    colls = [e for e in jaxpr.eqns if is_collective(e)]
    kc = {id(e): closure(e) for e in kernels}
    pairs = 0
    for c in colls:
        cc = closure(c)
        for k in kernels:
            if id(k) not in cc and id(c) not in kc[id(k)]:
                pairs += 1
    nk, nc = len(kernels), len(colls)
    for comp in composites:
        for _, sub in _sub_jaxprs(comp):
            p, k, c = independence_pairs(sub)
            pairs += p
            nk += k
            nc += c
    return pairs, nk, nc
