"""Knob analyzers: trace-time binding lint + declaration/documentation lint.

**knob-binding** — the subtle bug class: an ``os.environ`` / ``IGG_*`` read
executed *inside* a ``jit``/``shard_map``/Pallas-traced function runs at
TRACE time, so its value is baked into the cached executable; flipping the
env var later silently does nothing because the jit cache key never sees
it.  The pass builds an approximate intra-package call graph from the AST,
marks *trace roots* (functions handed to ``shard_map``/``pallas_call``/
``jit``/control-flow combinators, or decorated with them), and flags every
call edge that crosses from trace-reachable code into an env-reading
function.  Call resolution is name- and import-alias-based (documented
approximation: method dispatch and higher-order callables are not
followed), which is exactly enough for this package's idiom of nested
``def body(...)`` closures handed to ``shard_map``.

**knob-decl** — the discoverability lint from ``scripts/check_knobs.py``
(PR 4): every ``IGG_*`` referenced in the package must be declared in
``utils/config.py`` and documented in ``docs/usage.md``.  The script is now
a thin CLI wrapper over this module.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .core import Context, Finding

#: Callees whose function-valued arguments are traced.  ``grad``/
#: ``make_jaxpr``/``eval_shape`` trace too — an env read under any of
#: these binds at trace time.
TRACE_CALLEES = frozenset(
    {
        "jit",
        "shard_map",
        "pallas_call",
        "stencil",
        "fori_loop",
        "while_loop",
        "scan",
        "cond",
        "switch",
        "checkpoint",
        "remat",
        "custom_vjp",
        "custom_jvp",
        "vmap",
        "pmap",
        "grad",
        "value_and_grad",
        "make_jaxpr",
        "eval_shape",
        # package-local combinators that call their arguments inside an
        # enclosing trace (the fused group schedules)
        "run_group_schedule",
        "run_pipelined_group_schedule",
    }
)

#: Decorators that make the decorated function a trace root.
TRACE_DECORATORS = frozenset({"jit", "stencil", "custom_vjp", "custom_jvp"})

_KNOB = re.compile(r"IGG_[A-Z0-9_]+")


@dataclass
class _Func:
    """One function definition in the package."""

    module: str                 # repo-relative path
    qualname: str
    lineno: int
    calls: list = field(default_factory=list)   # (target_key, lineno, name)
    env_reads: list = field(default_factory=list)  # (lineno, knob-or-"")
    is_root: bool = False

    @property
    def key(self):
        return (self.module, self.qualname)


def _last_attr(node) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _last_attr(node.func)
    return ""


class _ModuleIndexer(ast.NodeVisitor):
    """Collect functions, call edges, env reads and trace roots of one
    module.  Call targets are recorded as unresolved ``("local", name)`` /
    ``("import", alias, attr)`` keys; `_CallGraph` resolves them."""

    def __init__(self, rel: str):
        self.rel = rel
        self.funcs: dict[str, _Func] = {}
        self.stack: list[str] = []
        # import maps: alias -> module path ("a.b.c"), and
        # from-imports: name -> (module path, original name)
        self.mod_alias: dict[str, str] = {}
        self.from_imports: dict[str, tuple[str, str]] = {}
        self.local_names: set[str] = set()

    # -- imports ---------------------------------------------------------

    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.mod_alias[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom):
        # resolve relative imports against this module's package path
        pkg_parts = self.rel.replace("/", ".").rsplit(".py", 1)[0].split(".")
        if node.level:
            base = pkg_parts[: -node.level]
        else:
            base = []
        mod = ".".join(base + (node.module.split(".") if node.module else []))
        for a in node.names:
            self.from_imports[a.asname or a.name] = (mod, a.name)

    # -- functions -------------------------------------------------------

    def _qual(self, name: str) -> str:
        return ".".join(self.stack + [name])

    def _cur(self) -> _Func | None:
        if not self.stack:
            return None
        return self.funcs.get(".".join(self.stack))

    def _visit_funcdef(self, node):
        qual = self._qual(node.name)
        fn = _Func(module=self.rel, qualname=qual, lineno=node.lineno)
        self.funcs[qual] = fn
        self.local_names.add(node.name)
        for dec in node.decorator_list:
            if _last_attr(dec) in TRACE_DECORATORS:
                fn.is_root = True
            # functools.partial(jax.jit, ...) and friends
            if isinstance(dec, ast.Call) and any(
                _last_attr(a) in TRACE_DECORATORS for a in dec.args
            ):
                fn.is_root = True
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    # -- reads + calls ---------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute):
        # os.environ in any expression position is an env read
        if (
            node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ):
            cur = self._cur()
            if cur is not None:
                cur.env_reads.append((node.lineno, ""))
        self.generic_visit(node)

    def _environ_get(self, node: ast.Call) -> bool:
        """``os.environ.get("X")``: record the knob constant and skip the
        func subtree so `visit_Attribute` does not double-count the read."""
        f = node.func
        if not (
            isinstance(f, ast.Attribute)
            and f.attr == "get"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "environ"
            and isinstance(f.value.value, ast.Name)
            and f.value.value.id == "os"
        ):
            return False
        cur = self._cur()
        if cur is not None:
            knob = ""
            if node.args and isinstance(node.args[0], ast.Constant):
                knob = str(node.args[0].value)
            cur.env_reads.append((node.lineno, knob))
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            self.visit(arg)
        return True

    def visit_Call(self, node: ast.Call):
        if self._environ_get(node):
            return
        cur = self._cur()
        name = _last_attr(node.func)
        if cur is not None:
            if name == "getenv":
                knob = ""
                if node.args and isinstance(node.args[0], ast.Constant):
                    knob = str(node.args[0].value)
                cur.env_reads.append((node.lineno, knob))
            else:
                target = self._call_target(node.func)
                if target is not None:
                    # constant first-arg knob names ride along so accessor
                    # calls like _int_env("IGG_DONATE") attribute the knob
                    knob = ""
                    if node.args and isinstance(node.args[0], ast.Constant):
                        if isinstance(node.args[0].value, str) and _KNOB.match(
                            node.args[0].value
                        ):
                            knob = node.args[0].value
                    cur.calls.append((target, node.lineno, name, knob))
        # any function handed to a tracing callee becomes a trace root
        if name in TRACE_CALLEES:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    self._mark_root(arg.id)
        self.generic_visit(node)

    def _call_target(self, func) -> tuple | None:
        if isinstance(func, ast.Name):
            if func.id in self.from_imports:
                mod, orig = self.from_imports[func.id]
                return ("import", mod, orig)
            return ("local", func.id)
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            base = func.value.id
            if base in self.from_imports:
                mod, orig = self.from_imports[base]
                return ("import", f"{mod}.{orig}", func.attr)
            if base in self.mod_alias:
                return ("import", self.mod_alias[base], func.attr)
        return None

    def _mark_root(self, name: str):
        # innermost function of that name visible from the current scope
        for depth in range(len(self.stack), -1, -1):
            qual = ".".join(self.stack[:depth] + [name])
            if qual in self.funcs:
                self.funcs[qual].is_root = True
                return


class _CallGraph:
    def __init__(self, ctx: Context):
        self.package_name = os.path.basename(ctx.package_root)
        self.modules: dict[str, _ModuleIndexer] = {}
        for rel, (_src, tree) in ctx.module_asts().items():
            idx = _ModuleIndexer(rel)
            idx.visit(tree)
            self.modules[rel] = idx
        # global indices
        self.funcs: dict[tuple, _Func] = {}
        self.by_module_and_name: dict[tuple, list[tuple]] = {}
        for rel, idx in self.modules.items():
            for qual, fn in idx.funcs.items():
                self.funcs[fn.key] = fn
                bare = qual.split(".")[-1]
                self.by_module_and_name.setdefault((rel, bare), []).append(
                    fn.key
                )

    def _module_rel(self, dotted: str) -> str | None:
        """``implicitglobalgrid_tpu.utils.config`` -> its repo-relative
        path, if the module is part of the scanned package."""
        parts = dotted.split(".")
        if not parts or parts[0] != self.package_name:
            # relative imports already resolved to full dotted paths that
            # start with the scanned package's directory name
            pass
        for cand in (
            "/".join(parts) + ".py",
            "/".join(parts) + "/__init__.py",
        ):
            if cand in self.modules:
                return cand
        return None

    def resolve(self, caller: _Func, target: tuple) -> list[tuple]:
        """Candidate callee keys for one recorded call target."""
        if target[0] == "local":
            name = target[1]
            idx = self.modules[caller.module]
            # innermost enclosing scope first, then module level
            parts = caller.qualname.split(".")
            for depth in range(len(parts), -1, -1):
                qual = ".".join(parts[:depth] + [name])
                if qual in idx.funcs:
                    return [(caller.module, qual)]
            return []
        _, mod, name = target
        rel = self._module_rel(mod)
        if rel is None:
            return []
        return self.by_module_and_name.get((rel, name), [])

    def trace_roots(self) -> list[tuple]:
        return [k for k, f in self.funcs.items() if f.is_root]


def _direct_readers(graph: _CallGraph) -> dict[tuple, list]:
    return {
        k: f.env_reads for k, f in graph.funcs.items() if f.env_reads
    }


def _transitive_readers(graph: _CallGraph) -> dict[tuple, set[str]]:
    """``func key -> set of knob names`` for every function that reads env
    directly or through calls.  Knob names come from constant reads and
    from constant first args passed into reader calls (the accessor idiom
    ``_int_env("IGG_DONATE")``)."""
    readers: dict[tuple, set[str]] = {
        k: {kn for _, kn in reads if kn} or {""}
        for k, reads in _direct_readers(graph).items()
    }
    changed = True
    while changed:
        changed = False
        for key, fn in graph.funcs.items():
            for target, _ln, _name, knob in fn.calls:
                for callee in graph.resolve(fn, target):
                    if callee in readers:
                        knobs = set(readers[callee])
                        if knob:
                            knobs = {knob} | (knobs - {""})
                        cur = readers.setdefault(key, set())
                        if not knobs <= cur:
                            cur |= knobs
                            changed = True
    return readers


def run_knob_binding(ctx: Context) -> list[Finding]:
    """One finding PER KNOB reachable from traced code.

    BFS from the trace roots over the call graph; every edge that crosses
    from non-reading code into the env-reading closure is a "crossing",
    attributed to the knob(s) it binds.  Findings aggregate all crossings
    of one knob (a knob read by five cadences is ONE decision to make:
    fix the binding or baseline the documented per-trace contract), with
    an example trace chain and the crossing count in the message.  The
    fingerprint hashes only the knob name, so a baseline entry survives
    any refactor of the paths that reach it.
    """
    graph = _CallGraph(ctx)
    readers = _transitive_readers(graph)
    roots = graph.trace_roots()

    hits: dict[str, dict] = {}  # knob -> evidence

    def record(knob: str, chain, crossing_fn: _Func, lineno: int):
        h = hits.setdefault(
            knob,
            {"chain": None, "crossings": set(), "fn": crossing_fn,
             "line": lineno},
        )
        h["crossings"].add((crossing_fn.module, crossing_fn.qualname))
        if h["chain"] is None or len(chain) < len(h["chain"]):
            h["chain"] = chain
            h["fn"] = crossing_fn
            h["line"] = lineno

    seen = set(roots)
    frontier = list(roots)
    chains = {k: [k] for k in roots}
    while frontier:
        key = frontier.pop()
        fn = graph.funcs[key]
        # Crossing attribution happens at the first reader edge along a
        # chain: once a chain has passed THROUGH a reader, everything
        # deeper is that reader's internals (config accessors, telemetry
        # registry) and is already attributed by the crossing above it.
        entered_via_reader = any(k in readers for k in chains[key][:-1])
        if fn.env_reads and not entered_via_reader:
            for ln, knob in fn.env_reads:
                record(knob or f"os.environ@{fn.qualname}", chains[key], fn,
                       ln)
        for target, ln, name, knob in fn.calls:
            for callee in graph.resolve(fn, target):
                if callee in readers and not entered_via_reader:
                    # first edge into the reading closure: attribute knobs
                    cfn = graph.funcs[callee]
                    knobs = (
                        {knob} | (readers[callee] - {""})
                        if knob
                        else set(readers[callee])
                    )
                    for kn in knobs:
                        record(
                            kn or f"os.environ@{cfn.qualname}",
                            chains[key] + [callee],
                            cfn,
                            cfn.lineno,
                        )
                if callee not in seen:
                    seen.add(callee)
                    chains[callee] = chains[key] + [callee]
                    frontier.append(callee)

    out = []
    for knob in sorted(hits):
        h = hits[knob]
        fn: _Func = h["fn"]
        via = " -> ".join(q for _m, q in h["chain"])
        n = len(h["crossings"])
        out.append(
            Finding(
                analyzer="knob-binding",
                code="env-read-in-trace",
                severity="ERROR",
                message=(
                    f"{knob} is read inside traced code "
                    f"(`{fn.qualname}` at {fn.module}:{h['line']}, reached "
                    f"from {n} trace-reachable function(s); e.g. {via}): "
                    f"the value binds at TRACE time, so a cached jit "
                    f"executable silently ignores later changes to the "
                    f"knob."
                ),
                # path/symbol deliberately empty: the fingerprint must hash
                # the KNOB alone (anchor), so a baseline entry survives any
                # refactor of the functions that reach the read — the
                # reader's location lives in the message instead.
                anchor=knob,
                fix_hint=(
                    "resolve the knob host-side before entering "
                    "jit/shard_map and pass it as an argument (or bake it "
                    "into the jit cache key), or baseline it with a "
                    "justification if the per-trace binding is the "
                    "documented contract (utils/config.py)."
                ),
            )
        )
    return out


# -- knob-decl (scripts/check_knobs.py core) ----------------------------------


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read()


def referenced_knobs(repo: str, package: str, config: str) -> dict:
    """``knob -> [repo-relative files referencing it]`` over the package,
    excluding the declaration site (utils/config.py)."""
    refs: dict[str, list[str]] = {}
    for dirpath, dirnames, filenames in os.walk(package):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            if os.path.samefile(path, config):
                continue
            rel = os.path.relpath(path, repo)
            for knob in set(_KNOB.findall(_read(path))):
                refs.setdefault(knob, []).append(rel)
    return {k: sorted(v) for k, v in sorted(refs.items())}


def knob_decl_findings(repo: str, package: str, config: str,
                       usage: str) -> list[Finding]:
    declared = set(_KNOB.findall(_read(config)))
    documented = set(_KNOB.findall(_read(usage)))
    out = []
    for knob, files in referenced_knobs(repo, package, config).items():
        where = ", ".join(files)
        if knob not in declared:
            out.append(
                Finding(
                    analyzer="knob-decl",
                    code="undeclared-knob",
                    severity="ERROR",
                    message=(
                        f"{knob} (referenced in {where}) is not declared "
                        f"in implicitglobalgrid_tpu/utils/config.py"
                    ),
                    path=files[0],
                    symbol=knob,
                    anchor="declare",
                    fix_hint=(
                        "add it to the knob table in utils/config.py (and "
                        "an accessor if it is read per call)"
                    ),
                )
            )
        if knob not in documented:
            out.append(
                Finding(
                    analyzer="knob-decl",
                    code="undocumented-knob",
                    severity="ERROR",
                    message=(
                        f"{knob} (referenced in {where}) is not documented "
                        f"in docs/usage.md"
                    ),
                    path=files[0],
                    symbol=knob,
                    anchor="document",
                    fix_hint="add a row to the env-var table in docs/usage.md",
                )
            )
    return out


def run_knob_decl(ctx: Context) -> list[Finding]:
    return knob_decl_findings(
        repo=ctx.repo_root,
        package=ctx.package_root,
        config=os.path.join(ctx.package_root, "utils", "config.py"),
        usage=os.path.join(ctx.repo_root, "docs", "usage.md"),
    )
