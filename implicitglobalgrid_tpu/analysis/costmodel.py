"""Static HLO cost-model analyzer (``hlo-cost``) — the perf-invariant gate.

The repo's entire value proposition is a performance invariant — halo
exchange O(surface), compute O(volume) — but until this pass the only perf
evidence was hand-run ``bench.py`` records: a PR could add a silent copy,
defuse a kernel, or widen a halo payload and still pass tier-1.  This pass
walks the OPTIMIZED HLO of the production config matrix (the porous 5-field
coalesced exchange + all three models' fused cadences,
`ir.COMPILED_MATRIX`, compiled once per run and cached on the `Context`)
and pins per-program invariants in a versioned baseline with tolerance
bands — so a structural perf regression fails tier-1 without touching a
chip:

* **collective_permutes / collective_payload_bytes** — the exchange budget
  in bytes, parsed per hop by `utils.hlo_analysis.collective_payloads` and
  cross-checked BYTE-EXACTLY against the traced-jaxpr twin of the same
  program (two IRs, one number — a widened payload cannot hide in either);
* **fusions / kernel_launches** — the fusion structure XLA kept (a defused
  extra kernel shows up as a count bump);
* **flops / bytes_accessed** — the toolchain's own cost analysis (HBM
  traffic proxy: an extra full-field copy moves the needle far beyond the
  band);
* **temp_bytes / argument_bytes / output_bytes** — buffer assignment (peak
  temp allocation catches a materialized intermediate).

Baseline: `analysis/cost_baseline.json` — refreshed ONLY through
``scripts/refresh_cost_baseline.py``, which requires a ``--justify`` note
per changed metric (the same audit contract as `analysis/baseline.json`).
Tolerances are per-metric: structural counts are exact, toolchain-derived
floats carry a small band (`TOLERANCES`).
"""

from __future__ import annotations

import json
import os

from .core import Context, Finding

ANALYZER = "hlo-cost"

#: Versioned cost baseline, next to the analyzers like `baseline.json`.
COST_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "cost_baseline.json"
)

#: Relative tolerance per metric (fraction of the baseline value; ``"*"``
#: is the default).  Structural counts are exact — a single extra
#: collective or kernel launch IS the regression this pass exists to
#: catch; the toolchain-derived floats get a small band for compiler
#: scheduling noise.
TOLERANCES = {
    "flops": 0.02,
    "bytes_accessed": 0.02,
    "temp_bytes": 0.05,
    "*": 0.0,
}


# -- census -------------------------------------------------------------------


def text_census(txt: str) -> dict:
    """The text-derived half of one program's census (pure over HLO text).

    Instruction classification goes through the ONE blessed HLO parser
    (`utils.hlo_analysis`: `parse_computations` + `_op_kind`, the module's
    "one parser ... cannot drift" contract) — a formatting fix landed
    there must not diverge from the counts this baseline gates on.
    ``kernel_launches`` counts ``custom-call`` instructions (Pallas kernels
    on a real backend; the generic interpreter lowers kernels to pure HLO,
    where the fusion count carries the structure instead).
    """
    from ..utils.hlo_analysis import (
        _INST_RE,
        _op_kind,
        collective_payloads,
        parse_computations,
    )

    kinds = {"collective-permute": 0, "collective-permute-start": 0,
             "fusion": 0, "custom-call": 0}
    for lines in parse_computations(txt).values():
        for line in lines:
            m = _INST_RE.match(line)
            if m:
                kind = _op_kind(m.group(2))
                if kind in kinds:
                    kinds[kind] += 1
    recs = collective_payloads(txt)
    return {
        "collective_permutes": kinds["collective-permute"]
        + kinds["collective-permute-start"],
        "collective_payload_bytes": sum(r["bytes"] for r in recs),
        "payload_fallbacks": sum(
            1 for r in recs if r.get("payload_fallback")
        ),
        "fusions": kinds["fusion"],
        "kernel_launches": kinds["custom-call"],
    }


def program_census(prog) -> dict:
    """Full metric census of one `ir.CompiledProgram` (text + toolchain
    stats).  Metrics a toolchain does not expose are simply absent — the
    baseline comparison reports them as LOST rather than silently passing."""
    out = text_census(prog.text)
    out.update(prog.memory)
    out.update(prog.cost)
    return out


def cost_census(ctx: Context) -> dict:
    """``{program name: metric census}`` over the compiled matrix."""
    return {
        name: program_census(prog)
        for name, prog in ctx.compiled_programs().items()
    }


# -- the traced-vs-compiled payload cross-check -------------------------------


def payload_crosscheck_findings(ctx: Context) -> list[Finding]:
    """The two-IR payload identity of the porous coalesced exchange.

    The traced jaxpr and the compiled HLO describe the SAME program
    (`ir.EXCHANGE_HLO_PROGRAM` shares its name and config with the traced
    entry), so their per-hop collective payloads must agree byte-exactly —
    hop count, byte multiset, and total.  Any daylight between the two
    means one census lost track of the exchange (and every downstream
    budget built on it is an estimate); a `collective_payloads` raw-sum
    fallback is the same failure declared by the parser itself.
    """
    from ..utils.hlo_analysis import collective_payloads
    from .ir import EXCHANGE_HLO_PROGRAM

    out = []
    entry = next(
        (e for e in ctx.exchange_entries() if e.name == EXCHANGE_HLO_PROGRAM),
        None,
    )
    if entry is None:
        return [
            Finding(
                analyzer=ANALYZER,
                code="crosscheck-broken",
                severity="ERROR",
                message=(
                    f"traced entry {EXCHANGE_HLO_PROGRAM} is missing from "
                    f"the exchange matrix — the payload cross-check has no "
                    f"jaxpr side to compare."
                ),
                symbol=EXCHANGE_HLO_PROGRAM,
                anchor="traced-missing",
            )
        ]
    traced = sorted(
        op.payload_bytes
        for op in entry.collectives()
        if op.kind == "ppermute"
    )
    recs = collective_payloads(ctx.exchange_hlo())
    compiled = sorted(r["bytes"] for r in recs)
    fallbacks = [r for r in recs if r.get("payload_fallback")]
    if fallbacks:
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="payload-fallback",
                severity="ERROR",
                message=(
                    f"{EXCHANGE_HLO_PROGRAM}: {len(fallbacks)} compiled "
                    f"collective payload(s) fell back to a raw operand sum "
                    f"— the byte census is an upper bound, not exact, and "
                    f"the cost baseline cannot gate on it."
                ),
                symbol=EXCHANGE_HLO_PROGRAM,
                anchor="fallback",
            )
        )
    if traced != compiled:
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="payload-mismatch",
                severity="ERROR",
                message=(
                    f"{EXCHANGE_HLO_PROGRAM}: traced jaxpr moves "
                    f"{sum(traced)} payload bytes across {len(traced)} "
                    f"hop(s) {traced} but the optimized HLO moves "
                    f"{sum(compiled)} across {len(compiled)} {compiled} — "
                    f"the compiler re-shaped the exchange (or a census "
                    f"lost track of it)."
                ),
                symbol=EXCHANGE_HLO_PROGRAM,
                anchor="bytes",
            )
        )
    return out


# -- baseline -----------------------------------------------------------------


def load_baseline(path: str = COST_BASELINE) -> dict:
    """The committed cost baseline.  Schema::

        {"version": 1,
         "tolerances": {"flops": 0.02, ..., "*": 0.0},
         "programs": {name: {"metrics": {metric: value},
                             "justifications": {metric: note}}}}

    Every metric value must carry a justification note (written by
    ``scripts/refresh_cost_baseline.py --justify``) — the file is an audit
    trail, not a snapshot dump.
    """
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != 1:
        raise ValueError(
            f"cost baseline {path}: unsupported version "
            f"{data.get('version')!r} (expected 1)."
        )
    for name, prog in data.get("programs", {}).items():
        just = prog.get("justifications", {})
        missing = [
            m for m in prog.get("metrics", {})
            if not (just.get(m) or "").strip()
        ]
        if missing:
            raise ValueError(
                f"cost baseline {path}: program {name} has unjustified "
                f"metric(s) {missing} — refresh through "
                f"scripts/refresh_cost_baseline.py --justify."
            )
    return data


def _tolerance(metric: str, baseline: dict) -> float:
    tols = baseline.get("tolerances", TOLERANCES)
    return float(tols.get(metric, tols.get("*", 0.0)))


def within_band(old: float, new: float, tol: float) -> bool:
    return abs(float(new) - float(old)) <= tol * max(abs(float(old)), 1.0)


def compare_census(census: dict, baseline: dict) -> list[Finding]:
    """Findings of one census-vs-baseline comparison (empty = clean).

    Deviations in EITHER direction fail: an improvement outside the band is
    real news that belongs in the baseline (with a justification), not a
    silent drift that widens the next regression's headroom.
    """
    out = []
    programs = baseline.get("programs", {})
    for name, prog in programs.items():
        got = census.get(name)
        if got is None:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="program-missing",
                    severity="ERROR",
                    message=(
                        f"baselined program {name} is missing from the "
                        f"compiled matrix — the cost gate lost a config."
                    ),
                    symbol=name,
                    anchor="missing",
                )
            )
            continue
        for metric, old in prog.get("metrics", {}).items():
            if metric not in got:
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="metric-lost",
                        severity="ERROR",
                        message=(
                            f"{name}: baselined metric {metric} is absent "
                            f"from the fresh census — the toolchain "
                            f"stopped reporting it (gate has a blind spot)."
                        ),
                        symbol=name,
                        anchor=metric,
                    )
                )
                continue
            new = got[metric]
            tol = _tolerance(metric, baseline)
            if not within_band(old, new, tol):
                direction = "regressed" if new > old else "improved"
                if metric in ("collective_permutes", "fusions",
                              "kernel_launches"):
                    hint = (
                        "an extra collective/kernel usually means a "
                        "defused or re-serialized structure"
                    )
                else:
                    hint = "an extra copy or materialized intermediate"
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="cost-regression",
                        severity="ERROR",
                        message=(
                            f"{name}: {metric} {direction} "
                            f"{old} -> {new} (tolerance "
                            f"{tol:.0%} of baseline; {hint}).  If the "
                            f"change is intentional, refresh via "
                            f"scripts/refresh_cost_baseline.py --justify."
                        ),
                        symbol=name,
                        anchor=metric,
                    )
                )
        for metric in sorted(set(got) - set(prog.get("metrics", {}))):
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="metric-unbaselined",
                    severity="WARNING",
                    message=(
                        f"{name}: census metric {metric}={got[metric]} has "
                        f"no baseline entry — refresh to start gating it."
                    ),
                    symbol=name,
                    anchor=metric,
                )
            )
    for name in sorted(set(census) - set(programs)):
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="program-unbaselined",
                severity="WARNING",
                message=(
                    f"compiled program {name} has no baseline entry — "
                    f"refresh scripts/refresh_cost_baseline.py to gate it."
                ),
                symbol=name,
                anchor="unbaselined",
            )
        )
    return out


def run(ctx: Context) -> list[Finding]:
    out = payload_crosscheck_findings(ctx)
    if not os.path.exists(COST_BASELINE):
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="baseline-missing",
                severity="ERROR",
                message=(
                    f"cost baseline {COST_BASELINE} does not exist — run "
                    f"scripts/refresh_cost_baseline.py to create it."
                ),
                symbol="cost_baseline.json",
                anchor="missing",
            )
        )
        return out
    return out + compare_census(cost_census(ctx), load_baseline())
