"""Collective-budget analyzer (the `scripts/check_collectives.py` core).

The coalesced exchange's value is structural — one collective-permute pair
per (dimension, dtype width group) regardless of field count — and it is
provable below the compiler: trace each model's production exchange set on
the virtual 8-device mesh and count the ppermute equations per exchanged
dimension.  The budget table pins the allowed pairs; a regression that
silently re-serializes the exchange into per-field collectives (or emits
extras) fails the suite.  The per-field control (coalesce=False must
EXCEED the budget) keeps the census itself honest.

`scripts/check_collectives.py` is the thin CLI wrapper; the tier-1 test
`tests/test_collective_budget.py` keeps its exit-code contract.
"""

from __future__ import annotations

from .core import Context, Finding
from .ir import model_field_structs

ANALYZER = "collective-budget"

#: Allowed collective-permute PAIRS per exchanged dimension for each model's
#: production exchange set (all fields f32 => ONE dtype width group each).
#: The per-field counts these budgets forbid are len(fields) pairs per dim.
BUDGET_PAIRS = {
    "diffusion": 1,  # T
    "acoustic": 1,   # P, Vx, Vy, Vz — 4 fields, one pair
    "porous": 1,     # Pf, qDx, qDy, qDz, T — the 5-field step, one pair
}


def _count_ppermutes(jaxpr) -> int:
    n = 0
    for e in jaxpr.eqns:
        if e.primitive.name == "ppermute":
            n += 1
        for v in e.params.values():
            if hasattr(v, "jaxpr"):
                n += _count_ppermutes(v.jaxpr)
            elif hasattr(v, "eqns"):
                n += _count_ppermutes(v)
    return n


def _traced_dim_ppermutes(fields, d: int, coalesce) -> int:
    """ppermute equations in the traced dim-``d`` exchange of ``fields``
    (the shard_map/spec scaffolding is `ir._trace_mapped`'s — one tracing
    convention for every analyzer, so the censuses cannot drift)."""
    import implicitglobalgrid_tpu as igg
    from ..ops.halo import exchange_dims_multi
    from .ir import _trace_mapped

    def body(*fs):
        return exchange_dims_multi(fs, (d,), width=1, coalesce=coalesce)

    gg = igg.get_global_grid()
    return _count_ppermutes(_trace_mapped(body, fields, gg).jaxpr)


def budget_findings(n: int = 8, budget_pairs=None) -> list[Finding]:
    """Findings of one budget run (empty = clean).

    Grid: dims (2,2,2), periodic z — every dimension exchanges, both
    PROC_NULL and periodic transports in one config.  Explicit
    ``coalesce=True`` pins the budget to the coalesced path regardless of
    ``IGG_COALESCE`` (the knob toggles per-field attribution; the budget's
    claim is about what the DEFAULT production path emits).
    """
    import implicitglobalgrid_tpu as igg

    budget_pairs = BUDGET_PAIRS if budget_pairs is None else budget_pairs
    out = []
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        for model, pairs in budget_pairs.items():
            fields = model_field_structs(model, n)
            for d in range(3):
                got = _traced_dim_ppermutes(fields, d, coalesce=True)
                if got > 2 * pairs:
                    out.append(
                        Finding(
                            analyzer=ANALYZER,
                            code="budget-exceeded",
                            severity="ERROR",
                            message=(
                                f"{model}: dimension {d} emits {got} "
                                f"collective-permutes for {len(fields)} "
                                f"fields — budget is {2 * pairs} "
                                f"({pairs} pair(s); the coalesced exchange "
                                f"regressed to per-field collectives?)"
                            ),
                            symbol=f"{model}/dim{d}",
                            anchor=str(got),
                        )
                    )
            # The lint itself must be alive: the per-field control has to
            # exceed the budget for every multi-field model, or the counter
            # is not seeing the collectives at all.
            if len(fields) > 1:
                ctrl = _traced_dim_ppermutes(fields, 0, coalesce=False)
                if ctrl != 2 * len(fields):
                    out.append(
                        Finding(
                            analyzer=ANALYZER,
                            code="census-broken",
                            severity="ERROR",
                            message=(
                                f"{model}: per-field control counted "
                                f"{ctrl} collectives in dim 0, expected "
                                f"{2 * len(fields)} — the ppermute census "
                                f"is broken"
                            ),
                            symbol=f"{model}/control",
                            anchor=str(ctrl),
                        )
                    )
    finally:
        igg.finalize_global_grid()
    return out


def violation_strings(n: int = 8, budget_pairs=None) -> list[str]:
    """The `scripts/check_collectives.py` contract: human-readable
    violations, empty list = clean."""
    return [f.message for f in budget_findings(n, budget_pairs)]


def entry_budget_findings(entries, budget_pairs=None) -> list[Finding]:
    """The budget census over the SHARED traced-entry matrix.

    The suite path: `run(ctx)` counts ppermutes per exchanged dimension in
    the `Context.exchange_entries()` programs the consistency pass already
    traced (each ppermute's mesh-axis name identifies its dimension; the
    ``coalesce=False`` twin is the per-field liveness control), so the
    full suite traces the exchange matrix exactly once.  `budget_findings`
    keeps its self-managed grid for the standalone
    ``scripts/check_collectives.py`` entry.
    """
    from .. import AXIS_NAMES
    from .ir import model_field_structs

    budget_pairs = BUDGET_PAIRS if budget_pairs is None else budget_pairs
    by_name = {e.name: e for e in entries}
    out = []
    for model, pairs in budget_pairs.items():
        coal = by_name.get(f"exchange/{model}[coalesce=True]")
        ctrl = by_name.get(f"exchange/{model}[coalesce=False]")
        if coal is None or ctrl is None:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="census-broken",
                    severity="ERROR",
                    message=(
                        f"{model}: the traced entry matrix is missing the "
                        f"coalesce=True/False exchange entries — the "
                        f"budget census has nothing to count."
                    ),
                    symbol=f"{model}/entries",
                    anchor="missing",
                )
            )
            continue
        nfields = len(model_field_structs(model, 8))
        counts = {a: 0 for a in AXIS_NAMES}
        for op in coal.collectives():
            if op.kind == "ppermute" and op.axes:
                counts[op.axes[0]] = counts.get(op.axes[0], 0) + 1
        for d, axis in enumerate(AXIS_NAMES):
            got = counts.get(axis, 0)
            if got > 2 * pairs:
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="budget-exceeded",
                        severity="ERROR",
                        message=(
                            f"{model}: dimension {d} emits {got} "
                            f"collective-permutes for {nfields} fields — "
                            f"budget is {2 * pairs} ({pairs} pair(s); the "
                            f"coalesced exchange regressed to per-field "
                            f"collectives?)"
                        ),
                        symbol=f"{model}/dim{d}",
                        anchor=str(got),
                    )
                )
        if nfields > 1:
            c0 = sum(
                1
                for op in ctrl.collectives()
                if op.kind == "ppermute" and op.axes
                and op.axes[0] == AXIS_NAMES[0]
            )
            if c0 != 2 * nfields:
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="census-broken",
                        severity="ERROR",
                        message=(
                            f"{model}: per-field control counted {c0} "
                            f"collectives in dim 0, expected "
                            f"{2 * nfields} — the ppermute census is "
                            f"broken"
                        ),
                        symbol=f"{model}/control",
                        anchor=str(c0),
                    )
                )
    return out


# -- batched-exchange census (ISSUE 8: B for the price of 1) ------------------

#: Ensemble size the batched census traces alongside the unbatched program.
BATCHED_CENSUS_B = 4


def _exchange_axis_counts(fields, B: int | None) -> dict:
    """Per-mesh-axis ppermute counts of the coalesced 3-dim exchange of
    ``fields`` — traced unbatched (``B=None``) or under a vmapped leading
    ensemble axis of size ``B`` (the `models._batched` layout)."""
    import jax

    import implicitglobalgrid_tpu as igg
    from .. import AXIS_NAMES
    from ..ops.halo import exchange_dims_multi
    from .ir import _trace_mapped, collect_collectives, unwrap_inner

    gg = igg.get_global_grid()

    def single(*fs):
        return exchange_dims_multi(fs, (0, 1, 2), width=1, coalesce=True)

    if B is None:
        body, args = single, fields
    else:
        def body(*fs):
            return jax.vmap(single)(*fs)

        args = [
            jax.ShapeDtypeStruct((B,) + tuple(f.shape), f.dtype)
            for f in fields
        ]
    jaxpr = unwrap_inner(_trace_mapped(body, args, gg).jaxpr)
    counts = {a: 0 for a in AXIS_NAMES}
    for op in collect_collectives(jaxpr):
        if op.kind == "ppermute" and op.axes:
            counts[op.axes[0]] = counts.get(op.axes[0], 0) + 1
    return counts


def batched_exchange_census(n: int = 8, B: int = BATCHED_CENSUS_B,
                            models=None) -> dict:
    """``{model: {1: {axis: count}, B: {axis: count}}}`` over the coalesced
    production exchange — the evidence behind the "B for the price of 1"
    claim: the vmapped ensemble exchange must issue exactly the collective
    counts of the unbatched one (the ppermute batching rule carries the
    ensemble axis inside the SAME hop; payload bytes scale ×B instead).

    Same grid as `budget_findings` (dims (2,2,2), periodic z: PROC_NULL and
    periodic transports both live).  Trace-only — cheap enough for tier-1.
    """
    import implicitglobalgrid_tpu as igg

    models = tuple(BUDGET_PAIRS) if models is None else tuple(models)
    census: dict = {}
    igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2, periodz=1,
                         quiet=True)
    try:
        for model in models:
            fields = model_field_structs(model, n)
            census[model] = {
                1: _exchange_axis_counts(fields, None),
                B: _exchange_axis_counts(fields, B),
            }
    finally:
        igg.finalize_global_grid()
    return census


def batched_census_findings(census: dict) -> list[Finding]:
    """Findings over a batched-exchange census (pure — fixture-testable).

    The invariant: for every model, every batched variant's per-dimension
    ppermute counts EQUAL the unbatched baseline's.  A mismatch means the
    ensemble axis re-serialized into per-member collectives (vmap fell
    back to a loop, or a batching rule split the hop) — the exact
    regression that would silently multiply fabric traffic by B.
    """
    out = []
    for model, variants in sorted(census.items()):
        base = variants.get(1)
        if not base or all(v == 0 for v in base.values()):
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="census-broken",
                    severity="ERROR",
                    message=(
                        f"{model}: the batched-exchange census counted no "
                        f"collectives in the unbatched baseline — the "
                        f"ppermute census is not seeing the exchange."
                    ),
                    symbol=f"{model}/batched",
                    anchor="baseline",
                )
            )
            continue
        for b, counts in sorted(variants.items()):
            if b == 1:
                continue
            if counts != base:
                out.append(
                    Finding(
                        analyzer=ANALYZER,
                        code="batched-budget-mismatch",
                        severity="ERROR",
                        message=(
                            f"{model}: the B={b} ensemble exchange emits "
                            f"{counts} collective-permutes per dimension vs "
                            f"{base} at B=1 — batching must ride the SAME "
                            f"collectives (payload ×B), not issue more; the "
                            f"vmapped exchange re-serialized per member."
                        ),
                        symbol=f"{model}/batch{b}",
                        anchor=str(sorted(counts.items())),
                    )
                )
    return out


def batched_budget_findings(n: int = 8, B: int = BATCHED_CENSUS_B,
                            models=None) -> list[Finding]:
    """The batched-exchange census as tier-1 findings (empty = the
    B-for-the-price-of-1 invariant holds for every model)."""
    return batched_census_findings(batched_exchange_census(n, B, models))


def hlo_budget_findings(txt: str, *, model: str = "porous",
                        pairs: int | None = None,
                        active_dims: int = 3) -> list[Finding]:
    """The budget's optimized-HLO cross-check (pure over the HLO text).

    The jaxpr census proves what the PROGRAM asks for; this proves what the
    COMPILER kept: after XLA optimization the coalesced exchange must still
    be within ``2 * pairs`` collective-permutes per exchanged dimension
    (splitting a packed hop back apart would silently re-serialize the
    fabric traffic), and every permute's payload must parse cleanly through
    `utils.hlo_analysis.collective_payloads` with no raw-sum fallback —
    unaccounted payload bytes make every downstream budget an estimate.
    """
    from ..utils.hlo_analysis import collective_payloads

    pairs = BUDGET_PAIRS[model] if pairs is None else pairs
    n_perm = txt.count(" collective-permute(") + txt.count(
        " collective-permute-start("
    )
    recs = collective_payloads(txt)
    out = []
    if n_perm == 0 or len(recs) != n_perm:
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="hlo-census-broken",
                severity="ERROR",
                message=(
                    f"{model}: optimized HLO shows {n_perm} "
                    f"collective-permute(s) but collective_payloads "
                    f"accounts for {len(recs)} — the HLO payload census "
                    f"lost track of the exchange."
                ),
                symbol=f"{model}/hlo",
                anchor="census",
            )
        )
    budget = 2 * pairs * active_dims
    if n_perm > budget:
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="hlo-budget-exceeded",
                severity="ERROR",
                message=(
                    f"{model}: the OPTIMIZED program emits {n_perm} "
                    f"collective-permutes across {active_dims} exchanged "
                    f"dimension(s) — budget is {budget} ({pairs} pair(s) "
                    f"per dim); the compiler split the coalesced hops "
                    f"back apart."
                ),
                symbol=f"{model}/hlo",
                anchor=str(n_perm),
            )
        )
    for i, rec in enumerate(recs):
        if rec.get("payload_fallback"):
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="hlo-payload-fallback",
                    severity="WARNING",
                    message=(
                        f"{model}: collective-permute {i} payload fell "
                        f"back to a raw operand sum ({rec['shape']}) — "
                        f"its byte count is an upper bound, not exact."
                    ),
                    symbol=f"{model}/hlo",
                    anchor=f"hop{i}",
                )
            )
    return out


def run(ctx: Context) -> list[Finding]:
    return (
        entry_budget_findings(ctx.exchange_entries())
        + hlo_budget_findings(ctx.exchange_hlo())
        + batched_census_findings(ctx.batched_exchange_census())
    )
