"""Cost-model reconciliation report: achieved vs modeled traffic.

The static HLO cost model (``hlo-cost``, `analysis.costmodel`) pins what
each production program MOVES — ``bytes_accessed`` per compiled multi-step
cadence — and the bench records pin what the hardware ACHIEVED — the
``T_eff`` GB/s convention, which counts only the must-stream bytes of the
evolving state.  Until this module nothing joined the two: a bench round
could report a flattering T_eff while the compiled program quietly moved 3x
the mandatory bytes, and nobody would see the gap.

The join is one number per model (docs/performance.md):

    achieved_fraction = must_stream_bytes * iterations / bytes_accessed

the fraction of the program's *modeled* HBM traffic that the T_eff
convention counts as algorithmically mandatory.  1.0 means the compiled
cadence streams nothing beyond the convention; every extra copy, halo
recompute pass or materialized intermediate pulls it down.  It is also the
exact conversion factor between the two measurement worlds: a measured
``T_eff`` of X GB/s implies the hardware sustained ``X / achieved_fraction``
GB/s of modeled traffic (`join_measured` attaches both to a bench record).

Conventions mirror ``benchmarks/run.py`` (the numbers must reconcile
against ITS records): diffusion streams T in+out per step; acoustic streams
P, Vx, Vy, Vz per step; porous streams Pf, qDx, qDy, qDz in+out per PT
iteration (``iterations = nt * npt`` — the PT solver's inner loop is the
unit the porous bench times).  The per-program bytes come either from the
committed ``analysis/cost_baseline.json`` (``source="baseline"`` — fast,
no compile, exactly the audited numbers tier-1 gates on) or from a fresh
XLA:CPU compile of the same `ir.COMPILED_MATRIX` cadence programs
(``source="compiled"`` — what ``bench.py`` records via the
``benchmarks/run.py reconcile`` mode).

Caveat recorded in every report: the fraction is computed at the cadence
matrix's config (small blocks, 2-device mesh), where halo-adjacent
redundancy weighs MORE than at bench sizes — treat it as a conservative
floor when joining against large-grid teff measurements (``sizes`` in the
report name both configs).
"""

from __future__ import annotations

import json
import os

from . import ir

#: models covered (keys of `ir._CADENCES` / `ir._MODEL_MODULES`)
MODELS = ("diffusion", "acoustic", "porous")

#: per-model must-stream state slice, in the model's state-tuple order —
#: the benchmarks/run.py T_eff conventions (see module docstring).
_STREAM_SLICES = {
    "diffusion": slice(0, 1),   # T
    "acoustic": slice(0, 4),    # P, Vx, Vy, Vz
    "porous": slice(1, 5),      # Pf, qDx, qDy, qDz (per PT iteration)
}


def cadence_program(model: str) -> str:
    return f"cadence/{model}[pipelined=True]"


def model_iterations(model: str) -> int:
    """Streaming iterations of one compiled cadence program: ``nt`` steps,
    times ``npt`` inner PT iterations for porous (the unit its bench
    times)."""
    cfg = dict(ir._CADENCES)[model]
    return int(cfg["nt"]) * int(cfg.get("npt", 1))


def model_stream_bytes(model: str) -> dict:
    """Must-stream bytes per iteration of one cadence config.

    Sets up the model on the SAME grid as the cadence matrix
    (`ir._cadence_setup_kwargs` — 2-device x-split, f32) so the byte count
    is taken from the actual global field shapes (staggered +1 faces
    included), then tears the grid down.  Returns ``{stream_bytes,
    global_shape, dtype, fields}``.
    """
    import importlib

    import implicitglobalgrid_tpu as igg
    from ..utils.telemetry import teff_bytes

    cfg = dict(ir._CADENCES)[model]
    mod = importlib.import_module(
        "implicitglobalgrid_tpu.models." + ir._MODEL_MODULES[model]
    )
    state, _params = mod.setup(*cfg["nloc"], **ir._cadence_setup_kwargs(cfg))
    try:
        fields = state[_STREAM_SLICES[model]]
        sb = teff_bytes(fields)
        gg = igg.get_global_grid()
        info = {
            "stream_bytes": int(sb),
            "global_shape": list(gg.nxyz_g),
            "dtype": str(fields[0].dtype),
            "fields": len(fields),
        }
    finally:
        if igg.grid_is_initialized():
            igg.finalize_global_grid()
    return info


def _program_costs(source: str) -> dict:
    """``{model: {"bytes_accessed", "flops"}}`` for the cadence programs.

    ``source="baseline"`` reads the committed `costmodel.COST_BASELINE`
    (the audited numbers); ``source="compiled"`` compiles each cadence
    fresh on XLA:CPU (`ir.compile_program`).
    """
    out = {}
    if source == "baseline":
        from .costmodel import load_baseline

        programs = load_baseline().get("programs", {})
        for model in MODELS:
            metrics = programs.get(cadence_program(model), {}).get(
                "metrics", {}
            )
            out[model] = {
                "bytes_accessed": metrics.get("bytes_accessed"),
                "flops": metrics.get("flops"),
            }
    elif source == "compiled":
        for model in MODELS:
            prog = ir.compile_program(cadence_program(model))
            out[model] = {
                "bytes_accessed": prog.cost.get("bytes_accessed"),
                "flops": prog.cost.get("flops"),
            }
    else:
        raise ValueError(
            f"source must be 'baseline' or 'compiled', got {source!r}"
        )
    return out


def reconcile_report(*, source: str = "baseline") -> dict:
    """The achieved-vs-modeled report for all three models.

    Per model: the cadence program's modeled ``bytes_accessed``/``flops``,
    the must-stream bytes of its config, and ``achieved_fraction`` (module
    docstring).  A model whose cost numbers are unavailable (toolchain
    without cost analysis, baseline entry missing) reports
    ``achieved_fraction: None`` with the reason — absence must be visible,
    not a silent skip.
    """
    costs = _program_costs(source)
    models = {}
    for model in MODELS:
        iters = model_iterations(model)
        stream = model_stream_bytes(model)
        rec = {
            "program": cadence_program(model),
            "iterations": iters,
            **stream,
            **costs[model],
        }
        ba = costs[model].get("bytes_accessed")
        if ba:
            rec["modeled_bytes_per_iteration"] = float(ba) / iters
            rec["achieved_fraction"] = round(
                stream["stream_bytes"] * iters / float(ba), 6
            )
        else:
            rec["achieved_fraction"] = None
            rec["note"] = (
                f"no bytes_accessed available from source={source!r} for "
                f"{cadence_program(model)}"
            )
        models[model] = rec
    return {
        "source": source,
        "note": (
            "achieved_fraction = must-stream bytes / modeled bytes_accessed "
            "of the cadence-matrix config (small 2-device blocks: halo "
            "redundancy weighs more than at bench sizes — a conservative "
            "floor); measured_teff / achieved_fraction = implied modeled "
            "GB/s the hardware sustained"
        ),
        "models": models,
    }


def join_measured(report: dict, measured_teff_gbs: dict,
                  measured_overlap: dict | None = None) -> dict:
    """Attach measured ``T_eff`` values (``{model: GB/s}``) to a report.

    Adds ``measured_teff_gbs`` and ``modeled_actual_gbs`` (= measured /
    achieved_fraction — the modeled total-traffic rate that measurement
    implies) per model; models without a measurement or a fraction pass
    through unchanged.  ``measured_overlap`` (``{model: fraction}``, the
    device-timeline capture's comm/compute overlap from
    `utils.profiling` — ISSUE 15) rides along as
    ``measured_overlap_fraction``: the report then carries BOTH halves of
    ROADMAP item 1's acceptance — how much of the modeled traffic is
    mandatory, and how much of the fabric time the schedule actually hid.
    This is the `efficiency` extra ``bench.py`` attaches to every record.
    """
    out = {"source": report.get("source"), "note": report.get("note"),
           "models": {}}
    for model, rec in report.get("models", {}).items():
        rec = dict(rec)
        teff = measured_teff_gbs.get(model)
        frac = rec.get("achieved_fraction")
        if teff is not None:
            rec["measured_teff_gbs"] = float(teff)
            if frac:
                rec["modeled_actual_gbs"] = round(float(teff) / frac, 3)
        if measured_overlap and measured_overlap.get(model) is not None:
            rec["measured_overlap_fraction"] = float(measured_overlap[model])
        out["models"][model] = rec
    return out


def main(argv=None) -> int:
    """CLI: print the report as one JSON line (the ``benchmarks/run.py
    reconcile`` mode shells out here on the CPU mesh)."""
    import argparse

    from .core import ensure_cpu_devices

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--source", choices=("baseline", "compiled"), default="compiled",
        help="baseline: the committed cost_baseline.json numbers (no "
             "compile); compiled: fresh XLA:CPU compiles of the cadence "
             "matrix (default)",
    )
    args = ap.parse_args(argv)
    ensure_cpu_devices()
    print(json.dumps(reconcile_report(source=args.source)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
