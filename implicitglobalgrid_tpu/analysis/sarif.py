"""SARIF 2.1.0 export of an analysis `Report` (``igg_lint.py --sarif``).

SARIF is the interchange format CI systems (GitHub code scanning et al.)
consume to annotate PR diffs with findings.  The mapping is deliberately
small and deterministic (no timestamps, sorted rules, stable ordering), so
a golden-file test can pin the whole artifact:

* one ``run`` with ``tool.driver = igg-lint``; one reporting rule per
  distinct ``analyzer/code`` pair seen in the report;
* one ``result`` per finding — active findings as-is, baselined findings
  with a SARIF ``suppressions`` entry carrying the justification;
* severities: CRITICAL/ERROR → ``error``, WARNING → ``warning``, INFO →
  ``note`` (CRITICAL keeps its name in ``properties.iggSeverity``);
* the repo's refactor-stable fingerprint rides in ``partialFingerprints``
  under ``iggLintFingerprint/v1`` — CI dedups findings across pushes with
  it, the same property the suppression baseline keys on.
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

_LEVELS = {"CRITICAL": "error", "ERROR": "error", "WARNING": "warning",
           "INFO": "note"}
#: Severity rank for the per-rule default level (a rule spanning
#: severities — e.g. grad-soundness/cotangent-dropper at CRITICAL and
#: WARNING — must advertise its WORST case, independent of finding order).
_SEV_RANK = {"CRITICAL": 3, "ERROR": 2, "WARNING": 1, "INFO": 0}


def _rule_id(finding) -> str:
    return f"{finding.analyzer}/{finding.code}"


def _result(finding, justification: str | None = None) -> dict:
    res = {
        "ruleId": _rule_id(finding),
        "level": _LEVELS[finding.severity],
        "message": {"text": finding.message},
        "partialFingerprints": {
            "iggLintFingerprint/v1": finding.fingerprint
        },
        "properties": {"iggSeverity": finding.severity},
    }
    if finding.path:
        loc = {"artifactLocation": {"uri": finding.path}}
        if finding.line:
            loc["region"] = {"startLine": finding.line}
        res["locations"] = [{"physicalLocation": loc}]
    if finding.symbol:
        res["properties"]["symbol"] = finding.symbol
    if finding.fix_hint:
        res["properties"]["fixHint"] = finding.fix_hint
    if justification is not None:
        res["suppressions"] = [
            {"kind": "external", "justification": justification}
        ]
    return res


def report_to_sarif(report) -> dict:
    """One SARIF 2.1.0 log for a `core.Report` (JSON-ready dict)."""
    pairs = [(f, None) for f in report.findings] + [
        (f, j) for f, j in report.suppressed
    ]
    worst = {}
    for f, _ in pairs:
        rid = _rule_id(f)
        if rid not in worst or _SEV_RANK[f.severity] > _SEV_RANK[
                worst[rid].severity]:
            worst[rid] = f
    rules = {
        rid: {
            "id": rid,
            "shortDescription": {"text": f"{f.analyzer}: {f.code}"},
            "defaultConfiguration": {"level": _LEVELS[f.severity]},
        }
        for rid, f in worst.items()
    }
    results = [_result(f, j) for f, j in pairs]
    results.sort(
        key=lambda r: (r["ruleId"],
                       r["partialFingerprints"]["iggLintFingerprint/v1"])
    )
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "igg-lint",
                        # NOT informationUri: SARIF 2.1.0 requires that to
                        # be an absolute URI, and the doc lives in-repo
                        "properties": {"docs": "docs/static-analysis.md"},
                        "rules": [rules[k] for k in sorted(rules)],
                    }
                },
                "results": results,
                "invocations": [
                    {
                        "executionSuccessful": not report.errors,
                        "properties": {
                            "ran": report.ran,
                            "skipped": report.skipped,
                        },
                    }
                ],
            }
        ],
    }
