"""Cross-rank collective-consistency / SPMD-divergence detector.

``update_halo!`` in the reference (and every collective here) is only safe
because all ranks issue the same collectives in the same order; one rank
diverging — the PR-1 ``_gather_chunked`` hang, where non-root processes ran
a different in-flight collective schedule than the root — deadlocks the
fabric.  MPI tools like MUST detect this at runtime; GSPMD's partitioner
proves it per-program at compile time.  This analyzer makes it a trace-time
invariant over three evidence sources:

1. **AST rank-guard pass** — any collective call lexically nested under
   ``if``/``while``/ternary control flow whose predicate mentions a
   rank-identity (``rank``, ``coords``, ``process_index``, ``is_root``...)
   is exactly the hang class and is flagged CRITICAL.
2. **Traced-jaxpr census** — every entry point of the config matrix is
   traced; each collective's ``perm`` must be a valid partial permutation
   (duplicate sources/targets = data races, out-of-range = silent drops)
   and no collective may sit inside a ``cond`` branch (a rank-divergent
   predicate would run it on a subset of ranks).  A traced SPMD program
   is ONE program — rank-uniformity of its dispatch sequence holds by
   construction, which is exactly why the remaining divergence channels
   are Python-level (caught by the AST pass: each real process traces
   its OWN program, so a rank-guarded trace-time branch yields different
   programs per process) and host-level (caught below).
3. **Host-plan census** — host-side orchestration loops issue compiled
   collectives per dispatch where no jaxpr sees the ORDER.  Such entry
   points expose a pure ``collective_plan`` (today: `ops.gather`), which
   is evaluated per simulated rank (root and every non-root) and must be
   identical — the PR-1 flaky gather, now a static invariant next to its
   3-round runtime tripwire.  Additional censuses register via
   `register_census_provider` (how the seeded-divergence fixtures drive
   the real pipeline in `tests/test_static_analysis.py`).
"""

from __future__ import annotations

import ast

from .core import Context, Finding
from .ir import RankCensus

ANALYZER = "collective-consistency"

#: Call names that issue (or wrap) a cross-rank collective.  The package's
#: own transport helpers are included so a guard ABOVE the lax call is
#: still caught at the call site that matters.
COLLECTIVE_CALL_NAMES = frozenset(
    {
        "ppermute",
        "psum",
        "pmax",
        "pmin",
        "pmean",
        "all_gather",
        "all_to_all",
        "pbroadcast",
        "collective_permute",
        "_permute_slabs",
        "_coalesced_permute",
    }
)

#: Identifier fragments that name a rank identity.  A predicate mentioning
#: one of these differs across ranks by construction.
RANKISH_NAMES = frozenset(
    {
        "rank",
        "myrank",
        "my_rank",
        "coords",
        "is_root",
        "process_index",
        "proc_id",
        "procid",
        "me",
    }
)


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _rankish_terms(test: ast.AST) -> list[str]:
    """Rank-identity terms mentioned in a predicate expression."""
    hits = []
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id.lower() in RANKISH_NAMES:
            hits.append(n.id)
        elif isinstance(n, ast.Attribute) and n.attr.lower() in RANKISH_NAMES:
            hits.append(n.attr)
        elif isinstance(n, ast.Call) and _call_name(n) in (
            "process_index",
            "axis_index",
        ):
            hits.append(_call_name(n))
    return hits


def _always_exits(body: list) -> bool:
    """The statement list unconditionally leaves the enclosing block —
    ends in return/raise/continue/break (the early-exit guard idiom)."""
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


class _RankGuardVisitor(ast.NodeVisitor):
    """Find collective calls under rank-dependent Python control flow.

    Both guard shapes are covered: a collective lexically INSIDE a
    rank-conditioned branch, and the early-exit form — ``if rank != 0:
    return x`` followed by the collective — where every statement after
    the exiting branch runs only for the ranks that did not take it (the
    commonest shape of the PR-1 divergence).
    """

    def __init__(self, rel_path: str):
        self.rel = rel_path
        self.guards: list[tuple[ast.AST, list[str]]] = []
        self.func_stack: list[str] = []
        self.findings: list[Finding] = []

    def _with_guard(self, test, bodies):
        terms = _rankish_terms(test)
        if terms:
            self.guards.append((test, terms))
        for b in bodies:
            if isinstance(b, list):
                self._visit_block(b)
            else:
                self.visit(b)
        if terms:
            self.guards.pop()

    def _visit_block(self, stmts: list):
        """Visit a statement list; a rank-conditioned ``if`` whose taken
        branch always exits guards the REST of the block too."""
        pushed = 0
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                self.visit(stmt.test)
                self._with_guard(stmt.test, [stmt.body, stmt.orelse])
                terms = _rankish_terms(stmt.test)
                if terms and (
                    _always_exits(stmt.body) or _always_exits(stmt.orelse)
                ):
                    self.guards.append((stmt.test, terms))
                    pushed += 1
            else:
                self.visit(stmt)
        for _ in range(pushed):
            self.guards.pop()

    def visit_If(self, node: ast.If):
        # fallback for If nodes reached outside a _visit_block context
        self.visit(node.test)
        self._with_guard(node.test, [node.body, node.orelse])

    def visit_While(self, node: ast.While):
        self.visit(node.test)
        self._with_guard(node.test, [node.body, node.orelse])

    def visit_For(self, node: ast.For):
        self.visit(node.target)
        self.visit(node.iter)
        self._visit_block(node.body)
        self._visit_block(node.orelse)

    def visit_With(self, node: ast.With):
        for item in node.items:
            self.visit(item)
        self._visit_block(node.body)

    def visit_IfExp(self, node: ast.IfExp):
        self.visit(node.test)
        self._with_guard(node.test, [node.body, node.orelse])

    def _visit_func(self, node):
        self.func_stack.append(node.name)
        for dec in node.decorator_list:
            self.visit(dec)
        self._visit_block(node.body)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        if name in COLLECTIVE_CALL_NAMES and self.guards:
            terms = sorted({t for _, ts in self.guards for t in ts})
            qual = ".".join(self.func_stack) or "<module>"
            self.findings.append(
                Finding(
                    analyzer=ANALYZER,
                    code="rank-guarded-collective",
                    severity="CRITICAL",
                    message=(
                        f"collective `{name}` is issued under Python "
                        f"control flow conditioned on rank identity "
                        f"({', '.join(terms)}) — ranks taking different "
                        f"branches issue different collective sequences "
                        f"and deadlock (the PR-1 _gather_chunked class)."
                    ),
                    path=self.rel,
                    line=node.lineno,
                    symbol=qual,
                    anchor=name,
                    fix_hint=(
                        "issue the collective unconditionally on every "
                        "rank and mask its RESULT per rank (jnp.where / "
                        "contribute zeros), or lift the branch above the "
                        "collective so all ranks agree on it."
                    ),
                )
            )
        self.generic_visit(node)


def ast_findings(ctx: Context) -> list[Finding]:
    out = []
    for rel, (_src, tree) in ctx.module_asts().items():
        v = _RankGuardVisitor(rel)
        v.visit(tree)
        out.extend(v.findings)
    return out


# -- traced-jaxpr census ------------------------------------------------------


def check_rank_consistency(census: RankCensus) -> list[Finding]:
    """The core invariant: every rank's ordered collective sequence is
    identical.  Shared by the host-plan censuses and the seeded test
    fixtures."""
    items = sorted(census.sequences.items(), key=lambda kv: str(kv[0]))
    if not items:
        return []
    ref_rank, ref = items[0]
    out = []
    for rank, seq in items[1:]:
        if seq == ref:
            continue
        # first divergence position, for a actionable message
        i = next(
            (
                j
                for j in range(min(len(ref), len(seq)))
                if ref[j] != seq[j]
            ),
            min(len(ref), len(seq)),
        )
        at = (
            f"op {i}: rank {ref_rank} issues {ref[i]!r}, rank {rank} "
            f"issues {seq[i]!r}"
            if i < min(len(ref), len(seq))
            else f"rank {ref_rank} issues {len(ref)} collective(s), rank "
            f"{rank} issues {len(seq)}"
        )
        out.append(
            Finding(
                analyzer=ANALYZER,
                code="rank-divergent-sequence",
                severity="CRITICAL",
                message=(
                    f"entry `{census.name}`: collective sequences diverge "
                    f"across ranks — {at}.  A rank waiting in a collective "
                    f"its peers never issue hangs the fabric."
                ),
                symbol=census.name,
                anchor=str(rank),
                fix_hint=(
                    "make every rank issue the identical dispatch "
                    "sequence; rank-dependent work must happen host-side "
                    "on the fetched results, never in the collective "
                    "schedule."
                ),
            )
        )
        break  # one finding per entry; the first divergent rank names it
    return out


def _perm_findings(entry) -> list[Finding]:
    out = []
    for i, op in enumerate(entry.collectives()):
        if "cond" in op.path:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="collective-under-cond",
                    severity="CRITICAL",
                    message=(
                        f"entry `{entry.name}`: `{op.kind}` (op {i}) is "
                        f"traced inside a `cond` branch "
                        f"(path {'/'.join(op.path)}) — if the predicate "
                        f"ever differs across ranks, only some ranks run "
                        f"the collective."
                    ),
                    symbol=entry.name,
                    anchor=f"op{i}-cond",
                    fix_hint=(
                        "hoist the collective out of the cond, or prove "
                        "the predicate is replicated and select on the "
                        "result instead."
                    ),
                )
            )
        if op.kind != "ppermute" or op.perm is None:
            continue
        axis_size = entry.mesh_shape.get(op.axes[0]) if op.axes else None
        srcs = [s for s, _ in op.perm]
        dsts = [d for _, d in op.perm]
        bad = []
        if len(set(srcs)) != len(srcs):
            bad.append("duplicate sources (a rank sends twice in one hop)")
        if len(set(dsts)) != len(dsts):
            bad.append(
                "duplicate targets (two ranks write one rank's buffer)"
            )
        if axis_size is not None and any(
            not (0 <= x < axis_size) for x in srcs + dsts
        ):
            bad.append(f"index outside the axis size {axis_size}")
        if bad:
            out.append(
                Finding(
                    analyzer=ANALYZER,
                    code="malformed-permute",
                    severity="CRITICAL",
                    message=(
                        f"entry `{entry.name}`: ppermute op {i} has an "
                        f"invalid perm {op.perm}: {'; '.join(bad)}."
                    ),
                    symbol=entry.name,
                    anchor=f"op{i}-perm",
                )
            )
    return out


def traced_findings(ctx: Context) -> list[Finding]:
    # Perm validity + no-collective-under-cond per traced entry.  No
    # per-rank equality check here: one traced jaxpr IS one program, so
    # its dispatch sequence is rank-uniform by construction — the
    # divergence channels that can actually differ per rank are Python
    # control flow (ast_findings) and host-side plans (host_plan_findings).
    out = []
    for entry in list(ctx.exchange_entries()) + list(ctx.cadence_entries()):
        out.extend(_perm_findings(entry))
    return out


# -- host-plan census ---------------------------------------------------------

#: Census providers: callables ``ctx -> iterable[RankCensus]``.  Extensible
#: so host-side orchestration added later (and test fixtures) plug into the
#: same detector.
CENSUS_PROVIDERS: list = []


def register_census_provider(fn):
    """Register a ``ctx -> iterable[RankCensus]`` provider.  Returns ``fn``
    (decorator-friendly); remove with ``CENSUS_PROVIDERS.remove(fn)``."""
    CENSUS_PROVIDERS.append(fn)
    return fn


#: (dims, batch, root) grids the gather plan is simulated over — small,
#: ragged-tail-covering, and with a non-default root.
_GATHER_PLAN_CONFIGS = (
    ((2, 2, 2), 3, 0),
    ((2, 2, 2), 8, 7),
    ((4, 2, 1), 3, 5),
    ((1,), 1, 0),
)


def gather_plan_censuses(ctx: Context):
    """The `_gather_chunked` collective schedule per simulated rank.

    `ops.gather.collective_plan` is the single source of the chunked
    gather's dispatch order; its ``is_root`` parameter exists precisely so
    this census can prove the schedule ignores it (the PR-1 hang was
    non-roots running a DIFFERENT in-flight schedule than the root).
    """
    from ..ops.gather import collective_plan

    for dims, batch, root in _GATHER_PLAN_CONFIGS:
        nprocs = 1
        for d in dims:
            nprocs *= d
        yield RankCensus(
            name=f"host/gather_chunked[dims={dims},batch={batch},"
            f"root={root}]",
            sequences={
                rank: tuple(
                    ("block_fetch",) + tuple(rec[1:])
                    for rec in collective_plan(
                        dims, batch, is_root=(rank == root)
                    )
                )
                for rank in range(nprocs)
            },
        )


register_census_provider(gather_plan_censuses)


def tuning_plan_censuses(ctx: Context):
    """The autotuner resolve's host-transport schedule per simulated rank.

    `tuning.search.control_plan` is the single source of the resolve's
    dispatch order (cache-decision broadcast, then — on a miss — the
    measured candidates and the winner broadcast); its ``is_root``
    parameter exists precisely so this census can prove the schedule
    ignores rank identity AND rank-local cache state: a rank-keyed cache
    lookup (one rank's local hit skipping the measurement collectives its
    peers enter) is the `_gather_chunked` hang class wearing a tuner hat —
    the seeded positive fixture in ``tests/test_tuning.py`` shows this
    detector catching exactly that divergence.
    """
    from ..tuning.search import control_plan

    for hit, n in ((True, 0), (False, 3), (False, 1), (False, 0)):
        yield RankCensus(
            name=f"host/tune_resolve[hit={hit},measured={n}]",
            sequences={
                rank: control_plan(is_root=(rank == 0), hit=hit,
                                   n_measured=n)
                for rank in range(4)
            },
        )


register_census_provider(tuning_plan_censuses)


def supervisor_plan_censuses(ctx: Context):
    """The supervised ranks' in-band recovery schedule per simulated rank.

    `supervisor.policy.recovery_plan` is the single source of the
    collective schedule applying one recovery directive implies (control
    broadcast + the checkpoint barriers for the resize family, nothing
    for out-of-band restarts); its ``is_root`` parameter exists precisely
    so this census can prove the schedule ignores rank identity, and its
    ``stale`` (fence) flag is rank-uniform by construction — a recovery
    decision keyed on rank identity or rank-LOCAL fence state (one stale
    rank skipping the checkpoint barriers its peers enter) is the
    `_gather_chunked` hang class wearing a supervisor hat; the seeded
    positive fixture in ``tests/test_supervisor.py`` shows this detector
    catching exactly that divergence.
    """
    from ..supervisor.policy import ACTIONS, recovery_plan

    for action in ACTIONS:
        for stale in (False, True):
            yield RankCensus(
                name=f"host/supervisor_recovery[action={action},"
                f"stale={stale}]",
                sequences={
                    rank: recovery_plan(
                        is_root=(rank == 0), action=action, stale=stale
                    )
                    for rank in range(4)
                },
            )


register_census_provider(supervisor_plan_censuses)


def fleet_plan_censuses(ctx: Context):
    """A pool's ranks' in-band fleet-directive schedule per simulated rank.

    `fleet.policy.fleet_plan` is the single source of the collective
    schedule a fleet action implies INSIDE the affected pool (the adopt/
    replay control broadcast for respawn and spill, the config-directive
    broadcast for the canary verdicts, the drain broadcast for retire —
    and NOTHING for quarantine, which is out-of-band by design); its
    ``is_root`` parameter exists precisely so this census can prove the
    schedule ignores rank identity, and its ``stale`` (fence) flag must
    gate all ranks or none — a zombie incarnation where one stale rank
    skips the broadcast its peers enter is the `_gather_chunked` hang
    class wearing a fleet hat; the seeded positive fixture in
    ``tests/test_static_analysis.py`` shows this detector catching
    exactly that divergence.
    """
    from ..fleet.policy import FLEET_ACTIONS, fleet_plan

    for action in FLEET_ACTIONS:
        for stale in (False, True):
            yield RankCensus(
                name=f"host/fleet_plan[action={action},stale={stale}]",
                sequences={
                    rank: fleet_plan(
                        is_root=(rank == 0), action=action, stale=stale
                    )
                    for rank in range(4)
                },
            )


register_census_provider(fleet_plan_censuses)


def integrity_plan_censuses(ctx: Context):
    """The integrity plane's per-step schedule per simulated rank.

    `integrity.plan.integrity_plan` is the single source of what one
    guarded step's integrity observation implies on the wire: the
    transport checksum must add NO collective (the checksum word rides
    the existing ``ppermute`` payload; verification is a local recompute
    and a mismatch raises LOCALLY, escalated out-of-band through the
    ``sdc`` flight bundle), and the shadow audit's one replicated
    bit-compare ``psum`` must key ONLY on the rank-uniform cadence
    (``IGG_INTEGRITY_EVERY`` via the env tier), never on a rank-local
    verdict — a rank-local integrity verdict driving a collective is the
    `_gather_chunked` hang class wearing an integrity hat.  ``is_root``
    exists precisely so this census can prove rank identity does not
    shape the schedule.
    """
    from ..integrity.plan import integrity_plan

    for checksums in (False, True):
        for audit_every, step in ((0, 5), (4, 4), (4, 5)):
            for dims in (1, 3):
                yield RankCensus(
                    name=f"host/integrity_plan[checksums={checksums},"
                    f"every={audit_every},step={step},dims={dims}]",
                    sequences={
                        rank: integrity_plan(
                            is_root=(rank == 0), checksums=checksums,
                            audit_every=audit_every, step=step,
                            exchange_dims=dims,
                        )
                        for rank in range(4)
                    },
                )


register_census_provider(integrity_plan_censuses)


def host_plan_findings(ctx: Context) -> list[Finding]:
    out = []
    for provider in list(CENSUS_PROVIDERS):
        for census in provider(ctx):
            out.extend(check_rank_consistency(census))
    return out


def run(ctx: Context) -> list[Finding]:
    return ast_findings(ctx) + host_plan_findings(ctx) + traced_findings(ctx)
