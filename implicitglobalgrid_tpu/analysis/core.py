"""Pass-registry framework of ``igg.analysis`` (docs/static-analysis.md).

The correctness story of the reference is implicit — ``update_halo!`` is only
safe because every rank issues the same MPI calls in the same order — and the
repo's one real distributed hang (the ~50%-flaky ``_gather_chunked``, PR 1)
was exactly a cross-rank collective-ordering divergence found by hand.  This
framework turns that bug class (and three more) into machine-checked
invariants that run at trace time, in the spirit of compiler-level SPMD
verification (GSPMD partitioner invariants; MPI deadlock detectors like
MUST): analyzers run over three IRs the codebase already produces — the
package's Python AST, traced jaxprs of the public entry points under a
config matrix, and optimized HLO via `utils.hlo_analysis` — and report
`Finding` records through one runner with a baseline/suppression file and
JSON + human reporters.

Layering: this module is IR-free and jax-free (import is cheap — the package
``__init__`` re-exports it); IR construction lives in `analysis.ir` and is
built lazily by `Context`; each analyzer lives in its own module and is
imported only when it runs.
"""

from __future__ import annotations

import fnmatch
import hashlib
import importlib
import json
import os
from dataclasses import dataclass, field

#: Finding severities, most severe first.  CRITICAL = a distributed-deadlock
#: class (cross-rank divergence); ERROR = must be fixed or explicitly
#: baselined with a justification; WARNING = reported, does not fail the
#: suite (unless ``strict``); INFO = notes/metrics carriers.
SEVERITIES = ("CRITICAL", "ERROR", "WARNING", "INFO")

#: Severities that make `Report.exit_code` nonzero (WARNING joins under
#: ``strict``).
FAILING = ("CRITICAL", "ERROR")


@dataclass(frozen=True)
class Finding:
    """One analyzer result.

    ``path``/``line`` locate the finding in the repo when it is source-
    anchored; ``symbol`` is the stable anchor (function qualname, traced
    entry name) that survives line drift; ``anchor`` disambiguates several
    findings of one rule in one symbol (a knob name, an alias pair).  The
    `fingerprint` — the baseline-file key — deliberately hashes only the
    stable parts (analyzer, code, path, symbol, anchor), never the message
    or line number, so suppressions survive refactors that move lines or
    reword diagnostics.
    """

    analyzer: str
    code: str
    severity: str
    message: str
    path: str = ""
    line: int = 0
    symbol: str = ""
    anchor: str = ""
    fix_hint: str = ""

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"Finding severity must be one of {SEVERITIES}, "
                f"got {self.severity!r}."
            )

    @property
    def fingerprint(self) -> str:
        key = "|".join(
            (self.analyzer, self.code, self.path, self.symbol, self.anchor)
        )
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    @property
    def location(self) -> str:
        if self.path and self.line:
            return f"{self.path}:{self.line}"
        return self.path or self.symbol or "<package>"

    def to_dict(self) -> dict:
        return {
            "analyzer": self.analyzer,
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "location": self.location,
            "symbol": self.symbol,
            "anchor": self.anchor,
            "fix_hint": self.fix_hint,
            "fingerprint": self.fingerprint,
        }


# -- Analyzer registry --------------------------------------------------------


@dataclass(frozen=True)
class AnalyzerSpec:
    """Registry row: where the pass lives and when it is relevant.

    ``paths`` are repo-relative glob patterns used by ``--changed-only`` —
    the analyzer runs iff some changed file matches one of them (changes to
    the analysis framework or its scripts always select every analyzer).
    ``cost``: ``"ast"`` passes parse source only; ``"trace"`` passes build
    jaxprs on the 8-device virtual mesh (seconds, not milliseconds);
    ``"compile"`` passes additionally build optimized HLO through XLA:CPU
    (a few more seconds per program, cached per `Context`).
    """

    name: str
    module: str
    func: str
    title: str
    paths: tuple = ("implicitglobalgrid_tpu/**",)
    cost: str = "ast"

    def load(self):
        mod = importlib.import_module(self.module)
        return getattr(mod, self.func)


#: The shipped analyzer suite.  Order = run + report order.
REGISTRY: dict[str, AnalyzerSpec] = {
    s.name: s
    for s in (
        AnalyzerSpec(
            name="collective-consistency",
            module="implicitglobalgrid_tpu.analysis.collectives",
            func="run",
            title="cross-rank collective-consistency / SPMD-divergence "
            "detector (the _gather_chunked hang class)",
            paths=(
                "implicitglobalgrid_tpu/ops/**",
                "implicitglobalgrid_tpu/models/**",
                "implicitglobalgrid_tpu/parallel/**",
            ),
            cost="trace",
        ),
        AnalyzerSpec(
            name="knob-binding",
            module="implicitglobalgrid_tpu.analysis.knobs",
            func="run_knob_binding",
            title="trace-time knob-binding lint (env reads reachable from "
            "jit/shard_map/Pallas-traced code)",
            paths=("implicitglobalgrid_tpu/**",),
            cost="ast",
        ),
        AnalyzerSpec(
            name="pallas-aliasing",
            module="implicitglobalgrid_tpu.analysis.aliasing",
            func="run",
            title="Pallas input_output_aliases / donation declarations vs "
            "actual in-place use",
            paths=(
                "implicitglobalgrid_tpu/ops/**",
                "implicitglobalgrid_tpu/models/**",
            ),
            cost="trace",
        ),
        AnalyzerSpec(
            name="overlap-independence",
            module="implicitglobalgrid_tpu.analysis.overlap",
            func="run",
            title="structural kernel/collective overlap guarantee of the "
            "pipelined schedules (ISSUE 2), across all models",
            paths=(
                "implicitglobalgrid_tpu/ops/**",
                "implicitglobalgrid_tpu/models/**",
            ),
            cost="trace",
        ),
        AnalyzerSpec(
            name="collective-budget",
            module="implicitglobalgrid_tpu.analysis.budget",
            func="run",
            title="coalesced-exchange collective budget per (dimension, "
            "width group) (scripts/check_collectives.py)",
            paths=(
                "implicitglobalgrid_tpu/ops/**",
                "implicitglobalgrid_tpu/models/**",
                "implicitglobalgrid_tpu/parallel/**",
                # hlo_analysis.py IS the byte census — a change there must
                # re-run the gate that consumes it
                "implicitglobalgrid_tpu/utils/**",
            ),
            cost="trace",
        ),
        AnalyzerSpec(
            name="knob-decl",
            module="implicitglobalgrid_tpu.analysis.knobs",
            func="run_knob_decl",
            title="every IGG_* knob declared in utils/config.py and "
            "documented in docs/usage.md (scripts/check_knobs.py)",
            paths=("implicitglobalgrid_tpu/**", "docs/usage.md"),
            cost="ast",
        ),
        AnalyzerSpec(
            name="hlo-cost",
            module="implicitglobalgrid_tpu.analysis.costmodel",
            func="run",
            title="static HLO cost model of the production config matrix "
            "vs the versioned cost baseline (bytes, flops, payloads, "
            "launches, peak buffers)",
            paths=(
                "implicitglobalgrid_tpu/ops/**",
                "implicitglobalgrid_tpu/models/**",
                "implicitglobalgrid_tpu/parallel/**",
                # the cost census is produced BY utils/hlo_analysis.py —
                # the gate must re-run when its own parser changes
                "implicitglobalgrid_tpu/utils/**",
            ),
            cost="compile",
        ),
        AnalyzerSpec(
            name="grad-soundness",
            module="implicitglobalgrid_tpu.analysis.gradflow",
            func="run",
            title="cotangent-dropping primitives on the tangent path + "
            "backward-collective census of every differentiable entry "
            "point (the PR-5 zero-gradient-sink class)",
            paths=(
                "implicitglobalgrid_tpu/ops/**",
                "implicitglobalgrid_tpu/models/**",
            ),
            cost="trace",
        ),
        AnalyzerSpec(
            name="bench-regression",
            module="implicitglobalgrid_tpu.analysis.perf",
            func="run",
            title="committed bench trajectory within per-metric tolerance "
            "bands (scripts/check_perf.py; waivers in "
            "analysis/perf_waivers.json)",
            paths=("BENCH_*.json", "bench.py", "benchmarks/**"),
            cost="ast",
        ),
        AnalyzerSpec(
            name="tune-cache-valid",
            module="implicitglobalgrid_tpu.analysis.tunecache",
            func="run",
            title="committed autotuner seed entries parse against the "
            "schema and hold currently-admissible configs "
            "(tuning/entries, scripts/igg_tune.py)",
            paths=(
                "implicitglobalgrid_tpu/tuning/**",
                # the envelopes ARE the admissibility ladder — a kernel
                # constraint change must re-validate the committed winners
                "implicitglobalgrid_tpu/ops/**",
            ),
            cost="ast",
        ),
    )
}

#: Changes to the analysis subsystem itself select the whole suite.
_SELF_PATHS = (
    "implicitglobalgrid_tpu/analysis/**",
    "scripts/igg_lint.py",
    "scripts/check_collectives.py",
    "scripts/check_knobs.py",
    "scripts/check_perf.py",
    "scripts/refresh_cost_baseline.py",
)


def available_analyzers() -> tuple[str, ...]:
    return tuple(REGISTRY)


def select_for_paths(changed: list[str]) -> list[str]:
    """Analyzer names relevant to the given repo-relative changed paths
    (the ``--changed-only`` fast mode).  Framework changes select all."""
    changed = [p.replace(os.sep, "/") for p in changed]
    if any(
        fnmatch.fnmatch(p, pat) for p in changed for pat in _SELF_PATHS
    ):
        return list(REGISTRY)
    return [
        name
        for name, spec in REGISTRY.items()
        if any(
            fnmatch.fnmatch(p, pat) for p in changed for pat in spec.paths
        )
    ]


# -- Context: lazily-built shared IRs -----------------------------------------


class Context:
    """Shared state of one analysis run.

    IRs are built once and shared: the package AST parse (`module_asts`) and
    the traced-jaxpr entry matrix (`exchange_entries`/`cadence_entries`,
    built by `analysis.ir` — requires a jax runtime and manages its own
    grids).  ``package_root``/``repo_root`` are overridable so tests can
    point AST passes at fixture packages.
    """

    def __init__(self, repo_root: str | None = None,
                 package_root: str | None = None):
        here = os.path.dirname(os.path.abspath(__file__))
        default_pkg = os.path.dirname(here)
        self.repo_root = repo_root or os.path.dirname(default_pkg)
        self.package_root = package_root or default_pkg
        self._asts = None
        self._exchange = None
        self._cadence = None
        self._grad = None
        self._compiled = {}
        self._batched_census = None

    # AST IR ------------------------------------------------------------

    def module_asts(self) -> dict:
        """``{repo-relative path: (source, ast.Module)}`` for every ``.py``
        under the package (parsed once per context)."""
        if self._asts is None:
            import ast

            out = {}
            for dirpath, dirnames, filenames in os.walk(self.package_root):
                dirnames[:] = [
                    d for d in dirnames if d not in ("__pycache__",)
                ]
                for name in sorted(filenames):
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, name)
                    rel = os.path.relpath(path, self.repo_root).replace(
                        os.sep, "/"
                    )
                    with open(path, encoding="utf-8") as f:
                        src = f.read()
                    out[rel] = (src, ast.parse(src, filename=rel))
            self._asts = out
        return self._asts

    # Traced IR ----------------------------------------------------------

    def exchange_entries(self):
        """Traced halo-exchange entry points (all models x coalesce on/off
        x padded/slab variants on one periodic+PROC_NULL grid)."""
        if self._exchange is None:
            from . import ir

            self._exchange = ir.trace_exchange_entries()
        return self._exchange

    def batched_exchange_census(self):
        """The batched-exchange ppermute census (3 models x B∈{1, 4}),
        traced once per context like the other IRs (`analysis.budget`)."""
        if self._batched_census is None:
            from . import budget

            self._batched_census = budget.batched_exchange_census()
        return self._batched_census

    def cadence_entries(self):
        """Traced model multi-step cadences (3 models x pipelined on/off)."""
        if self._cadence is None:
            from . import ir

            self._cadence = ir.trace_cadence_entries()
        return self._cadence

    def grad_entries(self):
        """Traced VJP programs of the differentiable entry points (all
        models' coalesced exchange + fused cadences, `ir.trace_grad_entries`)."""
        if self._grad is None:
            from . import ir

            self._grad = ir.trace_grad_entries()
        return self._grad

    # Compiled IR (optimized HLO + toolchain stats) -----------------------

    def compiled_program(self, name: str):
        """One compiled program of `ir.COMPILED_MATRIX`, cached per config —
        the budget analyzer's HLO cross-check and the cost model's census
        share ONE compile of each program instead of rebuilding it."""
        if name not in self._compiled:
            from . import ir

            self._compiled[name] = ir.compile_program(name)
        return self._compiled[name]

    def compiled_programs(self) -> dict:
        """The full compiled matrix (`{name: ir.CompiledProgram}`)."""
        from . import ir

        return {n: self.compiled_program(n) for n in ir.COMPILED_MATRIX}

    def exchange_hlo(self) -> str:
        """Optimized-HLO text of the porous coalesced exchange (one small
        XLA:CPU build, shared with the cost model's census)."""
        from . import ir

        return self.compiled_program(ir.EXCHANGE_HLO_PROGRAM).text


# -- Baseline (suppression file) ----------------------------------------------

#: Default baseline location: versioned next to the analyzers.
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


@dataclass
class Baseline:
    """Fingerprint -> justification suppressions.

    Every entry MUST carry a non-empty justification — the file is the audit
    trail for "we looked at this finding and decided it is intentional",
    never a mute button (docs/static-analysis.md, baseline workflow).
    """

    suppressions: dict[str, dict] = field(default_factory=dict)
    path: str = ""

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        sup = {}
        for entry in data.get("suppressions", []):
            fp = entry.get("fingerprint", "")
            just = (entry.get("justification") or "").strip()
            if not fp:
                raise ValueError(
                    f"baseline {path}: suppression without a fingerprint: "
                    f"{entry!r}"
                )
            if not just:
                raise ValueError(
                    f"baseline {path}: suppression {fp} has no "
                    f"justification — every baselined finding must say WHY "
                    f"it is acceptable (see docs/static-analysis.md)."
                )
            sup[fp] = entry
        return cls(suppressions=sup, path=path)

    def match(self, finding: Finding) -> dict | None:
        return self.suppressions.get(finding.fingerprint)


# -- Report + runner ----------------------------------------------------------


@dataclass
class Report:
    """One run's outcome: active findings (severity-ordered), suppressed
    findings (baseline hits), stale suppressions (baseline entries that
    matched nothing — the tree moved on), per-analyzer stats, and the
    analyzers that ran/skipped."""

    findings: list = field(default_factory=list)
    suppressed: list = field(default_factory=list)
    stale_suppressions: list = field(default_factory=list)
    ran: list = field(default_factory=list)
    skipped: list = field(default_factory=list)
    stats: dict = field(default_factory=dict)
    errors: dict = field(default_factory=dict)

    def counts(self) -> dict:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def exit_code(self, strict: bool = False) -> int:
        if self.errors:
            return 2
        failing = FAILING + (("WARNING",) if strict else ())
        return 1 if any(f.severity in failing for f in self.findings) else 0

    def to_json(self) -> str:
        return json.dumps(
            {
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": [
                    {**f.to_dict(), "justification": j}
                    for f, j in self.suppressed
                ],
                "stale_suppressions": self.stale_suppressions,
                "ran": self.ran,
                "skipped": self.skipped,
                "counts": self.counts(),
                "stats": self.stats,
                "errors": self.errors,
            },
            indent=2,
            sort_keys=True,
        )

    def human(self) -> str:
        lines = []
        order = {s: i for i, s in enumerate(SEVERITIES)}
        for f in sorted(
            self.findings, key=lambda f: (order[f.severity], f.location)
        ):
            lines.append(f"{f.severity:8s} [{f.analyzer}/{f.code}] "
                         f"{f.location}: {f.message}")
            if f.fix_hint:
                lines.append(f"         fix: {f.fix_hint}")
            lines.append(f"         fingerprint: {f.fingerprint}")
        if self.suppressed:
            lines.append(f"-- {len(self.suppressed)} baselined finding(s):")
            for f, j in self.suppressed:
                lines.append(
                    f"   {f.analyzer}/{f.code} @ {f.location} "
                    f"[{f.fingerprint}] — {j}"
                )
        for fp in self.stale_suppressions:
            lines.append(
                f"WARNING  baseline suppression {fp} matched no finding — "
                f"remove it (the tree moved on)."
            )
        for name, err in self.errors.items():
            lines.append(f"ERROR    analyzer {name} crashed: {err}")
        c = self.counts()
        summary = ", ".join(f"{c[s]} {s}" for s in SEVERITIES if c[s])
        lines.append(
            f"igg-lint: {len(self.ran)} analyzer(s) ran"
            + (f", {len(self.skipped)} skipped" if self.skipped else "")
            + (f" — {summary}" if summary else " — clean")
        )
        return "\n".join(lines)


def run(
    names=None,
    *,
    baseline: str | None = DEFAULT_BASELINE,
    changed_paths: list[str] | None = None,
    ctx: Context | None = None,
    keep_going: bool = False,
) -> Report:
    """Run analyzers and fold their findings through the baseline.

    ``names``: analyzer subset (None = all).  ``changed_paths``: restrict to
    analyzers whose declared paths intersect (the ``--changed-only`` mode) —
    applied on top of ``names``.  ``baseline``: suppression-file path (None
    = no suppression).  ``keep_going``: trap analyzer crashes into
    ``report.errors`` instead of raising (the CLI's behavior; the tier-1
    test raises so a broken analyzer fails loudly).
    """
    ctx = ctx or Context()
    wanted = list(names) if names else list(REGISTRY)
    unknown = [n for n in wanted if n not in REGISTRY]
    if unknown:
        raise ValueError(
            f"unknown analyzer(s) {unknown}; available: {list(REGISTRY)}"
        )
    if changed_paths is not None:
        relevant = set(select_for_paths(changed_paths))
        selected = [n for n in wanted if n in relevant]
    else:
        selected = wanted

    report = Report(skipped=[n for n in wanted if n not in selected])
    base = Baseline.load(baseline) if baseline else Baseline()
    used = set()
    for name in selected:
        spec = REGISTRY[name]
        try:
            found = list(spec.load()(ctx))
        except Exception as e:  # noqa: BLE001 — CLI surfaces, test raises
            if not keep_going:
                raise
            report.errors[name] = f"{type(e).__name__}: {e}"
            continue
        report.ran.append(name)
        for f in found:
            hit = base.match(f)
            if hit is not None:
                used.add(f.fingerprint)
                report.suppressed.append((f, hit["justification"]))
            else:
                report.findings.append(f)
    # Staleness is only decidable when EVERY registered analyzer ran and
    # none crashed — on a subset / --changed-only / keep_going-crash run,
    # an unmatched suppression usually belongs to an analyzer that never
    # produced its findings, and advising "remove it" would delete valid
    # entries.
    if not report.errors and set(report.ran) == set(REGISTRY):
        report.stale_suppressions = [
            fp for fp in base.suppressions if fp not in used
        ]
    return report


def ensure_cpu_devices(n: int = 8) -> None:
    """Stage an ``n``-device XLA:CPU mesh before first backend use.

    The one staging recipe shared by every CLI driver of the suite
    (``igg_lint.py``, ``refresh_cost_baseline.py``; the tier-1 tests
    inherit conftest's identical dance): `XLA_FLAGS` must be set before
    the backend initializes, and the `jax_num_cpu_devices` config option
    does not exist on older installs.  Call it before the first
    `jax.devices()` — it is a no-op guard, not a backend reset.
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    elif int(m.group(1)) != n:
        # Silently keeping a pre-staged wrong count would surface later as
        # a confusing mesh-size error (or a census on the wrong mesh).
        raise RuntimeError(
            f"XLA_FLAGS already stages "
            f"--xla_force_host_platform_device_count={m.group(1)}, but the "
            f"analysis suite needs {n} devices — unset it (or set it to "
            f"{n}) before running."
        )
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # pre-0.4.38 installs: XLA_FLAGS alone carries it
        pass


def changed_files(repo_root: str | None = None,
                  ref: str | None = None) -> list[str]:
    """Repo-relative changed paths — the ``--changed-only`` census.

    ``ref=None`` (the default): `git status --porcelain` — staged, worktree
    and untracked changes vs HEAD; empty when git is unavailable (no fast
    mode, back-compat).  ``ref="main"`` (or any committish): the union of
    the merge-base diff against ``ref`` AND the status paths — what a PR
    branch changed even on a CLEAN CI checkout, where `git status` selects
    nothing.  In ref mode a git failure RAISES instead of returning empty:
    silently selecting zero analyzers on a bad ref would green-light a PR
    that was never linted.
    """
    import subprocess

    root = repo_root or Context().repo_root

    def _git(*args) -> str:
        return subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
            timeout=30, check=True,
        ).stdout

    paths = []
    try:
        status = _git("status", "--porcelain")
    except Exception as e:  # noqa: BLE001 — no git
        if ref is not None:
            raise RuntimeError(
                f"--changed-only={ref}: git status failed in {root}: {e}"
            ) from e
        return []
    for line in status.splitlines():
        if len(line) < 4:
            continue
        p = line[3:].strip()
        if " -> " in p:  # renames list "old -> new"
            p = p.split(" -> ", 1)[1]
        paths.append(p.strip('"'))
    if ref is None:
        return paths
    try:
        base = _git("merge-base", "HEAD", ref).strip()
        diff = _git("diff", "--name-only", base, "HEAD")
    except Exception as e:  # noqa: BLE001 — bad ref must not select zero
        raise RuntimeError(
            f"--changed-only={ref}: merge-base diff failed in {root} "
            f"(is {ref!r} a valid ref?): {e}"
        ) from e
    seen = set(paths)
    for p in diff.splitlines():
        p = p.strip()
        if p and p not in seen:
            seen.add(p)
            paths.append(p)
    return paths
