"""Pallas TPU kernel: temporally-blocked fused diffusion steps.

The per-chip analogue of the reference's custom pack kernels
(`/root/reference/src/update_halo.jl:599-649` exist because generic copies
were off peak; here the generic XLA stencil is *at* the HBM streaming
ceiling, so the remaining lever is doing MORE steps per HBM pass).  This
kernel advances the 3-D diffusion update ``T += pad(dt*lam/Cp * lap(T), 1)``
by ``k`` steps per HBM round trip — classic overlapped (trapezoid) tiling:

* The volume is processed in (x, y) tiles of ``(bx, by)`` output cells
  spanning all of z.  A ``k``-step tile needs ``k`` halo cells per side; the
  y-halo is padded to ``H = 8*ceil(k/8)`` (sublane alignment) and all tiles
  run in ONE flat `fori_loop` with *dynamic* DMA offsets, annotated with
  `pl.multiple_of(..., 8)` so Mosaic can prove the second-minor slice starts
  are sublane-aligned (without the hint it refuses to compile; an earlier
  toolchain miscompiled these DMAs outright, which is why a previous
  revision unrolled the y loop — the unroll made compile time scale as
  tiles x tile-elements and priced out volumes past 256^3).
* HBM traffic per simulated step falls from 3 full passes (read T, read Cp,
  write T) to ``(2*(bx+2k)*(by+2H)/(bx*by) + 1)/k`` — e.g. ``k=4`` with the
  tuned-default ``32x64`` tiles: ~1.03 passes/step, ~3x T_eff headroom on a
  bandwidth-bound chip (measured: 1.4x the XLA path at f32 256^3 on v5e,
  where halo-recompute makes the kernel VPU-bound before the traffic bound).
  Temporal blocking is how T_eff legitimately *exceeds* raw copy bandwidth.
* Input DMAs are double-buffered (two tile slots, alternating per tile) and
  the k-step ping-pong runs between the input slot and one scratch tile, so
  the working set is 5 tiles of VMEM; the out-DMA source is the input slot
  (``k`` even), whose reuse is fenced by waiting the previous out-DMA before
  prefetching into it.
* Each inner step updates only the tile interior and freezes the tile's
  border ring.  Tiles at the global faces are clamped to the array, so the
  frozen ring IS the physical boundary (correct frozen-boundary semantics);
  for interior tiles the stale ring never reaches the output cells (validity
  shrinks one ring per step; output offsets inside the tile are >= k).

``fused_diffusion_steps(T, Cp, k)`` equals ``k`` applications of the model's
single-step update to a few float32 ULPs (asserted in
`tests/test_pallas_stencil.py`; measured max |diff| ~ 5e-7 on random O(1)
data).  Not bit-exact: the kernel folds the constants as ``lap*cx`` and
multiplies by a precomputed ``1/Cp``, while the XLA path computes
``lap/dx^2`` and ``(dt*lam)/Cp`` — same math, different rounding.  The
frozen boundary ring IS bit-exact (it is never touched).

Multi-device note: between halo exchanges only ``k=1`` is valid with the
standard ``overlap=2`` grids (one fresh plane per side); ``k>1`` between
exchanges requires ``overlap >= 2k`` halos.
"""

from __future__ import annotations

import functools

from . import _fused_envelope as _envelope

#: Tile candidates for auto-selection, fastest first (tuned on v5e; smaller
#: tiles trade halo-recompute redundancy for fitting smaller volumes).  The
#: intermediate (32,32)/(16,64) rungs keep redundancy low when the VMEM
#: budget rejects (32,64) at large z extents (512^3: the round-3 envelope
#: fell all the way to (16,32), VERDICT r3 #6); (32,32) ranks above (16,64)
#: by measurement (acoustic 512^3 k=6: 1409 vs 1296 GB/s).
_TILE_CANDIDATES = ((32, 64), (32, 32), (16, 64), (16, 32), (8, 16))

#: Deep-z volumes (n2 >= 512) amortize a longer pipeline: (32,128) measured
#: +6% over (32,64) at 512^3 k=4 (609 vs 573 GB/s) but slightly BELOW it at
#: 256^3 — so it leads the ladder only when n2 qualifies and `_deep_z_crash`
#: clears the k.
_TILE_CANDIDATES_DEEP_Z = ((32, 128),) + _TILE_CANDIDATES


def _deep_z_crash(by, k, n2):
    """The probed (round 4) TPU compile-helper crash envelope: wide tiles
    (by >= 128) with k > 4 at 512-deep z.  ONE predicate behind both the
    auto-ladder gate and the explicit-tile rejection, so the two can never
    disagree about which combinations are legal."""
    return by >= 128 and k > 4 and n2 >= 512


def _candidates(shape, k):
    """Tile ladder for ``shape``, FULL-Y rungs (``by == n1``) first: they
    carry less halo-recompute redundancy (SX/bx vs (SX*SY)/(bx*by)) and
    measured 976 vs 444 GB/s against (32,64) at 256^3 k=4 on v5e (round 5);
    for the z-patch cadence they additionally enable the transposed
    thin-patch layout (its export windows must span full y rows for lane
    alignment).  The VMEM check degrades through them onto the y-windowed
    rungs for volumes where full-y windows don't fit (e.g. 512^3)."""
    n1, n2 = shape[1], shape[2]
    cands = []
    full_y = n1 % 8 == 0 and not _deep_z_crash(n1, k, n2)
    if full_y:
        cands += [(32, n1), (16, n1)]
    if n2 >= 512 and not _deep_z_crash(128, k, n2):
        cands += [(32, 128)]
    cands += list(_TILE_CANDIDATES)
    if full_y:
        # (8, n1) only as a last resort: bx=8's recompute redundancy
        # (SX/bx = 2 at k=4) loses to any y-windowed rung that fits, but it
        # is the tile that keeps the transposed z-patch layout reachable on
        # small blocks where nothing larger does.
        cands += [(8, n1)]
    seen, out = set(), []
    for c in cands:
        if c not in seen:
            seen.add(c)
            out.append(c)
    return tuple(out)

#: VMEM the kernel may plan against, as a `_tile_bytes` ESTIMATE bound.
#: Mosaic's real scoped stack for this kernel runs ~1.85x the buffer-byte
#: estimate (probed round 4: (32,64) k=4 at n2=1024 — estimate 65.5 MiB,
#: Mosaic wanted 121.4 and OOM'd against the 110 MiB cap; the deep-z
#: (32,128) k=4 at n2=512 — estimate 59 MiB — compiles, i.e. ~109 real),
#: so the budget is 110/1.85 ~ 59.5 MiB: every estimate it admits fits the
#: per-core cap after the overshoot.  Not a device query (jax's public API
#: does not expose per-generation VMEM size): a different generation
#: declares its capacity via ``IGG_VMEM_MB`` (`_fused_envelope.vmem_budget`
#: scales every kernel's budget proportionally; auto-selection then
#: grows/degrades through the candidate rungs, and `fused_support_error`
#: keeps oversized explicit tiles out).
_VMEM_BUDGET_BYTES = int(59.5 * 1024 * 1024)


def _tile_bytes(n1, n2, k, bx, by, itemsize, zslots: int = 0):
    """VMEM bytes for the 5-tile working set (2 T slots, 2 Cp slots, scratch)
    plus the z-window sets (``zslots``: 2 = z-patch inputs, 4 = + export
    staging; ``Cp`` is frozen — only ``T`` carries patches).

    ``by == n1`` is the full-y window mode (H = 0, single y-tile): its
    z windows use the TRANSPOSED thin-patch layout — pad8-plane sublane
    slabs over full ``pad128(n1)`` rows — instead of packed 128-lane
    fetches, ~16x less patch VMEM and traffic (round 5)."""
    full_y = by == n1
    H = 0 if full_y else _envelope.aligned_halo(k)
    SX, SY = bx + 2 * k, by + 2 * H
    total = 5 * SX * SY * n2
    if zslots and full_y:
        n1p = _envelope.pad128(n1)
        total += 2 * SX * _envelope.pad8(2 * k) * n1p  # transposed zpin slots
        if zslots >= 4:
            total += 2 * SX * _envelope.pad8(4 * k) * n1p  # transposed export staging
    else:
        total += zslots * SX * SY * 128
    return total * itemsize


# (Outside full-y mode, by | n1 and by + 2H <= n1 with H >= 8 force >= 2
# y-tiles.)
_tile_error = _envelope.make_tile_error(
    _tile_bytes, _VMEM_BUDGET_BYTES,
    "5 haloed tiles spanning z, v5e-tuned — see _VMEM_BUDGET_BYTES",
    full_y_ok=True,
)
_tile_error_zpatch = _envelope.make_tile_error(
    lambda n1, n2, k, bx, by, itemsize: _tile_bytes(n1, n2, k, bx, by, itemsize, 2),
    _VMEM_BUDGET_BYTES,
    "5 haloed tiles spanning z + 2 z-patch windows",
    full_y_ok=True,
)
_tile_error_zexport = _envelope.make_tile_error(
    lambda n1, n2, k, bx, by, itemsize: _tile_bytes(n1, n2, k, bx, by, itemsize, 4),
    _VMEM_BUDGET_BYTES,
    "5 haloed tiles spanning z + z-patch windows + export staging",
    full_y_ok=True,
)


def default_tile(shape, k: int, itemsize: int = 4, zpatch: bool = False,
                 zexport: bool | None = None):
    """First tuned tile candidate valid for ``shape``, or None if none fits.

    ``zexport`` defaults to ``zpatch`` — the production z-slab cadence
    always exports; pass ``zexport=False`` for a patch-only call."""
    return _envelope.default_tile(
        shape, k, itemsize,
        tile_error=_envelope.pick_tile_error(
            _tile_error, _tile_error_zpatch, _tile_error_zexport,
            zpatch, zexport,
        ),
        candidates=_candidates(shape, k),
    )


def zpatch_transposed(shape, k: int, itemsize: int = 4,
                      bx: int | None = None, by: int | None = None,
                      zexport: bool | None = None) -> bool:
    """Whether the z-patch cadence for this config uses the TRANSPOSED
    thin-patch layout (full-y tiles) — the model cadence must build and
    communicate patches in the matching layout (`ops.halo` ``*_t``
    helpers vs the packed 128-lane ones).

    Default-tile resolution mirrors the kernel's ``bx is None or by is
    None`` handling (ADVICE r5 low #4): a partially-specified tile resolves
    through the same ladder the kernel would use rather than trusting the
    lone ``by`` — otherwise a ``by=None``-only call could report one patch
    layout while `fused_diffusion_steps` (which rejects half tiles and, in
    the model's auto path, runs the ladder default) uses the other.
    """
    if bx is None or by is None:
        t = default_tile(shape, k, itemsize, zpatch=True, zexport=zexport)
        if t is None:
            return False
        bx, by = t
    return by == shape[1]


def fused_support_error(shape, k: int, itemsize: int = 4,
                        bx: int | None = None, by: int | None = None,
                        zpatch: bool = False,
                        zexport: bool | None = None) -> str | None:
    """Why the fused kernel cannot run this config, or None if it can.

    The single source of truth for the kernel's shape/tile envelope — used
    eagerly by `fused_diffusion_steps` (raise) and by
    `models.diffusion3d.make_multi_step` (warn once + fall back to the XLA
    cadence, the reference's runtime-path-selection precedent,
    `/root/reference/src/update_halo.jl:755-784`).  Kernel-independent
    checks (k parity, minor-dim ceiling + lane alignment, tile-selection
    flow) live in `ops/_fused_envelope.py`, shared with the staggered
    leapfrog kernel; only `_tile_error`'s VMEM accounting is specific.
    ``zpatch`` accounts for the in-kernel z-exchange variant's T patch
    windows; ``zexport`` (default = ``zpatch``, the production cadence) for
    the export staging slots on top.
    """
    if by is not None and _deep_z_crash(by, k, shape[2]):
        # Reject here so explicit tiles get the warn-once XLA fallback
        # instead of a hard crash (the auto ladder gates the deep-z rung
        # through the same predicate).
        return (
            f"tile (..,{by}) with k={k} at z>={shape[2]} crashes the TPU "
            "compiler (probed); use k <= 4 or by <= 64"
        )
    return _envelope.support_error(
        shape, k, itemsize, bx, by,
        tile_error=_envelope.pick_tile_error(
            _tile_error, _tile_error_zpatch, _tile_error_zexport,
            zpatch, zexport,
        ),
        candidates=_candidates(shape, k),
    )


def fused_diffusion_steps(T, Cp, k: int, cx: float, cy: float, cz: float,
                          *, bx: int | None = None, by: int | None = None,
                          z_patch=None, z_export: bool = False,
                          z_overlap: int | None = None,
                          tile_sel: str = "all", carry_in=None):
    """Advance ``k`` (even) diffusion steps in one HBM pass.

    ``cx = dt*lam/dx^2`` (likewise ``cy``, ``cz``); ``(bx, by)`` = output
    tile: ``bx`` divides ``T.shape[0]``; ``by`` divides ``T.shape[1]`` and is
    a multiple of 8; the haloed tile must fit inside the array.  Defaults to
    the fastest valid `_TILE_CANDIDATES` entry for the volume.

    ``z_patch``: z-exchange patch for ``T`` (width ``k``) applied per tile
    in VMEM before stepping (``Cp`` is frozen; its halos never change, so it
    needs no patch).  The LAYOUT follows the resolved tile — see
    `zpatch_transposed`: full-y tiles (``by == n1``, the ladder's preferred
    rungs) take the transposed thin-plane layout ``(n0, pad8(2k),
    pad128(n1))`` (`ops.halo.identity_z_patch_t` / `z_patch_from_export_t`);
    y-windowed tiles take the packed 128-lane layout ``(n0, n1, 128)``
    (`ops.halo.z_slab_patch`).

    ``z_export`` (requires ``z_patch`` + the grid z-overlap ``z_overlap``):
    additionally return the packed z-slab export for the NEXT group's patch
    — lanes ``[0,k)`` = post-step planes ``[n2-o, n2-o+k)`` (send-hi),
    ``[k,2k)`` = planes ``[o-k, o)`` (send-lo), ``[2k,3k)``/``[3k,4k)`` =
    the current boundary planes ``[0,k)``/``[n2-k,n2)`` (PROC_NULL
    keep-old values), junk beyond.  Extracting these in VMEM is free;
    doing it outside the kernel costs whole-array relayouts per group
    (minor-dim lane-unaligned slices — the z-anisotropy gap,
    docs/performance.md).  `ops.halo.z_patch_from_export` turns the export
    into the next patch.

    ``tile_sel`` (pipelined group schedule, `ops.overlap.tile_subset_map`):
    restrict the launch to a tile subset — ``"ring*"`` = the boundary tiles
    (owning the x/y slab-exchange send planes), ``"mid*"`` = the interior
    bulk.  A ``"mid*"`` launch requires ``carry_in``: the matching
    ``"ring*"`` launch's output array(s), aliased into this launch's
    outputs so the combined result needs no extra copy (the interior pass
    writes only its tiles' owned blocks; the boundary blocks ride the
    alias).  The split must be admissible (`ops.overlap.tile_split_error`);
    subset launches skip no per-tile work, so ring+mid is tile-for-tile
    identical to one ``"all"`` launch.
    """
    n0, n1, n2 = T.shape
    if T.dtype != Cp.dtype:
        raise ValueError("T and Cp must share a dtype")
    zp = z_patch is not None
    if zp and z_patch.dtype != T.dtype:
        raise ValueError("z_patch must share T's dtype")
    if z_export:
        if not zp:
            raise ValueError("z_export requires z_patch (the z-slab cadence)")
        if z_overlap is None or not (2 * k <= z_overlap <= n2 // 2):
            raise ValueError(
                f"z_export needs the grid z-overlap with 2k <= o <= n2/2: "
                f"got o={z_overlap}, k={k}, n2={n2}"
            )
        if 4 * k > 128:
            raise ValueError(f"z_export packs 4k lanes; k={k} > 32 unsupported")
    err = fused_support_error(
        (n0, n1, n2), k, T.dtype.itemsize, bx, by, zpatch=zp, zexport=z_export
    )
    if err is not None:
        raise ValueError(err)
    if bx is None:
        bx, by = default_tile(
            (n0, n1, n2), k, T.dtype.itemsize, zpatch=zp, zexport=z_export
        )
    if zp:
        # Patch layout follows the tile: full-y tiles take the transposed
        # thin-patch layout (see `zpatch_transposed` and ops/halo's ``*_t``
        # helpers), everything else the packed 128-lane layout.
        n1p = _envelope.pad128(n1)
        want = (
            (n0, _envelope.pad8(2 * k), n1p) if by == n1 else (n0, n1, 128)
        )
        if tuple(z_patch.shape) != want:
            raise ValueError(
                f"z_patch must have shape {want} for tile ({bx},{by}): got "
                f"{tuple(z_patch.shape)}"
            )
    carry_in = _envelope.check_tile_subset(
        tile_sel, carry_in, (n0, n1), (bx, by),
        nouts=2 if z_export else 1,
    )
    from ..utils.compat import pallas_interpret_active

    fn = _build(n0, n1, n2, str(T.dtype), int(k),
                float(cx), float(cy), float(cz), int(bx), int(by), zp,
                bool(z_export), int(z_overlap) if z_export else 0,
                str(tile_sel), carry_in is not None,
                pallas_interpret_active())
    args = (T, Cp, z_patch) if zp else (T, Cp)
    if carry_in is not None:
        args += tuple(carry_in)
    return fn(*args)


@functools.lru_cache(maxsize=64)
def _build(n0, n1, n2, dtype, k, cx, cy, cz, bx, by, zp: bool = False,
           zx: bool = False, o: int = 0, tile_sel: str = "all",
           carry: bool = False, interp: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ..utils.compat import pallas_compiler_params
    from .overlap import tile_subset_count, tile_subset_map

    # Full-y mode (by == n1): the window spans all of y with no y halo (the
    # window edge IS the block edge, where the frozen ring reproduces the
    # XLA cadence's frozen boundary), and the z patches/exports move in the
    # transposed thin-plane layout — ~16x less window traffic than the
    # packed 128-lane fetches (round 5, VERDICT r4 missing #3).
    fy = by == n1
    zt = zp and fy  # transposed z-window layout
    H = 0 if fy else _envelope.aligned_halo(k)
    SX, SY = bx + 2 * k, by + 2 * H
    ncx, ncy = n0 // bx, n1 // by
    PI, PE = _envelope.pad8(2 * k), _envelope.pad8(4 * k)
    n1p = _envelope.pad128(n1)
    dt_ = jnp.dtype(dtype)

    def sx_of(ix):  # haloed-window x start, clamped to the array
        return jnp.clip(ix * bx - k, 0, n0 - SX)

    def sy_of(iy):
        # Always a multiple of 8 (by, H, and n1-SY all are), but Mosaic
        # cannot prove that through the clip — assert it, or the dynamic
        # second-minor DMA slice is rejected as potentially unaligned.
        return pl.multiple_of(jnp.clip(iy * by - H, 0, n1 - SY), 8)

    def make_minv(cp):
        """1/cp, computed once per tile so the k inner steps are divide-free."""
        return (jnp.ones((), dt_) / cp).astype(dt_)

    def copy_ring(dst, s):
        """Copy the six boundary faces (the frozen ring) of ``s`` into ``dst``."""
        dst[0:1] = s[0:1]
        dst[SX - 1 : SX] = s[SX - 1 : SX]
        dst[1:-1, 0:1] = s[1:-1, 0:1]
        dst[1:-1, SY - 1 : SY] = s[1:-1, SY - 1 : SY]
        dst[1:-1, 1:-1, 0:1] = s[1:-1, 1:-1, 0:1]
        dst[1:-1, 1:-1, n2 - 1 : n2] = s[1:-1, 1:-1, n2 - 1 : n2]

    def step_into(dst, s, minv, ring: bool):
        """dst <- one diffusion step of tile value ``s``.

        ``minv`` is the precomputed Cp reciprocal (see `make_minv`), so each
        of the k steps is divide-free (VPU divides made the naive version
        compute-bound).  The frozen boundary ring is constant across all k
        steps, so it is copied at most once per buffer (``ring=True`` for
        scratch's first use; the in-slot buffer already holds it from the
        DMA) instead of the full-tile ``dst[:] = s`` copy a step used to do
        — the interior store below overwrites every non-ring cell anyway.
        """
        lap = (
            (s[2:, 1:-1, 1:-1] - 2 * s[1:-1, 1:-1, 1:-1] + s[:-2, 1:-1, 1:-1]) * cx
            + (s[1:-1, 2:, 1:-1] - 2 * s[1:-1, 1:-1, 1:-1] + s[1:-1, :-2, 1:-1]) * cy
            + (s[1:-1, 1:-1, 2:] - 2 * s[1:-1, 1:-1, 1:-1] + s[1:-1, 1:-1, :-2]) * cz
        )
        if ring:
            copy_ring(dst, s)
        dst[1:-1, 1:-1, 1:-1] = s[1:-1, 1:-1, 1:-1] + lap * minv[1:-1, 1:-1, 1:-1]

    ntiles = ncx * ncy
    # Tile-subset launch (pipelined group schedule): the loop runs over the
    # subset's index space and `t_of` maps it onto flat tile indices — the
    # per-tile work is identical to an "all" launch, only WHICH tiles run
    # changes.  `t_of` is pure arithmetic, so the drain below can evaluate
    # it on Python ints for the static last-two indices.
    nrun = tile_subset_count(tile_sel, ncx, ncy)
    t_of = tile_subset_map(tile_sel, ncx, ncy)

    def kernel(*refs):
        ZXout = None
        nin = 3 if zp else 2
        Tin, Cpin = refs[0], refs[1]
        ZPin = refs[2] if zp else None
        # A carry launch receives the ring pass's outputs as aliased inputs
        # between the real inputs and the outputs; the kernel never reads
        # them (the alias itself carries their bytes into the outputs).
        outs = refs[nin + ((2 if zx else 1) if carry else 0):]
        if zx:
            Tout, ZXout = outs
        else:
            (Tout,) = outs

        def body(tin, cpin, scratch, in_sems, cp_sems, out_sems,
                 zpin=None, zp_sems=None, zex=None, zex_sems=None):
            # One flat tile index t = ix*ncy + iy; slot parity alternates
            # with t, so consecutive tiles always double-buffer.
            def ixy(t):
                return t // ncy, t % ncy

            def in_dma(t, slot):
                ix, iy = ixy(t)
                return pltpu.make_async_copy(
                    Tin.at[pl.ds(sx_of(ix), SX), pl.ds(sy_of(iy), SY)],
                    tin.at[slot], in_sems.at[slot],
                )

            def cp_dma(t, slot):
                ix, iy = ixy(t)
                return pltpu.make_async_copy(
                    Cpin.at[pl.ds(sx_of(ix), SX), pl.ds(sy_of(iy), SY)],
                    cpin.at[slot], cp_sems.at[slot],
                )

            def out_dma(t, slot):
                ix, iy = ixy(t)
                ox = ix * bx - sx_of(ix)
                oy = pl.multiple_of(iy * by - sy_of(iy), 8)
                return pltpu.make_async_copy(
                    tin.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                    Tout.at[pl.ds(ix * bx, bx), pl.ds(iy * by, by)],
                    out_sems.at[slot],
                )

            def zp_dma(t, slot):
                ix, iy = ixy(t)
                if zt:
                    # transposed patch: full (PI, n1p) rows, x-windowed only
                    return pltpu.make_async_copy(
                        ZPin.at[pl.ds(sx_of(ix), SX)],
                        zpin.at[slot], zp_sems.at[slot],
                    )
                return pltpu.make_async_copy(
                    ZPin.at[pl.ds(sx_of(ix), SX), pl.ds(sy_of(iy), SY)],
                    zpin.at[slot], zp_sems.at[slot],
                )

            def zex_dma(t, slot):
                ix, iy = ixy(t)
                ox = ix * bx - sx_of(ix)
                if zt:
                    # transposed export: staging holds the whole window's
                    # rows; DMA only the owned bx rows (full PE, n1p)
                    return pltpu.make_async_copy(
                        zex.at[slot, pl.ds(ox, bx)],
                        ZXout.at[pl.ds(ix * bx, bx)],
                        zex_sems.at[slot],
                    )
                oy = pl.multiple_of(iy * by - sy_of(iy), 8)
                return pltpu.make_async_copy(
                    zex.at[slot, pl.ds(ox, bx), pl.ds(oy, by)],
                    ZXout.at[pl.ds(ix * bx, bx), pl.ds(iy * by, by)],
                    zex_sems.at[slot],
                )

            in_dma(t_of(0), 0).start()
            cp_dma(t_of(0), 0).start()
            if zp:
                zp_dma(t_of(0), 0).start()

            def tile(i, _):
                # i runs over the launch's subset; t is the flat tile index
                # (identical for "all" launches).  Slot parity follows i so
                # consecutive subset tiles always double-buffer.
                t = t_of(i)
                slot = jax.lax.rem(i, 2)
                nslot = 1 - slot

                @pl.when(i + 1 < nrun)
                def _():
                    @pl.when(i >= 1)
                    def _():
                        # nslot still holds the previous tile's output;
                        # fence the out-DMA (and the z-export DMA, whose
                        # staging slot is rewritten at the next tile's
                        # compute) before prefetching into it.
                        out_dma(t_of(i - 1), nslot).wait()
                        if zx:
                            zex_dma(t_of(i - 1), nslot).wait()

                    in_dma(t_of(i + 1), nslot).start()
                    cp_dma(t_of(i + 1), nslot).start()
                    if zp:
                        zp_dma(t_of(i + 1), nslot).start()

                in_dma(t, slot).wait()
                cp_dma(t, slot).wait()
                if zt:
                    zp_dma(t, slot).wait()
                    # Transposed patch: plane p of the field's y rows sits
                    # at [:, p, :] — a sublane->lane swap applies it
                    # (probed; the pad128 tail of n1p sliced off statically).
                    tin[slot, :, :, 0:k] = jnp.swapaxes(
                        zpin[slot, :, 0:k, :], 1, 2
                    )[:, 0:n1, :]
                    tin[slot, :, :, n2 - k : n2] = jnp.swapaxes(
                        zpin[slot, :, k : 2 * k, :], 1, 2
                    )[:, 0:n1, :]
                elif zp:
                    zp_dma(t, slot).wait()
                    # Apply the z-exchange patch in VMEM (see the leapfrog
                    # kernel): lanes [0,k) -> planes [0,k), [k,2k) -> the
                    # top k planes.
                    tin[slot, :, :, 0:k] = zpin[slot, :, :, 0:k]
                    tin[slot, :, :, n2 - k : n2] = zpin[slot, :, :, k : 2 * k]
                minv = make_minv(cpin[slot])
                # k-step ping-pong: tin[slot] -> scratch -> tin[slot] ...
                # k is even, so the final state lands back in tin[slot].
                for j in range(k):
                    if j % 2 == 0:
                        step_into(scratch, tin[slot], minv, ring=(j == 0))
                    else:
                        step_into(tin.at[slot], scratch[:], minv, ring=False)
                if zx and zt:
                    # Transposed export: whole-window transposes (static
                    # slices only — a traced-offset VMEM *load* is not
                    # lowerable, unlike DMAs, so the out-DMA does the
                    # owned-row selection).  Post-step send slabs sit >= k
                    # planes from the z edges (o >= 2k), so the owned-block
                    # values are exact.
                    zex[slot, :, 0:k, 0:n1] = jnp.swapaxes(
                        tin[slot, :, :, n2 - o : n2 - o + k], 1, 2
                    )
                    zex[slot, :, k : 2 * k, 0:n1] = jnp.swapaxes(
                        tin[slot, :, :, o - k : o], 1, 2
                    )
                    zex[slot, :, 2 * k : 3 * k, 0:n1] = jnp.swapaxes(
                        tin[slot, :, :, 0:k], 1, 2
                    )
                    zex[slot, :, 3 * k : 4 * k, 0:n1] = jnp.swapaxes(
                        tin[slot, :, :, n2 - k : n2], 1, 2
                    )
                    zex_dma(t, slot).start()
                elif zx:
                    # z-slab export for the NEXT group's patch, extracted
                    # here in VMEM where minor-dim plane surgery is free
                    # (outside, these lane-unaligned slices relayout the
                    # whole array — the z-anisotropy gap).  Post-step send
                    # slabs sit >= k planes from the z edges (o >= 2k), so
                    # the owned-block values are exact.
                    zex[slot, :, :, 0:k] = tin[slot, :, :, n2 - o : n2 - o + k]
                    zex[slot, :, :, k : 2 * k] = tin[slot, :, :, o - k : o]
                    zex[slot, :, :, 2 * k : 3 * k] = tin[slot, :, :, 0:k]
                    zex[slot, :, :, 3 * k : 4 * k] = tin[slot, :, :, n2 - k : n2]
                    zex_dma(t, slot).start()
                out_dma(t, slot).start()
                return 0

            jax.lax.fori_loop(0, nrun, tile, 0)
            # Drain the two in-flight out-DMAs (every launch runs >= 2
            # tiles by validation, and they use distinct slots).
            out_dma(t_of(nrun - 2), (nrun - 2) % 2).wait()
            out_dma(t_of(nrun - 1), (nrun - 1) % 2).wait()
            if zx:
                zex_dma(t_of(nrun - 2), (nrun - 2) % 2).wait()
                zex_dma(t_of(nrun - 1), (nrun - 1) % 2).wait()

        scopes = dict(
            tin=pltpu.VMEM((2, SX, SY, n2), dt_),
            cpin=pltpu.VMEM((2, SX, SY, n2), dt_),
            scratch=pltpu.VMEM((SX, SY, n2), dt_),
            in_sems=pltpu.SemaphoreType.DMA((2,)),
            cp_sems=pltpu.SemaphoreType.DMA((2,)),
            out_sems=pltpu.SemaphoreType.DMA((2,)),
        )
        if zp:
            scopes.update(
                zpin=pltpu.VMEM(
                    (2, SX, PI, n1p) if zt else (2, SX, SY, 128), dt_
                ),
                zp_sems=pltpu.SemaphoreType.DMA((2,)),
            )
        if zx:
            scopes.update(
                zex=pltpu.VMEM(
                    (2, SX, PE, n1p) if zt else (2, SX, SY, 128), dt_
                ),
                zex_sems=pltpu.SemaphoreType.DMA((2,)),
            )
        pl.run_scoped(body, **scopes)

    # 5 VMEM tiles (2 T slots, 2 Cp slots, 1 scratch) + Mosaic's own margin;
    # the default 16 MiB scoped-vmem budget rejects tiles past ~16x32, so
    # request what the kernel actually needs (v5e has 128 MiB VMEM).
    vmem_bytes = _tile_bytes(n1, n2, k, bx, by, dt_.itemsize, (4 if zx else 2) if zp else 0)
    out_shape = jax.ShapeDtypeStruct((n0, n1, n2), dt_)
    if zx:
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((n0, PE, n1p) if zt else (n0, n1, 128), dt_),
        )
    nouts = 2 if zx else 1
    nin = (3 if zp else 2) + (nouts if carry else 0)
    aliases = {3 if zp else 2: 0}
    if carry and zx:
        aliases[(3 if zp else 2) + 1] = 1
    call = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nin,
        out_specs=(
            [pl.BlockSpec(memory_space=pl.ANY)] * 2
            if zx else pl.BlockSpec(memory_space=pl.ANY)
        ),
        input_output_aliases=aliases if carry else {},
        interpret=interp,
        compiler_params=pallas_compiler_params(
            vmem_limit_bytes=_envelope.vmem_limit(2 * vmem_bytes)
        ),
    )
    return jax.jit(call)
